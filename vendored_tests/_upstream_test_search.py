"""Test the search module"""

import pickle
import re
import sys
import warnings
from collections.abc import Iterable, Sized
from functools import partial
from io import StringIO
from itertools import chain, product
from types import GeneratorType

import numpy as np
import pytest
from scipy.stats import bernoulli, expon, randint, uniform

from sklearn import config_context
from sklearn.base import BaseEstimator, ClassifierMixin, clone, is_classifier
try:
    from sklearn.callback.tests._utils import (
        MaxIterEstimator,
        NoCallbackEstimator,
        RecordingAutoPropagatedCallback,
        RecordingCallback,
        skip_callback_test_if_wasm,
    )
except ImportError:
    # installed sklearn has no callback module (stock releases): keep the
    # rest of the upstream suite runnable and skip only the callback
    # tests.  The stubs exist because _searchcv_callback_test_cases
    # instantiates them at parametrize time.
    class MaxIterEstimator(BaseEstimator):
        def __init__(self, max_iter=10):
            self.max_iter = max_iter

        def fit(self, X, y=None):
            return self

    class NoCallbackEstimator(MaxIterEstimator):
        pass

    class RecordingCallback:
        pass

    class RecordingAutoPropagatedCallback:
        pass

    skip_callback_test_if_wasm = pytest.mark.skip(
        reason="sklearn.callback is not available in this sklearn")
from sklearn.cluster import KMeans
from sklearn.compose import ColumnTransformer
from sklearn.datasets import (
    make_blobs,
    make_classification,
    make_multilabel_classification,
)
from sklearn.discriminant_analysis import LinearDiscriminantAnalysis
from sklearn.dummy import DummyClassifier
from sklearn.ensemble import HistGradientBoostingClassifier
from sklearn.exceptions import FitFailedWarning
from sklearn.experimental import enable_halving_search_cv  # noqa: F401
from sklearn.feature_extraction.text import TfidfVectorizer
from sklearn.impute import SimpleImputer
from sklearn.linear_model import (
    LinearRegression,
    LogisticRegression,
    Ridge,
    SGDClassifier,
)
from sklearn.metrics import (
    accuracy_score,
    confusion_matrix,
    f1_score,
    make_scorer,
    r2_score,
    recall_score,
    roc_auc_score,
)
from sklearn.metrics.pairwise import euclidean_distances
from sklearn.model_selection import (
    GridSearchCV,
    GroupKFold,
    GroupShuffleSplit,
    HalvingGridSearchCV,
    HalvingRandomSearchCV,
    KFold,
    LeaveOneGroupOut,
    LeavePGroupsOut,
    ParameterGrid,
    ParameterSampler,
    RandomizedSearchCV,
    StratifiedKFold,
    StratifiedShuffleSplit,
    train_test_split,
)
from sklearn.model_selection._search import (
    BaseSearchCV,
    _yield_masked_array_for_each_param,
)
from sklearn.model_selection.tests.common import OneTimeSplitter
from sklearn.naive_bayes import ComplementNB
from sklearn.neighbors import KernelDensity, KNeighborsClassifier, LocalOutlierFactor
from sklearn.pipeline import Pipeline, make_pipeline
from sklearn.preprocessing import (
    OneHotEncoder,
    OrdinalEncoder,
    SplineTransformer,
    StandardScaler,
)
from sklearn.svm import SVC, LinearSVC
from sklearn.tests.metadata_routing_common import (
    ConsumingScorer,
    _Registry,
    check_recorded_metadata,
)
from sklearn.tree import DecisionTreeClassifier, DecisionTreeRegressor
from sklearn.utils._array_api import (
    yield_namespace_device_dtype_combinations,
)
from sklearn.utils._mocking import CheckingClassifier, MockDataFrame
from sklearn.utils._testing import (
    MinimalClassifier,
    MinimalRegressor,
    MinimalTransformer,
    _array_api_for_tests,
    assert_allclose,
    assert_allclose_dense_sparse,
    assert_almost_equal,
    assert_array_almost_equal,
    assert_array_equal,
    set_random_state,
)
from sklearn.utils.estimator_checks import _enforce_estimator_tags_y
from sklearn.utils.fixes import CSR_CONTAINERS
from sklearn.utils.validation import _num_samples


# Neither of the following two estimators inherit from BaseEstimator,
# to test hyperparameter search on user-defined classifiers.
class MockClassifier(ClassifierMixin, BaseEstimator):
    """Dummy classifier to test the parameter search algorithms"""

    def __init__(self, foo_param=0):
        self.foo_param = foo_param

    def fit(self, X, Y):
        assert len(X) == len(Y)
        self.classes_ = np.unique(Y)
        return self

    def predict(self, T):
        return T.shape[0]

    def transform(self, X):
        return X + self.foo_param

    def inverse_transform(self, X):
        return X - self.foo_param

    predict_proba = predict
    predict_log_proba = predict
    decision_function = predict

    def score(self, X=None, Y=None):
        if self.foo_param > 1:
            score = 1.0
        else:
            score = 0.0
        return score

    def get_params(self, deep=False):
        return {"foo_param": self.foo_param}

    def set_params(self, **params):
        self.foo_param = params["foo_param"]
        return self


class LinearSVCNoScore(LinearSVC):
    """A LinearSVC classifier that has no score method."""

    @property
    def score(self):
        raise AttributeError


X = np.array([[-1, -1], [-2, -1], [1, 1], [2, 1]])
y = np.array([1, 1, 2, 2])


def assert_grid_iter_equals_getitem(grid):
    assert list(grid) == [grid[i] for i in range(len(grid))]


@pytest.mark.parametrize("klass", [ParameterGrid, partial(ParameterSampler, n_iter=10)])
@pytest.mark.parametrize(
    "input, error_type, error_message",
    [
        (0, TypeError, r"Parameter .* a dict or a list, got: 0 of type int"),
        ([{"foo": [0]}, 0], TypeError, r"Parameter .* is not a dict \(0\)"),
        (
            {"foo": 0},
            TypeError,
            r"Parameter (grid|distribution) for parameter 'foo' (is not|needs to be) "
            r"(a list or a numpy array|iterable or a distribution).*",
        ),
    ],
)
def test_validate_parameter_input(klass, input, error_type, error_message):
    with pytest.raises(error_type, match=error_message):
        klass(input)


def test_parameter_grid():
    # Test basic properties of ParameterGrid.
    params1 = {"foo": [1, 2, 3]}
    grid1 = ParameterGrid(params1)
    assert isinstance(grid1, Iterable)
    assert isinstance(grid1, Sized)
    assert len(grid1) == 3
    assert_grid_iter_equals_getitem(grid1)

    params2 = {"foo": [4, 2], "bar": ["ham", "spam", "eggs"]}
    grid2 = ParameterGrid(params2)
    assert len(grid2) == 6

    # loop to assert we can iterate over the grid multiple times
    for i in range(2):
        # tuple + chain transforms {"a": 1, "b": 2} to ("a", 1, "b", 2)
        points = set(tuple(chain(*(sorted(p.items())))) for p in grid2)
        assert points == set(
            ("bar", x, "foo", y) for x, y in product(params2["bar"], params2["foo"])
        )
    assert_grid_iter_equals_getitem(grid2)

    # Special case: empty grid (useful to get default estimator settings)
    empty = ParameterGrid({})
    assert len(empty) == 1
    assert list(empty) == [{}]
    assert_grid_iter_equals_getitem(empty)
    with pytest.raises(IndexError):
        empty[1]

    has_empty = ParameterGrid([{"C": [1, 10]}, {}, {"C": [0.5]}])
    assert len(has_empty) == 4
    assert list(has_empty) == [{"C": 1}, {"C": 10}, {}, {"C": 0.5}]
    assert_grid_iter_equals_getitem(has_empty)


def test_grid_search():
    # Test that the best estimator contains the right value for foo_param
    clf = MockClassifier()
    grid_search = GridSearchCV(clf, {"foo_param": [1, 2, 3]}, cv=2, verbose=3)
    # make sure it selects the smallest parameter in case of ties
    old_stdout = sys.stdout
    sys.stdout = StringIO()
    grid_search.fit(X, y)
    sys.stdout = old_stdout
    assert grid_search.best_estimator_.foo_param == 2

    assert_array_equal(grid_search.cv_results_["param_foo_param"].data, [1, 2, 3])

    # Smoke test the score etc:
    grid_search.score(X, y)
    grid_search.predict_proba(X)
    grid_search.decision_function(X)
    grid_search.transform(X)

    # Test exception handling on scoring
    grid_search.scoring = "sklearn"
    with pytest.raises(ValueError):
        grid_search.fit(X, y)


def test_grid_search_pipeline_steps():
    # check that parameters that are estimators are cloned before fitting
    pipe = Pipeline([("regressor", LinearRegression())])
    param_grid = {"regressor": [LinearRegression(), Ridge()]}
    grid_search = GridSearchCV(pipe, param_grid, cv=2)
    grid_search.fit(X, y)
    regressor_results = grid_search.cv_results_["param_regressor"]
    assert isinstance(regressor_results[0], LinearRegression)
    assert isinstance(regressor_results[1], Ridge)
    assert not hasattr(regressor_results[0], "coef_")
    assert not hasattr(regressor_results[1], "coef_")
    assert regressor_results[0] is not grid_search.best_estimator_
    assert regressor_results[1] is not grid_search.best_estimator_
    # check that we didn't modify the parameter grid that was passed
    assert not hasattr(param_grid["regressor"][0], "coef_")
    assert not hasattr(param_grid["regressor"][1], "coef_")


@pytest.mark.parametrize("SearchCV", [GridSearchCV, RandomizedSearchCV])
def test_SearchCV_with_fit_params(SearchCV):
    X = np.arange(100).reshape(10, 10)
    y = np.array([0] * 5 + [1] * 5)
    clf = CheckingClassifier(expected_fit_params=["spam", "eggs"])
    searcher = SearchCV(clf, {"foo_param": [1, 2, 3]}, cv=2, error_score="raise")

    # The CheckingClassifier generates an assertion error if
    # a parameter is missing or has length != len(X).
    err_msg = r"Expected fit parameter\(s\) \['eggs'\] not seen."
    with pytest.raises(AssertionError, match=err_msg):
        searcher.fit(X, y, spam=np.ones(10))

    err_msg = "Fit parameter spam has length 1; expected"
    with pytest.raises(AssertionError, match=err_msg):
        searcher.fit(X, y, spam=np.ones(1), eggs=np.zeros(10))
    searcher.fit(X, y, spam=np.ones(10), eggs=np.zeros(10))


def test_grid_search_no_score():
    # Test grid-search on classifier that has no score function.
    clf = LinearSVC(random_state=0)
    X, y = make_blobs(random_state=0, centers=2)
    Cs = [0.1, 1, 10]
    clf_no_score = LinearSVCNoScore(random_state=0)
    grid_search = GridSearchCV(clf, {"C": Cs}, scoring="accuracy")
    grid_search.fit(X, y)

    grid_search_no_score = GridSearchCV(clf_no_score, {"C": Cs}, scoring="accuracy")
    # smoketest grid search
    grid_search_no_score.fit(X, y)

    # check that best params are equal
    assert grid_search_no_score.best_params_ == grid_search.best_params_
    # check that we can call score and that it gives the correct result
    assert grid_search.score(X, y) == grid_search_no_score.score(X, y)

    # giving no scoring function raises an error
    grid_search_no_score = GridSearchCV(clf_no_score, {"C": Cs})
    with pytest.raises(TypeError, match="no scoring"):
        grid_search_no_score.fit([[1]])


def test_grid_search_score_method():
    X, y = make_classification(n_samples=100, n_classes=2, flip_y=0.2, random_state=0)
    clf = LinearSVC(random_state=0)
    grid = {"C": [0.1]}

    search_no_scoring = GridSearchCV(clf, grid, scoring=None).fit(X, y)
    search_accuracy = GridSearchCV(clf, grid, scoring="accuracy").fit(X, y)
    search_no_score_method_auc = GridSearchCV(
        LinearSVCNoScore(), grid, scoring="roc_auc"
    ).fit(X, y)
    search_auc = GridSearchCV(clf, grid, scoring="roc_auc").fit(X, y)

    # Check warning only occurs in situation where behavior changed:
    # estimator requires score method to compete with scoring parameter
    score_no_scoring = search_no_scoring.score(X, y)
    score_accuracy = search_accuracy.score(X, y)
    score_no_score_auc = search_no_score_method_auc.score(X, y)
    score_auc = search_auc.score(X, y)

    # ensure the test is sane
    assert score_auc < 1.0
    assert score_accuracy < 1.0
    assert score_auc != score_accuracy

    assert_almost_equal(score_accuracy, score_no_scoring)
    assert_almost_equal(score_auc, score_no_score_auc)


def test_grid_search_groups():
    # Check if ValueError (when groups is None) propagates to GridSearchCV
    # And also check if groups is correctly passed to the cv object
    rng = np.random.RandomState(0)

    X, y = make_classification(n_samples=15, n_classes=2, random_state=0)
    groups = rng.randint(0, 3, 15)

    clf = LinearSVC(random_state=0)
    grid = {"C": [1]}

    group_cvs = [
        LeaveOneGroupOut(),
        LeavePGroupsOut(2),
        GroupKFold(n_splits=3),
        GroupShuffleSplit(),
    ]
    error_msg = "The 'groups' parameter should not be None."
    for cv in group_cvs:
        gs = GridSearchCV(clf, grid, cv=cv)
        with pytest.raises(ValueError, match=error_msg):
            gs.fit(X, y)
        gs.fit(X, y, groups=groups)

    non_group_cvs = [StratifiedKFold(), StratifiedShuffleSplit()]
    for cv in non_group_cvs:
        gs = GridSearchCV(clf, grid, cv=cv)
        # Should not raise an error
        gs.fit(X, y)


def test_classes__property():
    # Test that classes_ property matches best_estimator_.classes_
    X = np.arange(100).reshape(10, 10)
    y = np.array([0] * 5 + [1] * 5)
    Cs = [0.1, 1, 10]

    grid_search = GridSearchCV(LinearSVC(random_state=0), {"C": Cs})
    grid_search.fit(X, y)
    assert_array_equal(grid_search.best_estimator_.classes_, grid_search.classes_)

    # Test that regressors do not have a classes_ attribute
    grid_search = GridSearchCV(Ridge(), {"alpha": [1.0, 2.0]})
    grid_search.fit(X, y)
    assert not hasattr(grid_search, "classes_")

    # Test that the grid searcher has no classes_ attribute before it's fit
    grid_search = GridSearchCV(LinearSVC(random_state=0), {"C": Cs})
    assert not hasattr(grid_search, "classes_")

    # Test that the grid searcher has no classes_ attribute without a refit
    grid_search = GridSearchCV(LinearSVC(random_state=0), {"C": Cs}, refit=False)
    grid_search.fit(X, y)
    assert not hasattr(grid_search, "classes_")


def test_trivial_cv_results_attr():
    # Test search over a "grid" with only one point.
    clf = MockClassifier()
    grid_search = GridSearchCV(clf, {"foo_param": [1]}, cv=2)
    grid_search.fit(X, y)
    assert hasattr(grid_search, "cv_results_")

    random_search = RandomizedSearchCV(clf, {"foo_param": [0]}, n_iter=1, cv=2)
    random_search.fit(X, y)
    assert hasattr(random_search, "cv_results_")


def test_no_refit():
    # Test that GSCV can be used for model selection alone without refitting
    clf = MockClassifier()
    for scoring in [None, ["accuracy", "precision"]]:
        grid_search = GridSearchCV(clf, {"foo_param": [1, 2, 3]}, refit=False, cv=2)
        grid_search.fit(X, y)
        assert (
            not hasattr(grid_search, "best_estimator_")
            and hasattr(grid_search, "best_index_")
            and hasattr(grid_search, "best_params_")
        )

        # Make sure the functions predict/transform etc. raise meaningful
        # error messages
        for fn_name in (
            "predict",
            "predict_proba",
            "predict_log_proba",
            "transform",
            "inverse_transform",
        ):
            outer_msg = f"has no attribute '{fn_name}'"
            inner_msg = (
                f"`refit=False`. {fn_name} is available only after "
                "refitting on the best parameters"
            )
            with pytest.raises(AttributeError, match=outer_msg) as exec_info:
                getattr(grid_search, fn_name)(X)

            assert isinstance(exec_info.value.__cause__, AttributeError)
            assert inner_msg in str(exec_info.value.__cause__)

    # Test that an invalid refit param raises appropriate error messages
    error_msg = (
        "For multi-metric scoring, the parameter refit must be set to a scorer key"
    )
    for refit in [True, "recall", "accuracy"]:
        with pytest.raises(ValueError, match=error_msg):
            GridSearchCV(
                clf, {}, refit=refit, scoring={"acc": "accuracy", "prec": "precision"}
            ).fit(X, y)


def test_grid_search_error():
    # Test that grid search will capture errors on data with different length
    X_, y_ = make_classification(n_samples=200, n_features=100, random_state=0)

    clf = LinearSVC()
    cv = GridSearchCV(clf, {"C": [0.1, 1.0]})
    with pytest.raises(ValueError):
        cv.fit(X_[:180], y_)


def test_grid_search_one_grid_point():
    X_, y_ = make_classification(n_samples=200, n_features=100, random_state=0)
    param_dict = {"C": [1.0], "kernel": ["rbf"], "gamma": [0.1]}

    clf = SVC(gamma="auto")
    cv = GridSearchCV(clf, param_dict)
    cv.fit(X_, y_)

    clf = SVC(C=1.0, kernel="rbf", gamma=0.1)
    clf.fit(X_, y_)

    assert_array_equal(clf.dual_coef_, cv.best_estimator_.dual_coef_)


def test_grid_search_when_param_grid_includes_range():
    # Test that the best estimator contains the right value for foo_param
    clf = MockClassifier()
    grid_search = None
    grid_search = GridSearchCV(clf, {"foo_param": range(1, 4)}, cv=2)
    grid_search.fit(X, y)
    assert grid_search.best_estimator_.foo_param == 2


def test_grid_search_bad_param_grid():
    X, y = make_classification(n_samples=10, n_features=5, random_state=0)
    param_dict = {"C": 1}
    clf = SVC(gamma="auto")
    error_msg = re.escape(
        "Parameter grid for parameter 'C' needs to be a list or "
        "a numpy array, but got 1 (of type int) instead. Single "
        "values need to be wrapped in a list with one element."
    )
    search = GridSearchCV(clf, param_dict)
    with pytest.raises(TypeError, match=error_msg):
        search.fit(X, y)

    param_dict = {"C": []}
    clf = SVC()
    error_msg = re.escape(
        "Parameter grid for parameter 'C' need to be a non-empty sequence, got: []"
    )
    search = GridSearchCV(clf, param_dict)
    with pytest.raises(ValueError, match=error_msg):
        search.fit(X, y)

    param_dict = {"C": "1,2,3"}
    clf = SVC(gamma="auto")
    error_msg = re.escape(
        "Parameter grid for parameter 'C' needs to be a list or a numpy array, "
        "but got '1,2,3' (of type str) instead. Single values need to be "
        "wrapped in a list with one element."
    )
    search = GridSearchCV(clf, param_dict)
    with pytest.raises(TypeError, match=error_msg):
        search.fit(X, y)

    param_dict = {"C": np.ones((3, 2))}
    clf = SVC()
    search = GridSearchCV(clf, param_dict)
    with pytest.raises(ValueError):
        search.fit(X, y)


@pytest.mark.parametrize("csr_container", CSR_CONTAINERS)
def test_grid_search_sparse(csr_container):
    # Test that grid search works with both dense and sparse matrices
    X_, y_ = make_classification(n_samples=200, n_features=100, random_state=0)

    clf = LinearSVC()
    cv = GridSearchCV(clf, {"C": [0.1, 1.0]})
    cv.fit(X_[:180], y_[:180])
    y_pred = cv.predict(X_[180:])
    C = cv.best_estimator_.C

    X_ = csr_container(X_)
    clf = LinearSVC()
    cv = GridSearchCV(clf, {"C": [0.1, 1.0]})
    cv.fit(X_[:180].tocoo(), y_[:180])
    y_pred2 = cv.predict(X_[180:])
    C2 = cv.best_estimator_.C

    assert np.mean(y_pred == y_pred2) >= 0.9
    assert C == C2


@pytest.mark.parametrize("csr_container", CSR_CONTAINERS)
def test_grid_search_sparse_scoring(csr_container):
    X_, y_ = make_classification(n_samples=200, n_features=100, random_state=0)

    clf = LinearSVC()
    cv = GridSearchCV(clf, {"C": [0.1, 1.0]}, scoring="f1")
    cv.fit(X_[:180], y_[:180])
    y_pred = cv.predict(X_[180:])
    C = cv.best_estimator_.C

    X_ = csr_container(X_)
    clf = LinearSVC()
    cv = GridSearchCV(clf, {"C": [0.1, 1.0]}, scoring="f1")
    cv.fit(X_[:180], y_[:180])
    y_pred2 = cv.predict(X_[180:])
    C2 = cv.best_estimator_.C

    assert_array_equal(y_pred, y_pred2)
    assert C == C2
    # Smoke test the score
    # np.testing.assert_allclose(f1_score(cv.predict(X_[:180]), y[:180]),
    #                            cv.score(X_[:180], y[:180]))

    # test loss where greater is worse
    def f1_loss(y_true_, y_pred_):
        return -f1_score(y_true_, y_pred_)

    F1Loss = make_scorer(f1_loss, greater_is_better=False)
    cv = GridSearchCV(clf, {"C": [0.1, 1.0]}, scoring=F1Loss)
    cv.fit(X_[:180], y_[:180])
    y_pred3 = cv.predict(X_[180:])
    C3 = cv.best_estimator_.C

    assert C == C3
    assert_array_equal(y_pred, y_pred3)


def test_grid_search_precomputed_kernel():
    # Test that grid search works when the input features are given in the
    # form of a precomputed kernel matrix
    X_, y_ = make_classification(n_samples=200, n_features=100, random_state=0)

    # compute the training kernel matrix corresponding to the linear kernel
    K_train = np.dot(X_[:180], X_[:180].T)
    y_train = y_[:180]

    clf = SVC(kernel="precomputed")
    cv = GridSearchCV(clf, {"C": [0.1, 1.0]})
    cv.fit(K_train, y_train)

    assert cv.best_score_ >= 0

    # compute the test kernel matrix
    K_test = np.dot(X_[180:], X_[:180].T)
    y_test = y_[180:]

    y_pred = cv.predict(K_test)

    assert np.mean(y_pred == y_test) >= 0

    # test error is raised when the precomputed kernel is not array-like
    # or sparse
    with pytest.raises(ValueError):
        cv.fit(K_train.tolist(), y_train)


def test_grid_search_precomputed_kernel_error_nonsquare():
    # Test that grid search returns an error with a non-square precomputed
    # training kernel matrix
    K_train = np.zeros((10, 20))
    y_train = np.ones((10,))
    clf = SVC(kernel="precomputed")
    cv = GridSearchCV(clf, {"C": [0.1, 1.0]})
    with pytest.raises(ValueError):
        cv.fit(K_train, y_train)


class BrokenClassifier(BaseEstimator):
    """Broken classifier that cannot be fit twice"""

    def __init__(self, parameter=None):
        self.parameter = parameter

    def fit(self, X, y):
        assert not hasattr(self, "has_been_fit_")
        self.has_been_fit_ = True

    def predict(self, X):
        return np.zeros(X.shape[0])


@pytest.mark.filterwarnings("ignore::sklearn.exceptions.UndefinedMetricWarning")
def test_refit():
    # Regression test for bug in refitting
    # Simulates re-fitting a broken estimator; this used to break with
    # sparse SVMs.
    X = np.arange(100).reshape(10, 10)
    y = np.array([0] * 5 + [1] * 5)

    clf = GridSearchCV(
        BrokenClassifier(), [{"parameter": [0, 1]}], scoring="precision", refit=True
    )
    clf.fit(X, y)


def test_refit_callable():
    """
    Test refit=callable, which adds flexibility in identifying the
    "best" estimator.
    """

    def refit_callable(cv_results):
        """
        A dummy function tests `refit=callable` interface.
        Return the index of a model that has the least
        `mean_test_score`.
        """
        # Fit a dummy clf with `refit=True` to get a list of keys in
        # clf.cv_results_.
        X, y = make_classification(n_samples=100, n_features=4, random_state=42)
        clf = GridSearchCV(
            LinearSVC(random_state=42),
            {"C": [0.01, 0.1, 1]},
            scoring="precision",
            refit=True,
        )
        clf.fit(X, y)
        # Ensure that `best_index_ != 0` for this dummy clf
        assert clf.best_index_ != 0

        # Assert every key matches those in `cv_results`
        for key in clf.cv_results_.keys():
            assert key in cv_results

        return cv_results["mean_test_score"].argmin()

    X, y = make_classification(n_samples=100, n_features=4, random_state=42)
    clf = GridSearchCV(
        LinearSVC(random_state=42),
        {"C": [0.01, 0.1, 1]},
        scoring="precision",
        refit=refit_callable,
    )
    clf.fit(X, y)

    assert clf.best_index_ == 0
    # Ensure `best_score_` is disabled when using `refit=callable`
    assert not hasattr(clf, "best_score_")


def test_refit_callable_invalid_type():
    """
    Test implementation catches the errors when 'best_index_' returns an
    invalid result.
    """

    def refit_callable_invalid_type(cv_results):
        """
        A dummy function tests when returned 'best_index_' is not integer.
        """
        return None

    X, y = make_classification(n_samples=100, n_features=4, random_state=42)

    clf = GridSearchCV(
        LinearSVC(random_state=42),
        {"C": [0.1, 1]},
        scoring="precision",
        refit=refit_callable_invalid_type,
    )
    with pytest.raises(TypeError, match="best_index_ returned is not an integer"):
        clf.fit(X, y)


@pytest.mark.parametrize("out_bound_value", [-1, 2])
@pytest.mark.parametrize("search_cv", [RandomizedSearchCV, GridSearchCV])
def test_refit_callable_out_bound(out_bound_value, search_cv):
    """
    Test implementation catches the errors when 'best_index_' returns an
    out of bound result.
    """

    def refit_callable_out_bound(cv_results):
        """
        A dummy function tests when returned 'best_index_' is out of bounds.
        """
        return out_bound_value

    X, y = make_classification(n_samples=100, n_features=4, random_state=42)

    clf = search_cv(
        LinearSVC(random_state=42),
        {"C": [0.1, 1]},
        scoring="precision",
        refit=refit_callable_out_bound,
    )
    with pytest.raises(IndexError, match="best_index_ index out of range"):
        clf.fit(X, y)


def test_refit_callable_multi_metric():
    """
    Test refit=callable in multiple metric evaluation setting
    """

    def refit_callable(cv_results):
        """
        A dummy function tests `refit=callable` interface.
        Return the index of a model that has the least
        `mean_test_prec`.
        """
        assert "mean_test_prec" in cv_results
        return cv_results["mean_test_prec"].argmin()

    X, y = make_classification(n_samples=100, n_features=4, random_state=42)
    scoring = {"Accuracy": make_scorer(accuracy_score), "prec": "precision"}
    clf = GridSearchCV(
        LinearSVC(random_state=42),
        {"C": [0.01, 0.1, 1]},
        scoring=scoring,
        refit=refit_callable,
    )
    clf.fit(X, y)

    assert clf.best_index_ == 0
    # Ensure `best_score_` is disabled when using `refit=callable`
    assert not hasattr(clf, "best_score_")


def test_gridsearch_nd():
    # Pass X as list in GridSearchCV
    X_4d = np.arange(10 * 5 * 3 * 2).reshape(10, 5, 3, 2)
    y_3d = np.arange(10 * 7 * 11).reshape(10, 7, 11)

    def check_X(x):
        return x.shape[1:] == (5, 3, 2)

    def check_y(x):
        return x.shape[1:] == (7, 11)

    clf = CheckingClassifier(
        check_X=check_X,
        check_y=check_y,
        methods_to_check=["fit"],
    )
    grid_search = GridSearchCV(clf, {"foo_param": [1, 2, 3]})
    grid_search.fit(X_4d, y_3d).score(X, y)
    assert hasattr(grid_search, "cv_results_")


def test_X_as_list():
    # Pass X as list in GridSearchCV
    X = np.arange(100).reshape(10, 10)
    y = np.array([0] * 5 + [1] * 5)

    clf = CheckingClassifier(
        check_X=lambda x: isinstance(x, list),
        methods_to_check=["fit"],
    )
    cv = KFold(n_splits=3)
    grid_search = GridSearchCV(clf, {"foo_param": [1, 2, 3]}, cv=cv)
    grid_search.fit(X.tolist(), y).score(X, y)
    assert hasattr(grid_search, "cv_results_")


def test_y_as_list():
    # Pass y as list in GridSearchCV
    X = np.arange(100).reshape(10, 10)
    y = np.array([0] * 5 + [1] * 5)

    clf = CheckingClassifier(
        check_y=lambda x: isinstance(x, list),
        methods_to_check=["fit"],
    )
    cv = KFold(n_splits=3)
    grid_search = GridSearchCV(clf, {"foo_param": [1, 2, 3]}, cv=cv)
    grid_search.fit(X, y.tolist()).score(X, y)
    assert hasattr(grid_search, "cv_results_")


def test_pandas_input():
    # check cross_val_score doesn't destroy pandas dataframe
    types = [(MockDataFrame, MockDataFrame)]
    try:
        from pandas import DataFrame, Series

        types.append((DataFrame, Series))
    except ImportError:
        pass

    X = np.arange(100).reshape(10, 10)
    y = np.array([0] * 5 + [1] * 5)

    for InputFeatureType, TargetType in types:
        # X dataframe, y series
        X_df, y_ser = InputFeatureType(X), TargetType(y)

        def check_df(x):
            return isinstance(x, InputFeatureType)

        def check_series(x):
            return isinstance(x, TargetType)

        clf = CheckingClassifier(check_X=check_df, check_y=check_series)

        grid_search = GridSearchCV(clf, {"foo_param": [1, 2, 3]})
        grid_search.fit(X_df, y_ser).score(X_df, y_ser)
        grid_search.predict(X_df)
        assert hasattr(grid_search, "cv_results_")


def test_unsupervised_grid_search():
    # test grid-search with unsupervised estimator
    X, y = make_blobs(n_samples=50, random_state=0)
    km = KMeans(random_state=0, init="random", n_init=1)

    # Multi-metric evaluation unsupervised
    scoring = ["adjusted_rand_score", "fowlkes_mallows_score"]
    for refit in ["adjusted_rand_score", "fowlkes_mallows_score"]:
        grid_search = GridSearchCV(
            km, param_grid=dict(n_clusters=[2, 3, 4]), scoring=scoring, refit=refit
        )
        grid_search.fit(X, y)
        # Both ARI and FMS can find the right number :)
        assert grid_search.best_params_["n_clusters"] == 3

    # Single metric evaluation unsupervised
    grid_search = GridSearchCV(
        km, param_grid=dict(n_clusters=[2, 3, 4]), scoring="fowlkes_mallows_score"
    )
    grid_search.fit(X, y)
    assert grid_search.best_params_["n_clusters"] == 3

    # Now without a score, and without y
    grid_search = GridSearchCV(km, param_grid=dict(n_clusters=[2, 3, 4]))
    grid_search.fit(X)
    assert grid_search.best_params_["n_clusters"] == 4


def test_gridsearch_no_predict():
    # test grid-search with an estimator without predict.
    # slight duplication of a test from KDE
    def custom_scoring(estimator, X):
        return 42 if estimator.bandwidth == 0.1 else 0

    X, _ = make_blobs(cluster_std=0.1, random_state=1, centers=[[0, 1], [1, 0], [0, 0]])
    search = GridSearchCV(
        KernelDensity(),
        param_grid=dict(bandwidth=[0.01, 0.1, 1]),
        scoring=custom_scoring,
    )
    search.fit(X)
    assert search.best_params_["bandwidth"] == 0.1
    assert search.best_score_ == 42


def test_param_sampler():
    # test basic properties of param sampler
    param_distributions = {"kernel": ["rbf", "linear"], "C": uniform(0, 1)}
    sampler = ParameterSampler(
        param_distributions=param_distributions, n_iter=10, random_state=0
    )
    samples = [x for x in sampler]
    assert len(samples) == 10
    for sample in samples:
        assert sample["kernel"] in ["rbf", "linear"]
        assert 0 <= sample["C"] <= 1

    # test that repeated calls yield identical parameters
    param_distributions = {"C": [0, 1, 2, 3, 4, 5, 6, 7, 8, 9, 10]}
    sampler = ParameterSampler(
        param_distributions=param_distributions, n_iter=3, random_state=0
    )
    assert [x for x in sampler] == [x for x in sampler]

    param_distributions = {"C": uniform(0, 1)}
    sampler = ParameterSampler(
        param_distributions=param_distributions, n_iter=10, random_state=0
    )
    assert [x for x in sampler] == [x for x in sampler]


def check_cv_results_array_types(
    search, param_keys, score_keys, expected_cv_results_kinds
):
    # Check if the search `cv_results`'s array are of correct types
    cv_results = search.cv_results_
    assert all(isinstance(cv_results[param], np.ma.MaskedArray) for param in param_keys)
    assert {
        key: cv_results[key].dtype.kind for key in param_keys
    } == expected_cv_results_kinds
    assert not any(isinstance(cv_results[key], np.ma.MaskedArray) for key in score_keys)
    assert all(
        cv_results[key].dtype == np.float64
        for key in score_keys
        if not key.startswith("rank")
    )

    scorer_keys = search.scorer_.keys() if search.multimetric_ else ["score"]

    for key in scorer_keys:
        assert cv_results["rank_test_%s" % key].dtype == np.int32


def check_cv_results_keys(cv_results, param_keys, score_keys, n_cand, extra_keys=()):
    # Test the search.cv_results_ contains all the required results
    all_keys = param_keys + score_keys + extra_keys
    assert_array_equal(sorted(cv_results.keys()), sorted(all_keys + ("params",)))
    assert all(cv_results[key].shape == (n_cand,) for key in param_keys + score_keys)


def test_grid_search_cv_results():
    X, y = make_classification(n_samples=50, n_features=4, random_state=42)

    n_grid_points = 6
    params = [
        dict(
            kernel=[
                "rbf",
            ],
            C=[1, 10],
            gamma=[0.1, 1],
        ),
        dict(
            kernel=[
                "poly",
            ],
            degree=[1, 2],
        ),
    ]

    param_keys = ("param_C", "param_degree", "param_gamma", "param_kernel")
    score_keys = (
        "mean_test_score",
        "mean_train_score",
        "rank_test_score",
        "split0_test_score",
        "split1_test_score",
        "split2_test_score",
        "split0_train_score",
        "split1_train_score",
        "split2_train_score",
        "std_test_score",
        "std_train_score",
        "mean_fit_time",
        "std_fit_time",
        "mean_score_time",
        "std_score_time",
    )
    n_candidates = n_grid_points

    search = GridSearchCV(SVC(), cv=3, param_grid=params, return_train_score=True)
    search.fit(X, y)
    cv_results = search.cv_results_
    # Check if score and timing are reasonable
    assert all(cv_results["rank_test_score"] >= 1)
    assert (all(cv_results[k] >= 0) for k in score_keys if k != "rank_test_score")
    assert (
        all(cv_results[k] <= 1)
        for k in score_keys
        if "time" not in k and k != "rank_test_score"
    )
    # Check cv_results structure
    expected_cv_results_kinds = {
        "param_C": "i",
        "param_degree": "i",
        "param_gamma": "f",
        "param_kernel": "O",
    }
    check_cv_results_array_types(
        search, param_keys, score_keys, expected_cv_results_kinds
    )
    check_cv_results_keys(cv_results, param_keys, score_keys, n_candidates)
    # Check masking
    cv_results = search.cv_results_

    poly_results = [
        (
            cv_results["param_C"].mask[i]
            and cv_results["param_gamma"].mask[i]
            and not cv_results["param_degree"].mask[i]
        )
        for i in range(n_candidates)
        if cv_results["param_kernel"][i] == "poly"
    ]
    assert all(poly_results)
    assert len(poly_results) == 2

    rbf_results = [
        (
            not cv_results["param_C"].mask[i]
            and not cv_results["param_gamma"].mask[i]
            and cv_results["param_degree"].mask[i]
        )
        for i in range(n_candidates)
        if cv_results["param_kernel"][i] == "rbf"
    ]
    assert all(rbf_results)
    assert len(rbf_results) == 4


def test_random_search_cv_results():
    X, y = make_classification(n_samples=50, n_features=4, random_state=42)

    n_search_iter = 30

    params = [
        {"kernel": ["rbf"], "C": expon(scale=10), "gamma": expon(scale=0.1)},
        {"kernel": ["poly"], "degree": [2, 3]},
    ]
    param_keys = ("param_C", "param_degree", "param_gamma", "param_kernel")
    score_keys = (
        "mean_test_score",
        "mean_train_score",
        "rank_test_score",
        "split0_test_score",
        "split1_test_score",
        "split2_test_score",
        "split0_train_score",
        "split1_train_score",
        "split2_train_score",
        "std_test_score",
        "std_train_score",
        "mean_fit_time",
        "std_fit_time",
        "mean_score_time",
        "std_score_time",
    )
    n_candidates = n_search_iter

    search = RandomizedSearchCV(
        SVC(),
        n_iter=n_search_iter,
        cv=3,
        param_distributions=params,
        return_train_score=True,
    )
    search.fit(X, y)
    cv_results = search.cv_results_
    # Check results structure
    expected_cv_results_kinds = {
        "param_C": "f",
        "param_degree": "i",
        "param_gamma": "f",
        "param_kernel": "O",
    }
    check_cv_results_array_types(
        search, param_keys, score_keys, expected_cv_results_kinds
    )
    check_cv_results_keys(cv_results, param_keys, score_keys, n_candidates)
    assert all(
        (
            cv_results["param_C"].mask[i]
            and cv_results["param_gamma"].mask[i]
            and not cv_results["param_degree"].mask[i]
        )
        for i in range(n_candidates)
        if cv_results["param_kernel"][i] == "poly"
    )
    assert all(
        (
            not cv_results["param_C"].mask[i]
            and not cv_results["param_gamma"].mask[i]
            and cv_results["param_degree"].mask[i]
        )
        for i in range(n_candidates)
        if cv_results["param_kernel"][i] == "rbf"
    )


@pytest.mark.parametrize(
    "SearchCV, specialized_params",
    [
        (GridSearchCV, {"param_grid": {"C": [1, 10]}}),
        (RandomizedSearchCV, {"param_distributions": {"C": [1, 10]}, "n_iter": 2}),
    ],
)
def test_search_default_iid(SearchCV, specialized_params):
    # Test the IID parameter  TODO: Clearly this test does something else???
    # noise-free simple 2d-data
    X, y = make_blobs(
        centers=[[0, 0], [1, 0], [0, 1], [1, 1]],
        random_state=0,
        cluster_std=0.1,
        shuffle=False,
        n_samples=80,
    )
    # split dataset into two folds that are not iid
    # first one contains data of all 4 blobs, second only from two.
    mask = np.ones(X.shape[0], dtype=bool)
    mask[np.where(y == 1)[0][::2]] = 0
    mask[np.where(y == 2)[0][::2]] = 0
    # this leads to perfect classification on one fold and a score of 1/3 on
    # the other
    # create "cv" for splits
    cv = [[mask, ~mask], [~mask, mask]]

    common_params = {"estimator": SVC(), "cv": cv, "return_train_score": True}
    search = SearchCV(**common_params, **specialized_params)
    search.fit(X, y)

    test_cv_scores = np.array(
        [
            search.cv_results_["split%d_test_score" % s][0]
            for s in range(search.n_splits_)
        ]
    )
    test_mean = search.cv_results_["mean_test_score"][0]
    test_std = search.cv_results_["std_test_score"][0]

    train_cv_scores = np.array(
        [
            search.cv_results_["split%d_train_score" % s][0]
            for s in range(search.n_splits_)
        ]
    )
    train_mean = search.cv_results_["mean_train_score"][0]
    train_std = search.cv_results_["std_train_score"][0]

    assert search.cv_results_["param_C"][0] == 1
    # scores are the same as above
    assert_allclose(test_cv_scores, [1, 1.0 / 3.0])
    assert_allclose(train_cv_scores, [1, 1])
    # Unweighted mean/std is used
    assert test_mean == pytest.approx(np.mean(test_cv_scores))
    assert test_std == pytest.approx(np.std(test_cv_scores))

    # For the train scores, we do not take a weighted mean irrespective of
    # i.i.d. or not
    assert train_mean == pytest.approx(1)
    assert train_std == pytest.approx(0)


def test_grid_search_cv_results_multimetric():
    X, y = make_classification(n_samples=50, n_features=4, random_state=42)

    n_splits = 3
    params = [
        dict(
            kernel=[
                "rbf",
            ],
            C=[1, 10],
            gamma=[0.1, 1],
        ),
        dict(
            kernel=[
                "poly",
            ],
            degree=[1, 2],
        ),
    ]

    grid_searches = []
    for scoring in (
        {"accuracy": make_scorer(accuracy_score), "recall": make_scorer(recall_score)},
        "accuracy",
        "recall",
    ):
        grid_search = GridSearchCV(
            SVC(), cv=n_splits, param_grid=params, scoring=scoring, refit=False
        )
        grid_search.fit(X, y)
        grid_searches.append(grid_search)

    compare_cv_results_multimetric_with_single(*grid_searches)


def test_random_search_cv_results_multimetric():
    X, y = make_classification(n_samples=50, n_features=4, random_state=42)

    n_splits = 3
    n_search_iter = 30

    params = dict(C=np.logspace(-4, 1, 3))
    for refit in (True, False):
        random_searches = []
        for scoring in (("accuracy", "recall"), "accuracy", "recall"):
            # If True, for multi-metric pass refit='accuracy'
            if refit and isinstance(scoring, tuple):
                refit = "accuracy"
            clf = LogisticRegression(random_state=42)
            random_search = RandomizedSearchCV(
                clf,
                n_iter=n_search_iter,
                cv=n_splits,
                param_distributions=params,
                scoring=scoring,
                refit=refit,
                random_state=0,
            )
            random_search.fit(X, y)
            random_searches.append(random_search)

        compare_cv_results_multimetric_with_single(*random_searches)
        compare_refit_methods_when_refit_with_acc(
            random_searches[0], random_searches[1], refit
        )


def compare_cv_results_multimetric_with_single(search_multi, search_acc, search_rec):
    """Compare multi-metric cv_results with the ensemble of multiple
    single metric cv_results from single metric grid/random search"""

    assert search_multi.multimetric_
    assert_array_equal(sorted(search_multi.scorer_), ("accuracy", "recall"))

    cv_results_multi = search_multi.cv_results_
    cv_results_acc_rec = {
        re.sub("_score$", "_accuracy", k): v for k, v in search_acc.cv_results_.items()
    }
    cv_results_acc_rec.update(
        {re.sub("_score$", "_recall", k): v for k, v in search_rec.cv_results_.items()}
    )

    # Check if score and timing are reasonable, also checks if the keys
    # are present
    assert all(
        (
            np.all(cv_results_multi[k] <= 1)
            for k in (
                "mean_score_time",
                "std_score_time",
                "mean_fit_time",
                "std_fit_time",
            )
        )
    )

    # Compare the keys, other than time keys, among multi-metric and
    # single metric grid search results. np.testing.assert_equal performs a
    # deep nested comparison of the two cv_results dicts
    np.testing.assert_equal(
        {k: v for k, v in cv_results_multi.items() if not k.endswith("_time")},
        {k: v for k, v in cv_results_acc_rec.items() if not k.endswith("_time")},
    )


def compare_refit_methods_when_refit_with_acc(search_multi, search_acc, refit):
    """Compare refit multi-metric search methods with single metric methods"""
    assert search_acc.refit == refit
    if refit:
        assert search_multi.refit == "accuracy"
    else:
        assert not search_multi.refit
        return  # search cannot predict/score without refit

    X, y = make_blobs(n_samples=100, n_features=4, random_state=42)
    for method in ("predict", "predict_proba", "predict_log_proba"):
        assert_almost_equal(
            getattr(search_multi, method)(X), getattr(search_acc, method)(X)
        )
    assert_almost_equal(search_multi.score(X, y), search_acc.score(X, y))
    for key in ("best_index_", "best_score_", "best_params_"):
        assert getattr(search_multi, key) == getattr(search_acc, key)


@pytest.mark.parametrize(
    "search_cv",
    [
        RandomizedSearchCV(
            estimator=DecisionTreeClassifier(),
            param_distributions={"max_depth": [5, 10]},
        ),
        GridSearchCV(
            estimator=DecisionTreeClassifier(), param_grid={"max_depth": [5, 10]}
        ),
    ],
)
def test_search_cv_score_samples_error(search_cv):
    X, y = make_blobs(n_samples=100, n_features=4, random_state=42)
    search_cv = clone(search_cv)
    search_cv.fit(X, y)

    # Make sure to error out when underlying estimator does not implement
    # the method `score_samples`
    outer_msg = f"'{search_cv.__class__.__name__}' has no attribute 'score_samples'"
    inner_msg = "'DecisionTreeClassifier' object has no attribute 'score_samples'"

    with pytest.raises(AttributeError, match=outer_msg) as exec_info:
        search_cv.score_samples(X)
    assert isinstance(exec_info.value.__cause__, AttributeError)
    assert inner_msg == str(exec_info.value.__cause__)


def test_unsupported_sample_weight_scorer():
    """Checks that fitting with sample_weight raises a warning if the scorer does not
    support sample_weight"""

    def fake_score_func(y_true, y_pred):
        "Fake scoring function that does not support sample_weight"
        return 0.5

    fake_scorer = make_scorer(fake_score_func)

    X, y = make_classification(n_samples=10, n_features=4, random_state=42)
    sw = np.ones_like(y)
    search_cv = GridSearchCV(estimator=LogisticRegression(), param_grid={"C": [1, 10]})
    # function
    search_cv.set_params(scoring=fake_score_func)
    with pytest.warns(UserWarning, match="does not support sample_weight"):
        search_cv.fit(X, y, sample_weight=sw)
    # scorer
    search_cv.set_params(scoring=fake_scorer)
    with pytest.warns(UserWarning, match="does not support sample_weight"):
        search_cv.fit(X, y, sample_weight=sw)
    # multi-metric evaluation
    search_cv.set_params(
        scoring=dict(fake=fake_scorer, accuracy="accuracy"), refit=False
    )
    # only fake scorer does not support sample_weight
    with pytest.warns(
        UserWarning, match=r"The scoring fake=.* does not support sample_weight"
    ):
        search_cv.fit(X, y, sample_weight=sw)


@pytest.mark.parametrize(
    "estimator",
    [
        GridSearchCV(estimator=LogisticRegression(), param_grid={"C": [1, 10, 100]}),
        RandomizedSearchCV(
            estimator=Ridge(), param_distributions={"alpha": [1, 0.1, 0.01]}
        ),
    ],
)
def test_search_cv_sample_weight_equivalence(estimator):
    estimator_weighted = clone(estimator)
    estimator_repeated = clone(estimator)
    set_random_state(estimator_weighted, random_state=0)
    set_random_state(estimator_repeated, random_state=0)

    rng = np.random.RandomState(42)
    n_classes = 3
    n_samples_per_group = 30
    n_groups = 4
    n_samples = n_groups * n_samples_per_group
    X = rng.rand(n_samples, n_samples * 2)
    y = rng.randint(0, n_classes, size=n_samples)
    sw = rng.randint(0, 5, size=n_samples)
    # we use groups with LeaveOneGroupOut to ensure that
    # the splits are the same in the repeated/weighted datasets
    groups = np.tile(np.arange(n_groups), n_samples_per_group)

    X_weighted = X
    y_weighted = y
    groups_weighted = groups
    splits_weighted = list(LeaveOneGroupOut().split(X_weighted, groups=groups_weighted))
    estimator_weighted.set_params(cv=splits_weighted)
    # repeat samples according to weights
    X_repeated = X_weighted.repeat(repeats=sw, axis=0)
    y_repeated = y_weighted.repeat(repeats=sw)
    groups_repeated = groups_weighted.repeat(repeats=sw)
    splits_repeated = list(LeaveOneGroupOut().split(X_repeated, groups=groups_repeated))
    estimator_repeated.set_params(cv=splits_repeated)

    y_weighted = _enforce_estimator_tags_y(estimator_weighted, y_weighted)
    y_repeated = _enforce_estimator_tags_y(estimator_repeated, y_repeated)

    estimator_repeated.fit(X_repeated, y=y_repeated, sample_weight=None)
    estimator_weighted.fit(X_weighted, y=y_weighted, sample_weight=sw)

    # check that scores stored in cv_results_
    # are equal for the weighted/repeated datasets
    score_keys = [
        key for key in estimator_repeated.cv_results_ if key.endswith("score")
    ]
    for key in score_keys:
        s1 = estimator_repeated.cv_results_[key]
        s2 = estimator_weighted.cv_results_[key]
        err_msg = f"{key} values are not equal for weighted/repeated datasets"
        assert_allclose(s1, s2, err_msg=err_msg)

    for key in ["best_score_", "best_index_"]:
        s1 = getattr(estimator_repeated, key)
        s2 = getattr(estimator_weighted, key)
        err_msg = f"{key} values are not equal for weighted/repeated datasets"
        assert_almost_equal(s1, s2, err_msg=err_msg)

    for method in ["predict_proba", "decision_function", "predict", "transform"]:
        if hasattr(estimator, method):
            s1 = getattr(estimator_repeated, method)(X)
            s2 = getattr(estimator_weighted, method)(X)
            err_msg = (
                f"Comparing the output of {method} revealed that fitting "
                "with `sample_weight` is not equivalent to fitting with removed "
                "or repeated data points."
            )
            assert_allclose_dense_sparse(s1, s2, err_msg=err_msg)


@pytest.mark.parametrize(
    "search_cv",
    [
        RandomizedSearchCV(
            estimator=LocalOutlierFactor(novelty=True),
            param_distributions={"n_neighbors": [5, 10]},
            scoring="precision",
        ),
        GridSearchCV(
            estimator=LocalOutlierFactor(novelty=True),
            param_grid={"n_neighbors": [5, 10]},
            scoring="precision",
        ),
    ],
)
def test_search_cv_score_samples_method(search_cv):
    search_cv = clone(search_cv)  # Avoid side effects from previous tests.
    # Set parameters
    rng = np.random.RandomState(42)
    n_samples = 300
    outliers_fraction = 0.15
    n_outliers = int(outliers_fraction * n_samples)
    n_inliers = n_samples - n_outliers

    # Create dataset
    X = make_blobs(
        n_samples=n_inliers,
        n_features=2,
        centers=[[0, 0], [0, 0]],
        cluster_std=0.5,
        random_state=0,
    )[0]
    # Add some noisy points
    X = np.concatenate([X, rng.uniform(low=-6, high=6, size=(n_outliers, 2))], axis=0)

    # Define labels to be able to score the estimator with `search_cv`
    y_true = np.array([1] * n_samples)
    y_true[-n_outliers:] = -1

    # Fit on data
    search_cv.fit(X, y_true)

    # Verify that the stand alone estimator yields the same results
    # as the ones obtained with *SearchCV
    assert_allclose(
        search_cv.score_samples(X), search_cv.best_estimator_.score_samples(X)
    )


def test_search_cv_results_rank_tie_breaking():
    X, y = make_blobs(n_samples=50, random_state=42)

    # The two C values are close enough to give similar models
    # which would result in a tie of their mean cv-scores
    param_grid = {"C": [1, 1.001, 0.001]}

    grid_search = GridSearchCV(SVC(), param_grid=param_grid, return_train_score=True)
    random_search = RandomizedSearchCV(
        SVC(), n_iter=3, param_distributions=param_grid, return_train_score=True
    )

    for search in (grid_search, random_search):
        search.fit(X, y)
        cv_results = search.cv_results_
        # Check tie breaking strategy -
        # Check that there is a tie in the mean scores between
        # candidates 1 and 2 alone
        assert_almost_equal(
            cv_results["mean_test_score"][0], cv_results["mean_test_score"][1]
        )
        assert_almost_equal(
            cv_results["mean_train_score"][0], cv_results["mean_train_score"][1]
        )
        assert not np.allclose(
            cv_results["mean_test_score"][1], cv_results["mean_test_score"][2]
        )
        assert not np.allclose(
            cv_results["mean_train_score"][1], cv_results["mean_train_score"][2]
        )
        # 'min' rank should be assigned to the tied candidates
        assert_almost_equal(search.cv_results_["rank_test_score"], [1, 1, 3])


def test_search_cv_results_none_param():
    X, y = [[1], [2], [3], [4], [5]], [0, 0, 0, 0, 1]
    estimators = (DecisionTreeRegressor(), DecisionTreeClassifier())
    est_parameters = {"random_state": [0, None]}
    cv = KFold()

    for est in estimators:
        grid_search = GridSearchCV(
            est,
            est_parameters,
            cv=cv,
        ).fit(X, y)
        assert_array_equal(grid_search.cv_results_["param_random_state"], [0, None])


@pytest.mark.filterwarnings("ignore::sklearn.exceptions.FitFailedWarning")
def test_search_cv_timing():
    svc = LinearSVC(random_state=0)

    X = [
        [
            1,
        ],
        [
            2,
        ],
        [
            3,
        ],
        [
            4,
        ],
    ]
    y = [0, 1, 1, 0]

    gs = GridSearchCV(svc, {"C": [0, 1]}, cv=2, error_score=0)
    rs = RandomizedSearchCV(svc, {"C": [0, 1]}, cv=2, error_score=0, n_iter=2)

    for search in (gs, rs):
        search.fit(X, y)
        for key in ["mean_fit_time", "std_fit_time"]:
            # NOTE The precision of time.time in windows is not high
            # enough for the fit/score times to be non-zero for trivial X and y
            assert np.all(search.cv_results_[key] >= 0)
            assert np.all(search.cv_results_[key] < 1)

        for key in ["mean_score_time", "std_score_time"]:
            assert search.cv_results_[key][1] >= 0
            assert search.cv_results_[key][0] == 0.0
            assert np.all(search.cv_results_[key] < 1)

        assert hasattr(search, "refit_time_")
        assert isinstance(search.refit_time_, float)
        assert search.refit_time_ >= 0


def test_grid_search_correct_score_results():
    # test that correct scores are used
    n_splits = 3
    clf = LinearSVC(random_state=0)
    X, y = make_blobs(random_state=0, centers=2)
    Cs = [0.1, 1, 10]
    for score in ["f1", "roc_auc"]:
        grid_search = GridSearchCV(clf, {"C": Cs}, scoring=score, cv=n_splits)
        cv_results = grid_search.fit(X, y).cv_results_

        # Test scorer names
        result_keys = list(cv_results.keys())
        expected_keys = ("mean_test_score", "rank_test_score") + tuple(
            "split%d_test_score" % cv_i for cv_i in range(n_splits)
        )
        assert all(np.isin(expected_keys, result_keys))

        cv = StratifiedKFold(n_splits=n_splits)
        n_splits = grid_search.n_splits_
        for candidate_i, C in enumerate(Cs):
            clf.set_params(C=C)
            cv_scores = np.array(
                [
                    grid_search.cv_results_["split%d_test_score" % s][candidate_i]
                    for s in range(n_splits)
                ]
            )
            for i, (train, test) in enumerate(cv.split(X, y)):
                clf.fit(X[train], y[train])
                if score == "f1":
                    correct_score = f1_score(y[test], clf.predict(X[test]))
                elif score == "roc_auc":
                    dec = clf.decision_function(X[test])
                    correct_score = roc_auc_score(y[test], dec)
                assert_almost_equal(correct_score, cv_scores[i])


def test_pickle():
    # Test that a fit search can be pickled
    clf = MockClassifier()
    grid_search = GridSearchCV(clf, {"foo_param": [1, 2, 3]}, refit=True, cv=2)
    grid_search.fit(X, y)
    grid_search_pickled = pickle.loads(pickle.dumps(grid_search))
    assert_array_almost_equal(grid_search.predict(X), grid_search_pickled.predict(X))

    random_search = RandomizedSearchCV(
        clf, {"foo_param": [1, 2, 3]}, refit=True, n_iter=3, cv=2
    )
    random_search.fit(X, y)
    random_search_pickled = pickle.loads(pickle.dumps(random_search))
    assert_array_almost_equal(
        random_search.predict(X), random_search_pickled.predict(X)
    )


def test_grid_search_with_multioutput_data():
    # Test search with multi-output estimator

    X, y = make_multilabel_classification(return_indicator=True, random_state=0)

    est_parameters = {"max_depth": [1, 2, 3, 4]}
    cv = KFold()

    estimators = [
        DecisionTreeRegressor(random_state=0),
        DecisionTreeClassifier(random_state=0),
    ]

    # Test with grid search cv
    for est in estimators:
        grid_search = GridSearchCV(est, est_parameters, cv=cv)
        grid_search.fit(X, y)
        res_params = grid_search.cv_results_["params"]
        for cand_i in range(len(res_params)):
            est.set_params(**res_params[cand_i])

            for i, (train, test) in enumerate(cv.split(X, y)):
                est.fit(X[train], y[train])
                correct_score = est.score(X[test], y[test])
                assert_almost_equal(
                    correct_score,
                    grid_search.cv_results_["split%d_test_score" % i][cand_i],
                )

    # Test with a randomized search
    for est in estimators:
        random_search = RandomizedSearchCV(est, est_parameters, cv=cv, n_iter=3)
        random_search.fit(X, y)
        res_params = random_search.cv_results_["params"]
        for cand_i in range(len(res_params)):
            est.set_params(**res_params[cand_i])

            for i, (train, test) in enumerate(cv.split(X, y)):
                est.fit(X[train], y[train])
                correct_score = est.score(X[test], y[test])
                assert_almost_equal(
                    correct_score,
                    random_search.cv_results_["split%d_test_score" % i][cand_i],
                )


def test_predict_proba_disabled():
    # Test predict_proba when disabled on estimator.
    X = np.arange(20).reshape(5, -1)
    y = [0, 0, 1, 1, 1]
    clf = SVC()
    gs = GridSearchCV(clf, {}, cv=2).fit(X, y)
    assert not hasattr(gs, "predict_proba")


def test_grid_search_allows_nans():
    # Test GridSearchCV with SimpleImputer
    X = np.arange(20, dtype=np.float64).reshape(5, -1)
    X[2, :] = np.nan
    y = [0, 0, 1, 1, 1]
    p = Pipeline(
        [
            ("imputer", SimpleImputer(strategy="mean", missing_values=np.nan)),
            ("classifier", MockClassifier()),
        ]
    )
    GridSearchCV(p, {"classifier__foo_param": [1, 2, 3]}, cv=2).fit(X, y)


class FailingClassifier(BaseEstimator):
    """Classifier that raises a ValueError on fit()"""

    FAILING_PARAMETER = 2

    def __init__(self, parameter=None):
        self.parameter = parameter

    def fit(self, X, y=None):
        if self.parameter == FailingClassifier.FAILING_PARAMETER:
            raise ValueError("Failing classifier failed as required")

    def predict(self, X):
        return np.zeros(X.shape[0])

    def score(self, X=None, Y=None):
        return 0.0


def test_grid_search_failing_classifier():
    # GridSearchCV with on_error != 'raise'
    # Ensures that a warning is raised and score reset where appropriate.

    X, y = make_classification(n_samples=20, n_features=10, random_state=0)

    clf = FailingClassifier()

    # refit=False because we only want to check that errors caused by fits
    # to individual folds will be caught and warnings raised instead. If
    # refit was done, then an exception would be raised on refit and not
    # caught by grid_search (expected behavior), and this would cause an
    # error in this test.
    gs = GridSearchCV(
        clf,
        [{"parameter": [0, 1, 2]}],
        scoring="accuracy",
        refit=False,
        error_score=0.0,
    )

    warning_message = re.compile(
        "5 fits failed.+total of 15.+The score on these"
        r" train-test partitions for these parameters will be set to 0\.0.+"
        "5 fits failed with the following error.+ValueError.+Failing classifier failed"
        " as required",
        flags=re.DOTALL,
    )
    with pytest.warns(FitFailedWarning, match=warning_message):
        gs.fit(X, y)
    n_candidates = len(gs.cv_results_["params"])

    # Ensure that grid scores were set to zero as required for those fits
    # that are expected to fail.
    def get_cand_scores(i):
        return np.array(
            [gs.cv_results_["split%d_test_score" % s][i] for s in range(gs.n_splits_)]
        )

    assert all(
        (
            np.all(get_cand_scores(cand_i) == 0.0)
            for cand_i in range(n_candidates)
            if gs.cv_results_["param_parameter"][cand_i]
            == FailingClassifier.FAILING_PARAMETER
        )
    )

    gs = GridSearchCV(
        clf,
        [{"parameter": [0, 1, 2]}],
        scoring="accuracy",
        refit=False,
        error_score=float("nan"),
    )
    warning_message = re.compile(
        "5 fits failed.+total of 15.+The score on these"
        r" train-test partitions for these parameters will be set to nan.+"
        "5 fits failed with the following error.+ValueError.+Failing classifier failed"
        " as required",
        flags=re.DOTALL,
    )
    with pytest.warns(FitFailedWarning, match=warning_message):
        gs.fit(X, y)
    n_candidates = len(gs.cv_results_["params"])
    assert all(
        np.all(np.isnan(get_cand_scores(cand_i)))
        for cand_i in range(n_candidates)
        if gs.cv_results_["param_parameter"][cand_i]
        == FailingClassifier.FAILING_PARAMETER
    )

    ranks = gs.cv_results_["rank_test_score"]

    # Check that succeeded estimators have lower ranks
    assert ranks[0] <= 2 and ranks[1] <= 2
    # Check that failed estimator has the highest rank
    assert ranks[clf.FAILING_PARAMETER] == 3
    assert gs.best_index_ != clf.FAILING_PARAMETER


def test_grid_search_classifier_all_fits_fail():
    X, y = make_classification(n_samples=20, n_features=10, random_state=0)

    clf = FailingClassifier()

    gs = GridSearchCV(
        clf,
        [{"parameter": [FailingClassifier.FAILING_PARAMETER] * 3}],
        error_score=0.0,
    )

    warning_message = re.compile(
        (
            "All the 15 fits failed.+15 fits failed with the following"
            " error.+ValueError.+Failing classifier failed as required"
        ),
        flags=re.DOTALL,
    )
    with pytest.raises(ValueError, match=warning_message):
        gs.fit(X, y)


def test_grid_search_failing_classifier_raise():
    # GridSearchCV with on_error == 'raise' raises the error

    X, y = make_classification(n_samples=20, n_features=10, random_state=0)

    clf = FailingClassifier()

    # refit=False because we want to test the behaviour of the grid search part
    gs = GridSearchCV(
        clf,
        [{"parameter": [0, 1, 2]}],
        scoring="accuracy",
        refit=False,
        error_score="raise",
    )

    # FailingClassifier issues a ValueError so this is what we look for.
    with pytest.raises(ValueError):
        gs.fit(X, y)


def test_parameters_sampler_replacement():
    # raise warning if n_iter is bigger than total parameter space
    params = [
        {"first": [0, 1], "second": ["a", "b", "c"]},
        {"third": ["two", "values"]},
    ]
    sampler = ParameterSampler(params, n_iter=9)
    n_iter = 9
    grid_size = 8
    expected_warning = (
        "The total space of parameters %d is smaller "
        "than n_iter=%d. Running %d iterations. For "
        "exhaustive searches, use GridSearchCV." % (grid_size, n_iter, grid_size)
    )
    with pytest.warns(UserWarning, match=expected_warning):
        list(sampler)

    # degenerates to GridSearchCV if n_iter the same as grid_size
    sampler = ParameterSampler(params, n_iter=8)
    samples = list(sampler)
    assert len(samples) == 8
    for values in ParameterGrid(params):
        assert values in samples
    assert len(ParameterSampler(params, n_iter=1000)) == 8

    # test sampling without replacement in a large grid
    params = {"a": range(10), "b": range(10), "c": range(10)}
    sampler = ParameterSampler(params, n_iter=99, random_state=42)
    samples = list(sampler)
    assert len(samples) == 99
    hashable_samples = ["a%db%dc%d" % (p["a"], p["b"], p["c"]) for p in samples]
    assert len(set(hashable_samples)) == 99

    # doesn't go into infinite loops
    params_distribution = {"first": bernoulli(0.5), "second": ["a", "b", "c"]}
    sampler = ParameterSampler(params_distribution, n_iter=7)
    samples = list(sampler)
    assert len(samples) == 7


def test_stochastic_gradient_loss_param():
    # Make sure the predict_proba works when loss is specified
    # as one of the parameters in the param_grid.
    param_grid = {
        "loss": ["log_loss"],
    }
    X = np.arange(24).reshape(6, -1)
    y = [0, 0, 0, 1, 1, 1]
    clf = GridSearchCV(
        estimator=SGDClassifier(loss="hinge"), param_grid=param_grid, cv=3
    )

    # When the estimator is not fitted, `predict_proba` is not available as the
    # loss is 'hinge'.
    assert not hasattr(clf, "predict_proba")
    clf.fit(X, y)
    clf.predict_proba(X)
    clf.predict_log_proba(X)

    # Make sure `predict_proba` is not available when setting loss=['hinge']
    # in param_grid
    param_grid = {
        "loss": ["hinge"],
    }
    clf = GridSearchCV(
        estimator=SGDClassifier(loss="hinge"), param_grid=param_grid, cv=3
    )
    assert not hasattr(clf, "predict_proba")
    clf.fit(X, y)
    assert not hasattr(clf, "predict_proba")


def test_search_train_scores_set_to_false():
    X = np.arange(6).reshape(6, -1)
    y = [0, 0, 0, 1, 1, 1]
    clf = LinearSVC(random_state=0)

    gs = GridSearchCV(clf, param_grid={"C": [0.1, 0.2]}, cv=3)
    gs.fit(X, y)


def test_grid_search_cv_splits_consistency():
    # Check if a one time iterable is accepted as a cv parameter.
    n_samples = 100
    n_splits = 5
    X, y = make_classification(n_samples=n_samples, random_state=0)

    gs = GridSearchCV(
        LinearSVC(random_state=0),
        param_grid={"C": [0.1, 0.2, 0.3]},
        cv=OneTimeSplitter(n_splits=n_splits, n_samples=n_samples),
        return_train_score=True,
    )
    gs.fit(X, y)

    gs2 = GridSearchCV(
        LinearSVC(random_state=0),
        param_grid={"C": [0.1, 0.2, 0.3]},
        cv=KFold(n_splits=n_splits),
        return_train_score=True,
    )
    gs2.fit(X, y)

    # Give generator as a cv parameter
    assert isinstance(
        KFold(n_splits=n_splits, shuffle=True, random_state=0).split(X, y),
        GeneratorType,
    )
    gs3 = GridSearchCV(
        LinearSVC(random_state=0),
        param_grid={"C": [0.1, 0.2, 0.3]},
        cv=KFold(n_splits=n_splits, shuffle=True, random_state=0).split(X, y),
        return_train_score=True,
    )
    gs3.fit(X, y)

    gs4 = GridSearchCV(
        LinearSVC(random_state=0),
        param_grid={"C": [0.1, 0.2, 0.3]},
        cv=KFold(n_splits=n_splits, shuffle=True, random_state=0),
        return_train_score=True,
    )
    gs4.fit(X, y)

    def _pop_time_keys(cv_results):
        for key in (
            "mean_fit_time",
            "std_fit_time",
            "mean_score_time",
            "std_score_time",
        ):
            cv_results.pop(key)
        return cv_results

    # Check if generators are supported as cv and
    # that the splits are consistent
    np.testing.assert_equal(
        _pop_time_keys(gs3.cv_results_), _pop_time_keys(gs4.cv_results_)
    )

    # OneTimeSplitter is a non-re-entrant cv where split can be called only
    # once if ``cv.split`` is called once per param setting in GridSearchCV.fit
    # the 2nd and 3rd parameter will not be evaluated as no train/test indices
    # will be generated for the 2nd and subsequent cv.split calls.
    # This is a check to make sure cv.split is not called once per param
    # setting.
    np.testing.assert_equal(
        {k: v for k, v in gs.cv_results_.items() if not k.endswith("_time")},
        {k: v for k, v in gs2.cv_results_.items() if not k.endswith("_time")},
    )

    # Check consistency of folds across the parameters
    gs = GridSearchCV(
        LinearSVC(random_state=0),
        param_grid={"C": [0.1, 0.1, 0.2, 0.2]},
        cv=KFold(n_splits=n_splits, shuffle=True),
        return_train_score=True,
    )
    gs.fit(X, y)

    # As the first two param settings (C=0.1) and the next two param
    # settings (C=0.2) are same, the test and train scores must also be
    # same as long as the same train/test indices are generated for all
    # the cv splits, for both param setting
    for score_type in ("train", "test"):
        per_param_scores = {}
        for param_i in range(4):
            per_param_scores[param_i] = [
                gs.cv_results_["split%d_%s_score" % (s, score_type)][param_i]
                for s in range(5)
            ]

        assert_array_almost_equal(per_param_scores[0], per_param_scores[1])
        assert_array_almost_equal(per_param_scores[2], per_param_scores[3])


def test_transform_inverse_transform_round_trip():
    clf = MockClassifier()
    grid_search = GridSearchCV(clf, {"foo_param": [1, 2, 3]}, cv=2, verbose=3)

    grid_search.fit(X, y)
    X_round_trip = grid_search.inverse_transform(grid_search.transform(X))
    assert_array_equal(X, X_round_trip)


def test_custom_run_search():
    def check_results(results, gscv):
        exp_results = gscv.cv_results_
        assert sorted(results.keys()) == sorted(exp_results)
        for k in results:
            if not k.endswith("_time"):
                # XXX: results['params'] is a list :|
                results[k] = np.asanyarray(results[k])
                if results[k].dtype.kind == "O":
                    assert_array_equal(
                        exp_results[k], results[k], err_msg="Checking " + k
                    )
                else:
                    assert_allclose(exp_results[k], results[k], err_msg="Checking " + k)

    def fit_grid(param_grid):
        return GridSearchCV(clf, param_grid, return_train_score=True).fit(X, y)

    class CustomSearchCV(BaseSearchCV):
        def __init__(self, estimator, **kwargs):
            super().__init__(estimator, **kwargs)

        def _run_search(self, evaluate):
            results = evaluate([{"max_depth": 1}, {"max_depth": 2}])
            check_results(results, fit_grid({"max_depth": [1, 2]}))
            results = evaluate([{"min_samples_split": 5}, {"min_samples_split": 10}])
            check_results(
                results,
                fit_grid([{"max_depth": [1, 2]}, {"min_samples_split": [5, 10]}]),
            )

    # Using regressor to make sure each score differs
    clf = DecisionTreeRegressor(random_state=0)
    X, y = make_classification(n_samples=100, n_informative=4, random_state=0)
    mycv = CustomSearchCV(clf, return_train_score=True).fit(X, y)
    gscv = fit_grid([{"max_depth": [1, 2]}, {"min_samples_split": [5, 10]}])

    results = mycv.cv_results_
    check_results(results, gscv)
    for attr in dir(gscv):
        if (
            attr[0].islower()
            and attr[-1:] == "_"
            and attr
            not in {
                "cv_results_",
                "best_estimator_",
                "refit_time_",
                "classes_",
                "scorer_",
            }
        ):
            assert getattr(gscv, attr) == getattr(mycv, attr), (
                "Attribute %s not equal" % attr
            )


def test__custom_fit_no_run_search():
    class NoRunSearchSearchCV(BaseSearchCV):
        def __init__(self, estimator, **kwargs):
            super().__init__(estimator, **kwargs)

        def fit(self, X, y=None, groups=None, **fit_params):
            return self

    # this should not raise any exceptions
    NoRunSearchSearchCV(SVC()).fit(X, y)

    class BadSearchCV(BaseSearchCV):
        def __init__(self, estimator, **kwargs):
            super().__init__(estimator, **kwargs)

    with pytest.raises(NotImplementedError, match="_run_search not implemented."):
        # this should raise a NotImplementedError
        BadSearchCV(SVC(), cv=KFold(n_splits=2)).fit(X, y)


# TODO: remove mark once loky bug is fixed:
# https://github.com/joblib/loky/issues/458
@pytest.mark.thread_unsafe
def test_empty_cv_iterator_error():
    # Use global X, y

    # create cv
    cv = KFold(n_splits=3).split(X)

    # pop all of it, this should cause the expected ValueError
    [u for u in cv]
    # cv is empty now

    train_size = 100
    ridge = RandomizedSearchCV(Ridge(), {"alpha": [1e-3, 1e-2, 1e-1]}, cv=cv, n_jobs=4)

    # assert that this raises an error
    with pytest.raises(
        ValueError,
        match=(
            "No fits were performed. "
            "Was the CV iterator empty\\? "
            "Were there no candidates\\?"
        ),
    ):
        ridge.fit(X[:train_size], y[:train_size])


# TODO: remove mark once loky bug is fixed:
# https://github.com/joblib/loky/issues/458
def test_random_search_bad_cv():
    # Use global X, y

    class BrokenKFold(KFold):
        def get_n_splits(self, *args, **kw):
            return 1

    # create bad cv
    cv = BrokenKFold(n_splits=3)

    train_size = 100
    ridge = RandomizedSearchCV(Ridge(), {"alpha": [1e-3, 1e-2, 1e-1]}, cv=cv, n_jobs=4)

    # assert that this raises an error
    with pytest.raises(
        ValueError,
        match=(
            "cv.split and cv.get_n_splits return "
            "inconsistent results. Expected \\d+ "
            "splits, got \\d+"
        ),
    ):
        ridge.fit(X[:train_size], y[:train_size])


@pytest.mark.parametrize("return_train_score", [False, True])
@pytest.mark.parametrize(
    "SearchCV, specialized_params",
    [
        (GridSearchCV, {"param_grid": {"max_depth": [2, 3, 5, 8]}}),
        (
            RandomizedSearchCV,
            {"param_distributions": {"max_depth": [2, 3, 5, 8]}, "n_iter": 4},
        ),
    ],
)
def test_searchcv_raise_warning_with_non_finite_score(
    SearchCV, specialized_params, return_train_score
):
    # Non-regression test for:
    # https://github.com/scikit-learn/scikit-learn/issues/10529
    # Check that we raise a UserWarning when a non-finite score is
    # computed in the SearchCV
    X, y = make_classification(n_classes=2, random_state=0)

    class FailingScorer:
        """Scorer that will fail for some split but not all."""

        def __init__(self):
            self.n_counts = 0

        def __call__(self, estimator, X, y):
            self.n_counts += 1
            if self.n_counts % 5 == 0:
                return np.nan
            return 1

    grid = SearchCV(
        DecisionTreeClassifier(),
        scoring=FailingScorer(),
        cv=3,
        return_train_score=return_train_score,
        **specialized_params,
    )

    with pytest.warns(UserWarning) as warn_msg:
        grid.fit(X, y)

    set_with_warning = ["test", "train"] if return_train_score else ["test"]
    assert len(warn_msg) == len(set_with_warning)
    for msg, dataset in zip(warn_msg, set_with_warning):
        assert f"One or more of the {dataset} scores are non-finite" in str(msg.message)

    # all non-finite scores should be equally ranked last
    last_rank = grid.cv_results_["rank_test_score"].max()
    non_finite_mask = np.isnan(grid.cv_results_["mean_test_score"])
    assert_array_equal(grid.cv_results_["rank_test_score"][non_finite_mask], last_rank)
    # all finite scores should be better ranked than the non-finite scores
    assert np.all(grid.cv_results_["rank_test_score"][~non_finite_mask] < last_rank)


def test_callable_multimetric_confusion_matrix():
    # Test callable with many metrics inserts the correct names and metrics
    # into the search cv object
    def custom_scorer(clf, X, y):
        y_pred = clf.predict(X)
        cm = confusion_matrix(y, y_pred)
        return {"tn": cm[0, 0], "fp": cm[0, 1], "fn": cm[1, 0], "tp": cm[1, 1]}

    X, y = make_classification(n_samples=40, n_features=4, random_state=42)
    est = LinearSVC(random_state=42)
    search = GridSearchCV(est, {"C": [0.1, 1]}, scoring=custom_scorer, refit="fp")

    search.fit(X, y)

    score_names = ["tn", "fp", "fn", "tp"]
    for name in score_names:
        assert "mean_test_{}".format(name) in search.cv_results_

    y_pred = search.predict(X)
    cm = confusion_matrix(y, y_pred)
    assert search.score(X, y) == pytest.approx(cm[0, 1])


def test_callable_multimetric_same_as_list_of_strings():
    # Test callable multimetric is the same as a list of strings
    def custom_scorer(est, X, y):
        y_pred = est.predict(X)
        return {
            "recall": recall_score(y, y_pred),
            "accuracy": accuracy_score(y, y_pred),
        }

    X, y = make_classification(n_samples=40, n_features=4, random_state=42)
    est = LinearSVC(random_state=42)
    search_callable = GridSearchCV(
        est, {"C": [0.1, 1]}, scoring=custom_scorer, refit="recall"
    )
    search_str = GridSearchCV(
        est, {"C": [0.1, 1]}, scoring=["recall", "accuracy"], refit="recall"
    )

    search_callable.fit(X, y)
    search_str.fit(X, y)

    assert search_callable.best_score_ == pytest.approx(search_str.best_score_)
    assert search_callable.best_index_ == search_str.best_index_
    assert search_callable.score(X, y) == pytest.approx(search_str.score(X, y))


def test_callable_single_metric_same_as_single_string():
    # Tests callable scorer is the same as scoring with a single string
    def custom_scorer(est, X, y):
        y_pred = est.predict(X)
        return recall_score(y, y_pred)

    X, y = make_classification(n_samples=40, n_features=4, random_state=42)
    est = LinearSVC(random_state=42)
    search_callable = GridSearchCV(
        est, {"C": [0.1, 1]}, scoring=custom_scorer, refit=True
    )
    search_str = GridSearchCV(est, {"C": [0.1, 1]}, scoring="recall", refit="recall")
    search_list_str = GridSearchCV(
        est, {"C": [0.1, 1]}, scoring=["recall"], refit="recall"
    )
    search_callable.fit(X, y)
    search_str.fit(X, y)
    search_list_str.fit(X, y)

    assert search_callable.best_score_ == pytest.approx(search_str.best_score_)
    assert search_callable.best_index_ == search_str.best_index_
    assert search_callable.score(X, y) == pytest.approx(search_str.score(X, y))

    assert search_list_str.best_score_ == pytest.approx(search_str.best_score_)
    assert search_list_str.best_index_ == search_str.best_index_
    assert search_list_str.score(X, y) == pytest.approx(search_str.score(X, y))


def test_callable_multimetric_error_on_invalid_key():
    # Raises when the callable scorer does not return a dict with `refit` key.
    def bad_scorer(est, X, y):
        return {"bad_name": 1}

    X, y = make_classification(n_samples=40, n_features=4, random_state=42)
    clf = GridSearchCV(
        LinearSVC(random_state=42),
        {"C": [0.1, 1]},
        scoring=bad_scorer,
        refit="good_name",
    )

    msg = (
        "For multi-metric scoring, the parameter refit must be set to a "
        "scorer key or a callable to refit"
    )
    with pytest.raises(ValueError, match=msg):
        clf.fit(X, y)


def test_callable_multimetric_error_failing_clf():
    # Warns when there is an estimator the fails to fit with a float
    # error_score
    def custom_scorer(est, X, y):
        return {"acc": 1}

    X, y = make_classification(n_samples=20, n_features=10, random_state=0)

    clf = FailingClassifier()
    gs = GridSearchCV(
        clf,
        [{"parameter": [0, 1, 2]}],
        scoring=custom_scorer,
        refit=False,
        error_score=0.1,
    )

    warning_message = re.compile(
        "5 fits failed.+total of 15.+The score on these"
        r" train-test partitions for these parameters will be set to 0\.1",
        flags=re.DOTALL,
    )
    with pytest.warns(FitFailedWarning, match=warning_message):
        gs.fit(X, y)

    assert_allclose(gs.cv_results_["mean_test_acc"], [1, 1, 0.1])


def test_callable_multimetric_clf_all_fits_fail():
    # Warns and raises when all estimator fails to fit.
    def custom_scorer(est, X, y):
        return {"acc": 1}

    X, y = make_classification(n_samples=20, n_features=10, random_state=0)

    clf = FailingClassifier()

    gs = GridSearchCV(
        clf,
        [{"parameter": [FailingClassifier.FAILING_PARAMETER] * 3}],
        scoring=custom_scorer,
        refit=False,
        error_score=0.1,
    )

    individual_fit_error_message = "ValueError: Failing classifier failed as required"
    error_message = re.compile(
        (
            "All the 15 fits failed.+your model is misconfigured.+"
            f"{individual_fit_error_message}"
        ),
        flags=re.DOTALL,
    )

    with pytest.raises(ValueError, match=error_message):
        gs.fit(X, y)


def test_n_features_in():
    # make sure grid search and random search delegate n_features_in to the
    # best estimator
    n_features = 4
    X, y = make_classification(n_features=n_features)
    gbdt = HistGradientBoostingClassifier()
    param_grid = {"max_iter": [3, 4]}
    gs = GridSearchCV(gbdt, param_grid)
    rs = RandomizedSearchCV(gbdt, param_grid, n_iter=1)
    assert not hasattr(gs, "n_features_in_")
    assert not hasattr(rs, "n_features_in_")
    gs.fit(X, y)
    rs.fit(X, y)
    assert gs.n_features_in_ == n_features
    assert rs.n_features_in_ == n_features


@pytest.mark.parametrize("pairwise", [True, False])
def test_search_cv_pairwise_property_delegated_to_base_estimator(pairwise):
    """
    Test implementation of BaseSearchCV has the pairwise tag
    which matches the pairwise tag of its estimator.
    This test make sure pairwise tag is delegated to the base estimator.

    Non-regression test for issue #13920.
    """

    class TestEstimator(BaseEstimator):
        def __sklearn_tags__(self):
            tags = super().__sklearn_tags__()
            tags.input_tags.pairwise = pairwise
            return tags

    est = TestEstimator()
    attr_message = "BaseSearchCV pairwise tag must match estimator"
    cv = GridSearchCV(est, {"n_neighbors": [10]})
    assert pairwise == cv.__sklearn_tags__().input_tags.pairwise, attr_message


def test_search_cv__pairwise_property_delegated_to_base_estimator():
    """
    Test implementation of BaseSearchCV has the pairwise property
    which matches the pairwise tag of its estimator.
    This test make sure pairwise tag is delegated to the base estimator.

    Non-regression test for issue #13920.
    """

    class EstimatorPairwise(BaseEstimator):
        def __init__(self, pairwise=True):
            self.pairwise = pairwise

        def __sklearn_tags__(self):
            tags = super().__sklearn_tags__()
            tags.input_tags.pairwise = self.pairwise
            return tags

    est = EstimatorPairwise()
    attr_message = "BaseSearchCV _pairwise property must match estimator"

    for _pairwise_setting in [True, False]:
        est.set_params(pairwise=_pairwise_setting)
        cv = GridSearchCV(est, {"n_neighbors": [10]})
        assert _pairwise_setting == cv.__sklearn_tags__().input_tags.pairwise, (
            attr_message
        )


def test_search_cv_pairwise_property_equivalence_of_precomputed():
    """
    Test implementation of BaseSearchCV has the pairwise tag
    which matches the pairwise tag of its estimator.
    This test ensures the equivalence of 'precomputed'.

    Non-regression test for issue #13920.
    """
    n_samples = 50
    n_splits = 2
    X, y = make_classification(n_samples=n_samples, random_state=0)
    grid_params = {"n_neighbors": [10]}

    # defaults to euclidean metric (minkowski p = 2)
    clf = KNeighborsClassifier()
    cv = GridSearchCV(clf, grid_params, cv=n_splits)
    cv.fit(X, y)
    preds_original = cv.predict(X)

    # precompute euclidean metric to validate pairwise is working
    X_precomputed = euclidean_distances(X)
    clf = KNeighborsClassifier(metric="precomputed")
    cv = GridSearchCV(clf, grid_params, cv=n_splits)
    cv.fit(X_precomputed, y)
    preds_precomputed = cv.predict(X_precomputed)

    attr_message = "GridSearchCV not identical with precomputed metric"
    assert (preds_original == preds_precomputed).all(), attr_message


@pytest.mark.parametrize(
    "SearchCV, param_search",
    [(GridSearchCV, {"a": [0.1, 0.01]}), (RandomizedSearchCV, {"a": uniform(1, 3)})],
)
def test_scalar_fit_param(SearchCV, param_search):
    # unofficially sanctioned tolerance for scalar values in fit_params
    # non-regression test for:
    # https://github.com/scikit-learn/scikit-learn/issues/15805
    class TestEstimator(ClassifierMixin, BaseEstimator):
        def __init__(self, a=None):
            self.a = a

        def fit(self, X, y, r=None):
            self.r_ = r

        def predict(self, X):
            return np.zeros(shape=(len(X)))

    model = SearchCV(TestEstimator(), param_search)
    X, y = make_classification(random_state=42)
    model.fit(X, y, r=42)
    assert model.best_estimator_.r_ == 42


@pytest.mark.parametrize(
    "SearchCV, param_search",
    [
        (GridSearchCV, {"alpha": [0.1, 0.01]}),
        (RandomizedSearchCV, {"alpha": uniform(0.01, 0.1)}),
    ],
)
def test_scalar_fit_param_compat(SearchCV, param_search):
    # check support for scalar values in fit_params, for instance in LightGBM
    # that do not exactly respect the scikit-learn API contract but that we do
    # not want to break without an explicit deprecation cycle and API
    # recommendations for implementing early stopping with a user provided
    # validation set. non-regression test for:
    # https://github.com/scikit-learn/scikit-learn/issues/15805
    X_train, X_valid, y_train, y_valid = train_test_split(
        *make_classification(random_state=42), random_state=42
    )

    class _FitParamClassifier(SGDClassifier):
        def fit(
            self,
            X,
            y,
            sample_weight=None,
            tuple_of_arrays=None,
            scalar_param=None,
            callable_param=None,
        ):
            super().fit(X, y, sample_weight=sample_weight)
            assert scalar_param > 0
            assert callable(callable_param)

            # The tuple of arrays should be preserved as tuple.
            assert isinstance(tuple_of_arrays, tuple)
            assert tuple_of_arrays[0].ndim == 2
            assert tuple_of_arrays[1].ndim == 1
            return self

    def _fit_param_callable():
        pass

    model = SearchCV(_FitParamClassifier(), param_search)

    # NOTE: `fit_params` should be data dependent (e.g. `sample_weight`) which
    # is not the case for the following parameters. But this abuse is common in
    # popular third-party libraries and we should tolerate this behavior for
    # now and be careful not to break support for those without following
    # proper deprecation cycle.
    fit_params = {
        "tuple_of_arrays": (X_valid, y_valid),
        "callable_param": _fit_param_callable,
        "scalar_param": 42,
    }
    model.fit(X_train, y_train, **fit_params)


# FIXME: Replace this test with a full `check_estimator` once we have API only
# checks.
@pytest.mark.filterwarnings("ignore:The total space of parameters 4 is")
@pytest.mark.parametrize("SearchCV", [GridSearchCV, RandomizedSearchCV])
@pytest.mark.parametrize("Predictor", [MinimalRegressor, MinimalClassifier])
def test_search_cv_using_minimal_compatible_estimator(SearchCV, Predictor):
    # Check that third-party library can run tests without inheriting from
    # BaseEstimator.
    rng = np.random.RandomState(0)
    X, y = rng.randn(25, 2), np.array([0] * 5 + [1] * 20)

    model = Pipeline(
        [("transformer", MinimalTransformer()), ("predictor", Predictor())]
    )

    params = {
        "transformer__param": [1, 10],
        "predictor__parama": [1, 10],
    }
    search = SearchCV(model, params, error_score="raise")
    search.fit(X, y)

    assert search.best_params_.keys() == params.keys()

    y_pred = search.predict(X)
    if is_classifier(search):
        assert_array_equal(y_pred, 1)
        assert search.score(X, y) == pytest.approx(accuracy_score(y, y_pred))
    else:
        assert_allclose(y_pred, y.mean())
        assert search.score(X, y) == pytest.approx(r2_score(y, y_pred))


@pytest.mark.parametrize("return_train_score", [True, False])
def test_search_cv_verbose_3(capsys, return_train_score):
    """Check that search cv with verbose>2 shows the score for single
    metrics. non-regression test for #19658."""
    X, y = make_classification(n_samples=100, n_classes=2, flip_y=0.2, random_state=0)
    clf = LinearSVC(random_state=0)
    grid = {"C": [0.1]}

    GridSearchCV(
        clf,
        grid,
        scoring="accuracy",
        verbose=3,
        cv=3,
        return_train_score=return_train_score,
    ).fit(X, y)
    captured = capsys.readouterr().out
    if return_train_score:
        match = re.findall(r"score=\(train=[\d\.]+, test=[\d.]+\)", captured)
    else:
        match = re.findall(r"score=[\d\.]+", captured)
    assert len(match) == 3


@pytest.mark.parametrize(
    "SearchCV, param_search",
    [
        (GridSearchCV, "param_grid"),
        (RandomizedSearchCV, "param_distributions"),
        (HalvingGridSearchCV, "param_grid"),
    ],
)
def test_search_estimator_param(SearchCV, param_search):
    # test that SearchCV object doesn't change the object given in the parameter grid
    X, y = make_classification(random_state=42)

    params = {"clf": [LinearSVC()], "clf__C": [0.01]}
    orig_C = params["clf"][0].C

    pipe = Pipeline([("trs", MinimalTransformer()), ("clf", None)])

    param_grid_search = {param_search: params}
    gs = SearchCV(pipe, refit=True, cv=2, scoring="accuracy", **param_grid_search).fit(
        X, y
    )

    # testing that the original object in params is not changed
    assert params["clf"][0].C == orig_C
    # testing that the GS is setting the parameter of the step correctly
    assert gs.best_estimator_.named_steps["clf"].C == 0.01


# TODO: remove mark once loky bug is fixed:
# https://github.com/joblib/loky/issues/458
@pytest.mark.thread_unsafe
def test_search_with_2d_array():
    parameter_grid = {
        "vect__ngram_range": ((1, 1), (1, 2)),  # unigrams or bigrams
        "vect__norm": ("l1", "l2"),
    }
    pipeline = Pipeline(
        [
            ("vect", TfidfVectorizer()),
            ("clf", ComplementNB()),
        ]
    )
    random_search = RandomizedSearchCV(
        estimator=pipeline,
        param_distributions=parameter_grid,
        n_iter=3,
        random_state=0,
        n_jobs=2,
        verbose=1,
        cv=3,
    )
    data_train = ["one", "two", "three", "four", "five"]
    data_target = [0, 0, 1, 0, 1]
    random_search.fit(data_train, data_target)
    result = random_search.cv_results_["param_vect__ngram_range"]
    expected_data = np.empty(3, dtype=object)
    expected_data[:] = [(1, 2), (1, 2), (1, 1)]
    np.testing.assert_array_equal(result.data, expected_data)


def test_search_html_repr():
    """Test different HTML representations for GridSearchCV."""
    X, y = make_classification(random_state=42)

    pipeline = Pipeline([("scale", StandardScaler()), ("clf", DummyClassifier())])
    param_grid = {"clf": [DummyClassifier(), LogisticRegression()]}

    # Unfitted shows the original pipeline
    search_cv = GridSearchCV(pipeline, param_grid=param_grid, refit=False)
    with config_context(display="diagram"):
        repr_html = search_cv._repr_html_()
        assert "<div>DummyClassifier</div>" in repr_html

    # Fitted with `refit=False` shows the original pipeline
    search_cv.fit(X, y)
    with config_context(display="diagram"):
        repr_html = search_cv._repr_html_()
        assert "<div>DummyClassifier</div>" in repr_html

    # Fitted with `refit=True` shows the best estimator
    search_cv = GridSearchCV(pipeline, param_grid=param_grid, refit=True)
    search_cv.fit(X, y)
    with config_context(display="diagram"):
        repr_html = search_cv._repr_html_()
        assert "<div>DummyClassifier</div>" not in repr_html
        assert "<div>LogisticRegression</div>" in repr_html


# Metadata Routing Tests
# ======================


@pytest.mark.parametrize(
    "SearchCV, param_search",
    [
        (GridSearchCV, "param_grid"),
        (RandomizedSearchCV, "param_distributions"),
    ],
)
@config_context(enable_metadata_routing=True)
def test_multi_metric_search_forwards_metadata(SearchCV, param_search):
    """Test that *SearchCV forwards metadata correctly when passed multiple metrics."""
    X, y = make_classification(random_state=42)
    n_samples = _num_samples(X)
    rng = np.random.RandomState(0)
    score_weights = rng.rand(n_samples)
    score_metadata = rng.rand(n_samples)

    est = LinearSVC()
    param_grid_search = {param_search: {"C": [1]}}

    scorer_registry = _Registry()
    scorer = ConsumingScorer(registry=scorer_registry).set_score_request(
        sample_weight="score_weights", metadata="score_metadata"
    )
    scoring = dict(my_scorer=scorer, accuracy="accuracy")
    SearchCV(est, refit="accuracy", cv=2, scoring=scoring, **param_grid_search).fit(
        X, y, score_weights=score_weights, score_metadata=score_metadata
    )
    assert len(scorer_registry)
    for _scorer in scorer_registry:
        check_recorded_metadata(
            obj=_scorer,
            method="score",
            parent="_score",
            split_params=("sample_weight", "metadata"),
            sample_weight=score_weights,
            metadata=score_metadata,
        )


@pytest.mark.parametrize(
    "SearchCV, param_search",
    [
        (GridSearchCV, "param_grid"),
        (RandomizedSearchCV, "param_distributions"),
        (HalvingGridSearchCV, "param_grid"),
    ],
)
def test_score_rejects_params_with_no_routing_enabled(SearchCV, param_search):
    """*SearchCV should reject **params when metadata routing is not enabled
    since this is added only when routing is enabled."""
    X, y = make_classification(random_state=42)
    est = LinearSVC()
    param_grid_search = {param_search: {"C": [1]}}

    gs = SearchCV(est, cv=2, **param_grid_search).fit(X, y)

    with pytest.raises(ValueError, match="is only supported if"):
        gs.score(X, y, metadata=1)


# End of Metadata Routing Tests
# =============================


def test_cv_results_dtype_issue_29074():
    """Non-regression test for https://github.com/scikit-learn/scikit-learn/issues/29074"""

    class MetaEstimator(BaseEstimator, ClassifierMixin):
        def __init__(
            self,
            base_clf,
            parameter1=None,
            parameter2=None,
            parameter3=None,
            parameter4=None,
        ):
            self.base_clf = base_clf
            self.parameter1 = parameter1
            self.parameter2 = parameter2
            self.parameter3 = parameter3
            self.parameter4 = parameter4

        def fit(self, X, y=None):
            self.base_clf.fit(X, y)
            return self

        def score(self, X, y):
            return self.base_clf.score(X, y)

    # Values of param_grid are such that np.result_type gives slightly
    # different errors, in particular ValueError and TypeError
    param_grid = {
        "parameter1": [None, {"option": "A"}, {"option": "B"}],
        "parameter2": [None, [1, 2]],
        "parameter3": [{"a": 1}],
        "parameter4": ["str1", "str2"],
    }
    grid_search = GridSearchCV(
        estimator=MetaEstimator(LogisticRegression()),
        param_grid=param_grid,
        cv=3,
    )

    X, y = make_blobs(random_state=0)
    grid_search.fit(X, y)
    for param in param_grid:
        assert grid_search.cv_results_[f"param_{param}"].dtype == object


def test_search_with_estimators_issue_29157():
    """Check cv_results_ for estimators with a `dtype` parameter, e.g. OneHotEncoder."""
    pd = pytest.importorskip("pandas")
    df = pd.DataFrame(
        {
            "numeric_1": [1, 2, 3, 4, 5],
            "object_1": ["a", "a", "a", "a", "a"],
            "target": [1.0, 4.1, 2.0, 3.0, 1.0],
        }
    )
    X = df.drop("target", axis=1)
    y = df["target"]
    enc = ColumnTransformer(
        [("enc", OneHotEncoder(sparse_output=False), ["object_1"])],
        remainder="passthrough",
    )
    pipe = Pipeline(
        [
            ("enc", enc),
            ("regressor", LinearRegression()),
        ]
    )
    grid_params = {
        "enc__enc": [
            OneHotEncoder(sparse_output=False),
            OrdinalEncoder(),
        ]
    }
    grid_search = GridSearchCV(pipe, grid_params, cv=2)
    grid_search.fit(X, y)
    assert grid_search.cv_results_["param_enc__enc"].dtype == object


def test_cv_results_multi_size_array():
    """Check that GridSearchCV works with params that are arrays of different sizes.

    Non-regression test for #29277.
    """
    n_features = 10
    X, y = make_classification(n_features=10)

    spline_reg_pipe = make_pipeline(
        SplineTransformer(extrapolation="periodic"),
        LogisticRegression(),
    )

    n_knots_list = [n_features * i for i in [10, 11, 12]]
    knots_list = [
        np.linspace(0, np.pi * 2, n_knots).reshape((-1, n_features))
        for n_knots in n_knots_list
    ]
    spline_reg_pipe_cv = GridSearchCV(
        estimator=spline_reg_pipe,
        param_grid={
            "splinetransformer__knots": knots_list,
        },
    )

    spline_reg_pipe_cv.fit(X, y)
    assert (
        spline_reg_pipe_cv.cv_results_["param_splinetransformer__knots"].dtype == object
    )


@pytest.mark.parametrize(
    "array_namespace, device_name, dtype_name",
    yield_namespace_device_dtype_combinations(),
)
@pytest.mark.parametrize("SearchCV", [GridSearchCV, RandomizedSearchCV])
def test_array_api_search_cv_classifier(
    SearchCV, array_namespace, device_name, dtype_name
):
    # installed sklearn's helper takes (namespace, device); the dtype
    # argument of the branch this file was vendored from is gone
    xp, device = _array_api_for_tests(array_namespace, device_name)

    X = np.arange(100).reshape((10, 10))
    X_np = X.astype(dtype_name)
    X_xp = xp.asarray(X_np, device=device)

    # y should always be an integer, no matter what `dtype_name` is
    y_np = np.array([0] * 5 + [1] * 5)
    y_xp = xp.asarray(y_np, device=device)

    with config_context(array_api_dispatch=True):
        searcher = SearchCV(
            LinearDiscriminantAnalysis(),
            {"tol": [1e-2, 1e-3, 1e-4, 1e-5, 1e-6, 1e-7]},
            cv=2,
            error_score="raise",
        )
        searcher.fit(X_xp, y_xp)
        searcher.score(X_xp, y_xp)


# Construct these outside the tests so that the same object is used
# for both input and `expected`
one_hot_encoder = OneHotEncoder()
ordinal_encoder = OrdinalEncoder()

# If we construct this directly via `MaskedArray`, the list of tuples
# gets auto-converted to a 2D array.
ma_with_tuples = np.ma.MaskedArray(np.empty(2), mask=True, dtype=object)  # type: ignore[var-annotated]
ma_with_tuples[0] = (1, 2)
ma_with_tuples[1] = (3, 4)


@pytest.mark.parametrize(
    ("candidate_params", "expected"),
    [
        pytest.param(
            [{"foo": 1}, {"foo": 2}],
            [
                ("param_foo", np.ma.MaskedArray(np.array([1, 2]))),
            ],
            id="simple numeric, single param",
        ),
        pytest.param(
            [{"foo": 1, "bar": 3}, {"foo": 2, "bar": 4}, {"foo": 3}],
            [
                ("param_foo", np.ma.MaskedArray(np.array([1, 2, 3]))),
                (
                    "param_bar",
                    np.ma.MaskedArray(np.array([3, 4, 0]), mask=[False, False, True]),
                ),
            ],
            id="simple numeric, one param is missing in one round",
        ),
        pytest.param(
            [{"foo": [[1], [2], [3]]}, {"foo": [[1], [2]]}],
            [
                (
                    "param_foo",
                    np.ma.MaskedArray([[[1], [2], [3]], [[1], [2]]], dtype=object),
                ),
            ],
            id="lists of different lengths",
        ),
        pytest.param(
            [{"foo": (1, 2)}, {"foo": (3, 4)}],
            [
                (
                    "param_foo",
                    ma_with_tuples,
                ),
            ],
            id="lists tuples",
        ),
        pytest.param(
            [{"foo": ordinal_encoder}, {"foo": one_hot_encoder}],
            [
                (
                    "param_foo",
                    np.ma.MaskedArray([ordinal_encoder, one_hot_encoder], dtype=object),
                ),
            ],
            id="estimators",
        ),
    ],
)
def test_yield_masked_array_for_each_param(candidate_params, expected):
    result = list(_yield_masked_array_for_each_param(candidate_params))
    for (key, value), (expected_key, expected_value) in zip(result, expected):
        assert key == expected_key
        assert value.dtype == expected_value.dtype
        np.testing.assert_array_equal(value, expected_value)
        np.testing.assert_array_equal(value.mask, expected_value.mask)


def test_yield_masked_array_no_runtime_warning():
    # non-regression test for https://github.com/scikit-learn/scikit-learn/issues/29929
    candidate_params = [{"param": i} for i in range(1000)]
    with warnings.catch_warnings():
        warnings.simplefilter("error", RuntimeWarning)
        list(_yield_masked_array_for_each_param(candidate_params))


def _searchcv_callback_test_cases(estimator, scoring):
    return [
        GridSearchCV(estimator(), {"max_iter": [1, 2, 3]}, cv=2, scoring=scoring),
        RandomizedSearchCV(
            estimator(),
            {"max_iter": randint(1, 4)},
            cv=2,
            n_iter=3,
            scoring=scoring,
            random_state=42,
        ),
        HalvingGridSearchCV(
            estimator(),
            {"max_iter": [1, 2, 3]},
            cv=2,
            aggressive_elimination=True,
            scoring=scoring,
        ),
        HalvingRandomSearchCV(
            estimator(),
            {"max_iter": randint(1, 4)},
            cv=2,
            aggressive_elimination=True,
            scoring=scoring,
            random_state=42,
        ),
    ]


@pytest.mark.parametrize("refit", [True, False])
@pytest.mark.parametrize(
    "search",
    _searchcv_callback_test_cases(NoCallbackEstimator, "accuracy"),
)
@skip_callback_test_if_wasm
def test_search_callbacks_no_propagation(search, refit):
    """Check number of hook calls when the sub-estimator doesn't support callbacks."""
    callbacks = [RecordingCallback(), RecordingAutoPropagatedCallback()]
    search = clone(search).set_params(refit=refit).set_callbacks(*callbacks).fit(X, y)

    # defining expected values
    root = 1
    search_task = 1
    n_splits = search.n_splits_  # 2

    if "Halving" in search.__class__.__name__:
        n_halving_rounds = search.n_iterations_
        n_evaluations = sum(n_cand * n_splits for n_cand in search.n_candidates_)
        expected = root + (search_task + refit) + n_halving_rounds + n_evaluations
    else:  # GridSearchCV, RandomizedSearchCV
        n_candidates = 3
        n_evaluations = n_candidates * n_splits
        expected = root + (search_task + refit) + n_evaluations

    # we expect only the hooks from `search` called:
    for callback in callbacks:
        assert callback.count_hooks("setup") == 1
        assert callback.count_hooks("on_fit_task_begin") == expected
        assert callback.count_hooks("on_fit_task_end") == expected
        assert callback.count_hooks("teardown") == 1


@pytest.mark.parametrize("refit", [True, False])
@pytest.mark.parametrize(
    "search",
    _searchcv_callback_test_cases(MaxIterEstimator, "r2"),
)
@skip_callback_test_if_wasm
def test_search_callbacks_propagation(search, refit):
    """Check number of hook calls when the sub-estimator does support callbacks."""
    callbacks = [RecordingCallback(), RecordingAutoPropagatedCallback()]
    search = clone(search).set_params(refit=refit).set_callbacks(*callbacks).fit(X, y)

    # defining expected values
    root = 1
    search_task = 1
    n_splits = search.n_splits_  # 2

    if "Halving" in search.__class__.__name__:
        n_halving_rounds = search.n_iterations_
        n_evaluations = sum(n_cand * n_splits for n_cand in search.n_candidates_)
        searchcv_tasks = root + (search_task + refit) + n_halving_rounds + n_evaluations
    else:  # GridSearchCV, RandomizedSearchCV
        n_candidates = 3
        n_evaluations = n_candidates * n_splits
        searchcv_tasks = root + (search_task + refit) + n_evaluations

    for callback in callbacks:
        assert callback.count_hooks("setup") == 1
        if callback.__class__.__name__ == "RecordingCallback":
            # Without propagation we expect only hook calls from the *SearchCV class.
            assert callback.count_hooks("on_fit_task_begin") == searchcv_tasks
            assert callback.count_hooks("on_fit_task_end") == searchcv_tasks
        else:  # TestingAutoPropagatedCallback
            # With propagation we expect additional calls from each inner estimator.
            # Each MaxIterEstimator has 1 root + max_iter tasks, but we ignore the root
            # because it's the same as the evaluation leaf of the searchcv class.
            # There are n_splits * n_candidates such inner estimators.
            search_inner_tasks = sum(
                p["max_iter"]
                for p in search.cv_results_["params"]
                for _ in range(n_splits)
            )
            refit_inner_tasks = search.best_estimator_.n_iter_ if refit else 0
            expected = searchcv_tasks + search_inner_tasks + refit_inner_tasks
            assert callback.count_hooks("on_fit_task_begin") == expected
            assert callback.count_hooks("on_fit_task_end") == expected
        assert callback.count_hooks("teardown") == 1


@skip_callback_test_if_wasm
def test_search_callbacks_receive_sample_weight():
    """Test that `sample_weight` gets passed to `callback.on_fit_task_*`.

    Note this tests all *SearchCV classes that inherit from `BaseSearchCV`.
    """
    callback = RecordingCallback()
    search = GridSearchCV(
        MaxIterEstimator(), {"max_iter": [1, 2, 3]}, cv=2, scoring="accuracy"
    ).set_callbacks(callback)
    sample_weight = np.random.RandomState(0).randint(0, 5, size=y.shape[0])
    search.fit(X, y, sample_weight=sample_weight)

    evaluation_records = [
        entry
        for entry in callback.record
        if entry["context"].task_name == "candidate-split-evaluation"
    ]
    assert evaluation_records
    refit_records = [
        entry
        for entry in callback.record
        if entry["context"].task_name == "refit-with-best-params"
    ]
    assert refit_records

    for entry in evaluation_records:
        assert "sample_weight" in entry["kwargs"]["metadata"]

    for entry in refit_records:
        assert "sample_weight" in entry["kwargs"]["metadata"]
        passed_weights = entry["kwargs"]["metadata"]["sample_weight"]
        assert_array_equal(passed_weights, sample_weight)


@pytest.mark.parametrize(
    "search",
    _searchcv_callback_test_cases(MaxIterEstimator, "r2"),
)
@skip_callback_test_if_wasm
def test_search_callbacks_receive_search_instance(search):
    """Test that all hooks receive the search instance as `estimator` argument."""
    callback = RecordingCallback()
    search = clone(search).set_callbacks(callback).fit(X, y)

    for entry in callback.record:
        assert entry["estimator"] is search


@skip_callback_test_if_wasm
def test_search_callbacks_with_partial_fit_failures():
    """Check callbacks hooks are called when some candidate fits fail.

    When error_score != "raise" and not all fit fail, the whole search doesn't raise so
    all callback invocations are expected.
    """

    class MayFailClassifier(ClassifierMixin, BaseEstimator):
        def __init__(self, fail=False):
            self.fail = fail

        def fit(self, X, y):
            self.classes_ = np.unique(y)
            if self.fail:
                raise RuntimeError("MayFailClassifier.fit failed")
            return self

        def predict(self, X):
            return np.zeros(len(X), dtype=int)

    callback = RecordingCallback()
    search = GridSearchCV(
        MayFailClassifier(),
        {"fail": [False, True]},
        cv=2,
        refit=False,
        error_score=0.0,
    ).set_callbacks(callback)

    with pytest.warns(FitFailedWarning, match="2 fits failed out of a total of 4."):
        search.fit(X, y)

    expected_n_tasks = 1 + 1 + 4  # root + search + 4 candidate-split evaluations
    assert callback.count_hooks("on_fit_task_begin") == expected_n_tasks
    assert callback.count_hooks("on_fit_task_end") == expected_n_tasks
