"""Re-point scikit-learn's own search test-suite at our implementations
(the reference's vendored-test strategy, SURVEY §4)."""

import jax

jax.config.update("jax_platforms", "cpu")

import sklearn.model_selection as ms  # noqa: E402
import sklearn.model_selection._search as mss  # noqa: E402

import spark_sklearn_tpu as sst  # noqa: E402

ms.GridSearchCV = sst.GridSearchCV
mss.GridSearchCV = sst.GridSearchCV
ms.RandomizedSearchCV = sst.RandomizedSearchCV
mss.RandomizedSearchCV = sst.RandomizedSearchCV
