"""spark_sklearn_tpu — a TPU-native framework with the capabilities of
databricks/spark-sklearn.

Instead of fanning (parameter x fold) tasks out to Spark executors over a
broadcast dataset (reference: python/spark_sklearn/grid_search.py), this
framework lowers the task grid onto a JAX/XLA device mesh: candidates become a
``vmap`` axis, TPU chips a sharded mesh axis, and the dataset a replicated
``jax.device_put`` array over ICI, with per-candidate fits re-expressed as
jit-compiled training loops (Tier A) and a host-Python fallback preserving
full scikit-learn generality (Tier B).

Public API (mirrors the reference's __init__.py exports):
  - GridSearchCV, RandomizedSearchCV   (reference: grid_search.py)
  - Converter                          (reference: converter.py)
  - KeyedEstimator, KeyedModel         (reference: keyed_models.py)
  - gapply                             (reference: group_apply.py)
  - CSRMatrix                          (reference: udt.py CSRVectorUDT)
"""

__version__ = "0.5.0"

import spark_sklearn_tpu.models  # noqa: F401 — registers Tier-A families
from spark_sklearn_tpu.search.grid import GridSearchCV, RandomizedSearchCV
from spark_sklearn_tpu.search.halving import (
    HalvingGridSearchCV,
    HalvingRandomSearchCV,
)
from spark_sklearn_tpu.parallel.mesh import TpuConfig, build_mesh
from spark_sklearn_tpu.convert.converter import Converter
from spark_sklearn_tpu.keyed.keyed import KeyedEstimator, KeyedModel
from spark_sklearn_tpu.keyed.gapply import compiled_group_func, gapply
from spark_sklearn_tpu.sparse.csr import CSRMatrix
from spark_sklearn_tpu.utils.session import (
    TpuSession,
    createLocalSparkSession,
    createLocalTpuSession,
    init_distributed,
)
from spark_sklearn_tpu.serve import (
    AdmissionError,
    SearchCancelledError,
    SearchExecutor,
    SearchFuture,
)

__all__ = [
    "GridSearchCV",
    "RandomizedSearchCV",
    "HalvingGridSearchCV",
    "HalvingRandomSearchCV",
    "AdmissionError",
    "SearchCancelledError",
    "SearchExecutor",
    "SearchFuture",
    "Converter",
    "KeyedEstimator",
    "KeyedModel",
    "gapply",
    "compiled_group_func",
    "CSRMatrix",
    "TpuConfig",
    "TpuSession",
    "build_mesh",
    "createLocalTpuSession",
    "createLocalSparkSession",
    "init_distributed",
    "__version__",
]
