"""gapply — grouped pandas apply with a declared output schema.

Reference: python/spark_sklearn/group_apply.py `gapply(grouped_data, func,
schema, *cols)` — pre-`pandas_udf`-era grouped apply: collect each key's rows
(collect_list(struct(...)) + shuffle), run a (key, pandas.DataFrame) ->
pandas.DataFrame function per group, explode back with a declared schema.

Here there is no shuffle machinery to work around (SURVEY §3.3): groups are
contiguous slices after a host-side sort, and the declared-schema contract is
kept because it is the part users depend on (column names, order, dtypes —
validated against what `func` returns).
"""

from __future__ import annotations

from typing import Callable, Mapping, Sequence, Union

import numpy as np
import pandas as pd

Schema = Union[Sequence[tuple], Mapping[str, object], "pd.Series", None]


def _normalize_schema(schema: Schema):
    """schema -> ordered list of (name, numpy dtype or None)."""
    if schema is None:
        return None
    if isinstance(schema, Mapping):
        return [(k, np.dtype(v) if v is not None else None)
                for k, v in schema.items()]
    out = []
    for item in schema:
        if isinstance(item, str):
            out.append((item, None))
        else:
            name, dtype = item
            out.append((name, np.dtype(dtype) if dtype is not None else None))
    return out


def gapply(
    grouped_data,
    func: Callable,
    schema: Schema = None,
    *cols: str,
    retainGroupColumns: bool = True,
):
    """Apply `func(key, pandas.DataFrame) -> pandas.DataFrame` per group.

    Parameters mirror the reference:
      grouped_data : a pandas ``DataFrameGroupBy`` (``df.groupby(keys)``) —
        the analog of pyspark's GroupedData — or a ``(df, keys)`` tuple.
      func : ``(key_tuple, pdf) -> pdf``: key is always a tuple (even for a
        single key column), pdf contains `cols` (or all non-key columns).
      schema : declared output schema — list of names, list of (name, dtype),
        or {name: dtype}; validated against func's output.  None = infer.
      *cols : the columns handed to func; default = all non-key columns.
      retainGroupColumns : prepend key columns to the output (the
        `spark.sql.retainGroupColumns` conf the reference reads).

    Examples
    --------
    >>> import pandas as pd
    >>> from spark_sklearn_tpu import gapply
    >>> df = pd.DataFrame({"g": [1, 1, 2], "v": [1.0, 2.0, 3.0]})
    >>> gapply(df.groupby("g"),
    ...        lambda key, pdf: pd.DataFrame({"s": [pdf.v.sum()]}),
    ...        [("s", "float64")])
       g    s
    0  1  3.0
    1  2  3.0
    """
    if isinstance(grouped_data, tuple):
        df, keys = grouped_data
        if isinstance(keys, str):
            keys = [keys]
        gb = df.groupby(list(keys), sort=True)
        key_names = list(keys)
    else:
        gb = grouped_data
        keys_attr = gb.keys if not isinstance(gb.keys, str) else [gb.keys]
        key_names = list(keys_attr)
        df = gb.obj

    value_cols = list(cols) if cols else [
        c for c in df.columns if c not in key_names]
    norm_schema = _normalize_schema(schema)

    pieces = []
    for key, pdf in gb:
        if not isinstance(key, tuple):
            key = (key,)
        out = func(key, pdf[value_cols].reset_index(drop=True))
        if not isinstance(out, pd.DataFrame):
            raise TypeError(
                f"func must return a pandas DataFrame, got {type(out)}")
        if norm_schema is not None:
            names = [n for n, _ in norm_schema]
            missing = set(names) - set(out.columns)
            if missing:
                raise ValueError(
                    f"func output is missing schema columns {sorted(missing)}")
            out = out[names]
            for n, dt in norm_schema:
                if dt is not None:
                    out[n] = out[n].astype(dt)
        if retainGroupColumns:
            for i, kn in enumerate(key_names):
                if kn in out.columns:  # func already emitted the key column
                    continue
                out.insert(min(i, len(out.columns)), kn,
                           [key[i]] * len(out))
        pieces.append(out)

    if not pieces:
        # zero groups: build the declared schema with correct dtypes; with
        # schema=None the func's output columns are unknowable without a
        # group, so fall back to the input value columns (documented quirk)
        out = pd.DataFrame()
        if retainGroupColumns:
            for kn in key_names:
                out[kn] = pd.Series([], dtype=df[kn].dtype)
        if norm_schema:
            for n, dt in norm_schema:
                out[n] = pd.Series([], dtype=dt if dt is not None
                                   else object)
        else:
            for c in value_cols:
                out[c] = pd.Series([], dtype=df[c].dtype)
        return out
    return pd.concat(pieces, ignore_index=True)
