"""gapply — grouped pandas apply with a declared output schema.

Reference: python/spark_sklearn/group_apply.py `gapply(grouped_data, func,
schema, *cols)` — pre-`pandas_udf`-era grouped apply: collect each key's rows
(collect_list(struct(...)) + shuffle), run a (key, pandas.DataFrame) ->
pandas.DataFrame function per group, explode back with a declared schema.

Here there is no shuffle machinery to work around (SURVEY §3.3): groups are
contiguous slices after a host-side sort, and the declared-schema contract is
kept because it is the part users depend on (column names, order, dtypes —
validated against what `func` returns).
"""

from __future__ import annotations

from typing import Callable, Mapping, Sequence, Union

import numpy as np
import pandas as pd

Schema = Union[Sequence[tuple], Mapping[str, object], "pd.Series", None]


def compiled_group_func(device_fn: Callable) -> Callable:
    """Mark a pure JAX per-group function for gapply's compiled path.

    `device_fn(X, w)` receives the group's value columns as one padded
    float32 array X of shape (L, n_cols) and a 0/1 row mask w of shape
    (L,) (padding rows carry w == 0), and must return a fixed-width 1-D
    array — one output row per group.  gapply then runs ALL groups as
    bucketed vmapped XLA programs (the keyed-fleet machinery) instead of
    a per-group host loop: the TPU-native answer to the reference's
    collect_list + Python-UDF shuffle (SURVEY §3.3 "sort by key, segment
    boundaries, vmap over segments").

    The value columns must be numeric (they are handed to `device_fn` as
    one float32 matrix); a non-numeric column raises TypeError.  Called
    directly as `func(key, pdf)` the decorated function processes one
    unpadded group and returns a positional-column DataFrame.

    Example
    -------
    >>> import jax.numpy as jnp, pandas as pd
    >>> from spark_sklearn_tpu import gapply, compiled_group_func
    >>> @compiled_group_func
    ... def mean_v(X, w):
    ...     return jnp.sum(X * w[:, None], axis=0) / jnp.sum(w)
    >>> df = pd.DataFrame({"g": [1, 1, 2], "v": [1.0, 2.0, 4.0]})
    >>> gapply(df.groupby("g"), mean_v, [("v", "float64")])
       g    v
    0  1  1.5
    1  2  4.0
    """

    def as_group_func(key, pdf):
        # direct-call convenience: one unpadded group, positional columns
        import jax.numpy as jnp
        X = jnp.asarray(pdf.to_numpy(np.float32))
        w = jnp.ones((len(pdf),), jnp.float32)
        out = np.atleast_1d(np.asarray(device_fn(X, w)))
        return pd.DataFrame([out])

    as_group_func._sst_segment_fn = device_fn
    as_group_func.__name__ = getattr(device_fn, "__name__", "group_func")
    return as_group_func


def _gapply_segments(gb, key_names, value_cols, func, norm_schema,
                     retain_group_columns):
    """Run a compiled_group_func over all groups via the keyed fleet's
    bucketed launcher (`keyed.run_bucketed`).  Returns None for zero
    groups (the caller's empty-schema path covers that)."""
    from spark_sklearn_tpu.keyed.keyed import run_bucketed

    keys, slices = [], []
    for key, pdf in gb:
        if not isinstance(key, tuple):
            key = (key,)
        keys.append(key)
        slices.append(pdf[value_cols])
    if not keys:
        return None
    try:
        mats = [p.to_numpy(np.float32) for p in slices]
    except (ValueError, TypeError) as exc:
        raise TypeError(
            "compiled_group_func requires numeric value columns; got "
            f"{[str(d) for d in slices[0].dtypes]}") from exc

    # one cached jit per decorated func: repeat gapply calls with the
    # same bucket shapes hit XLA's trace cache instead of recompiling
    launch = getattr(func, "_sst_segment_jit", None)
    if launch is None:
        import jax
        launch = jax.jit(jax.vmap(func._sst_segment_fn))
        func._sst_segment_jit = launch

    order, Y = run_bucketed(mats, None, None, func._sst_segment_fn,
                            launch=launch)
    Y = np.asarray(Y)
    if Y.ndim == 1:
        Y = Y[:, None]         # scalar-per-group -> one output column
    if Y.ndim != 2:
        raise ValueError(
            "a compiled_group_func must return a fixed-width 1-D "
            f"array per group; got per-group shape {Y.shape[1:]}")
    rows = [None] * len(keys)
    for j, gi in enumerate(order):
        rows[gi] = Y[j]

    width = len(rows[0])
    if norm_schema is not None:
        if len(norm_schema) != width:
            raise ValueError(
                f"schema declares {len(norm_schema)} columns but the "
                f"compiled group func returned {width}")
        names = [n for n, _ in norm_schema]
    else:
        names = [f"out{i}" for i in range(width)]
    out = pd.DataFrame(np.stack(rows), columns=names)
    if norm_schema is not None:
        for n, dt in norm_schema:
            if dt is not None:
                out[n] = out[n].astype(dt)
    if retain_group_columns:
        for i, kn in enumerate(key_names):
            if kn in out.columns:
                continue
            out.insert(min(i, len(out.columns)), kn,
                       [k[i] for k in keys])
    return out


def _normalize_schema(schema: Schema):
    """schema -> ordered list of (name, numpy dtype or None)."""
    if schema is None:
        return None
    if isinstance(schema, Mapping):
        return [(k, np.dtype(v) if v is not None else None)
                for k, v in schema.items()]
    out = []
    for item in schema:
        if isinstance(item, str):
            out.append((item, None))
        else:
            name, dtype = item
            out.append((name, np.dtype(dtype) if dtype is not None else None))
    return out


def gapply(
    grouped_data,
    func: Callable,
    schema: Schema = None,
    *cols: str,
    retainGroupColumns: bool = True,
):
    """Apply `func(key, pandas.DataFrame) -> pandas.DataFrame` per group.

    Parameters mirror the reference:
      grouped_data : a pandas ``DataFrameGroupBy`` (``df.groupby(keys)``) —
        the analog of pyspark's GroupedData — or a ``(df, keys)`` tuple.
      func : ``(key_tuple, pdf) -> pdf``: key is always a tuple (even for a
        single key column), pdf contains `cols` (or all non-key columns).
      schema : declared output schema — list of names, list of (name, dtype),
        or {name: dtype}; validated against func's output.  None = infer.
      *cols : the columns handed to func; default = all non-key columns.
      retainGroupColumns : prepend key columns to the output (the
        `spark.sql.retainGroupColumns` conf the reference reads).

    Examples
    --------
    >>> import pandas as pd
    >>> from spark_sklearn_tpu import gapply
    >>> df = pd.DataFrame({"g": [1, 1, 2], "v": [1.0, 2.0, 3.0]})
    >>> gapply(df.groupby("g"),
    ...        lambda key, pdf: pd.DataFrame({"s": [pdf.v.sum()]}),
    ...        [("s", "float64")])
       g    s
    0  1  3.0
    1  2  3.0
    """
    if isinstance(grouped_data, tuple):
        df, keys = grouped_data
        if isinstance(keys, str):
            keys = [keys]
        gb = df.groupby(list(keys), sort=True)
        key_names = list(keys)
    else:
        gb = grouped_data
        keys_attr = gb.keys if not isinstance(gb.keys, str) else [gb.keys]
        key_names = list(keys_attr)
        df = gb.obj

    value_cols = list(cols) if cols else [
        c for c in df.columns if c not in key_names]
    norm_schema = _normalize_schema(schema)

    if getattr(func, "_sst_segment_fn", None) is not None:
        res = _gapply_segments(gb, key_names, value_cols, func,
                               norm_schema, retainGroupColumns)
        if res is not None:
            return res

    pieces = []
    for key, pdf in gb:
        if not isinstance(key, tuple):
            key = (key,)
        out = func(key, pdf[value_cols].reset_index(drop=True))
        if not isinstance(out, pd.DataFrame):
            raise TypeError(
                f"func must return a pandas DataFrame, got {type(out)}")
        if norm_schema is not None:
            names = [n for n, _ in norm_schema]
            missing = set(names) - set(out.columns)
            if missing:
                raise ValueError(
                    f"func output is missing schema columns {sorted(missing)}")
            out = out[names]
            for n, dt in norm_schema:
                if dt is not None:
                    out[n] = out[n].astype(dt)
        if retainGroupColumns:
            for i, kn in enumerate(key_names):
                if kn in out.columns:  # func already emitted the key column
                    continue
                out.insert(min(i, len(out.columns)), kn,
                           [key[i]] * len(out))
        pieces.append(out)

    if not pieces:
        # zero groups: build the declared schema with correct dtypes; with
        # schema=None the func's output columns are unknowable without a
        # group, so fall back to the input value columns (documented quirk)
        out = pd.DataFrame()
        if retainGroupColumns:
            for kn in key_names:
                out[kn] = pd.Series([], dtype=df[kn].dtype)
        if norm_schema:
            for n, dt in norm_schema:
                out[n] = pd.Series([], dtype=dt if dt is not None
                                   else object)
        else:
            for c in value_cols:
                out[c] = pd.Series([], dtype=df[c].dtype)
        return out
    return pd.concat(pieces, ignore_index=True)
