"""KeyedEstimator / KeyedModel — per-key model fleets.

Reference: python/spark_sklearn/keyed_models.py — a pyspark.ml Estimator that
fits one sklearn estimator per key group of a DataFrame and stores the
fitted, *pickled* estimator inside a DataFrame column; transform joins on the
keys and applies per-row Python UDFs (call stack SURVEY §3.2).

TPU-native redesign: models live as **stacked parameter pytrees** with a
leading key axis when the estimator maps to a compiled family — one `vmap`
over keys replaces the per-key executor loop, and transform is one batched
gather + predict instead of a join shipping pickles.  Estimators outside the
registry fall back to per-key host fits (full sklearn generality, same as
the reference's semantics minus Spark).

API mirrors the reference's Params:
  KeyedEstimator(sklearnEstimator=, keyCols=, xCol=, yCol=, outputCol=,
                 estimatorType=)   with estimatorType in
  {"predictor", "transformer", "clusterer"} (inferred when yCol is given).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

import numpy as np
import pandas as pd

from sklearn.base import BaseEstimator, clone


def _stack_x(col) -> np.ndarray:
    """Column of vectors/scalars -> 2-D float array."""
    first = col.iloc[0]
    if np.isscalar(first) or (hasattr(first, "shape") and
                              np.asarray(first).ndim == 0):
        return np.asarray(col, dtype=np.float64)[:, None]
    return np.stack([np.asarray(v, dtype=np.float64) for v in col])


class KeyedEstimator(BaseEstimator):
    """Fits one estimator per distinct key of a DataFrame.

    >>> ke = KeyedEstimator(sklearnEstimator=LinearRegression(),
    ...                     keyCols=["user"], xCol="x", yCol="y")
    >>> model = ke.fit(df)          # df: pandas DataFrame
    >>> model.transform(df2)        # adds model.outputCol per-key predictions
    """

    _TYPES = ("predictor", "transformer", "clusterer")

    def __init__(self, sklearnEstimator=None,
                 keyCols: Sequence[str] = ("key",),
                 xCol: str = "features", yCol: Optional[str] = None,
                 outputCol: str = "output",
                 estimatorType: Optional[str] = None):
        self.outputCol = outputCol
        if sklearnEstimator is None:
            raise ValueError("sklearnEstimator must be provided")
        if not hasattr(sklearnEstimator, "fit"):
            raise ValueError("sklearnEstimator must implement fit()")
        if yCol is not None and not hasattr(sklearnEstimator, "predict"):
            raise ValueError(
                "supervised (yCol given) requires a predictor estimator")
        self.sklearnEstimator = sklearnEstimator
        self.keyCols = list(keyCols)
        self.xCol = xCol
        self.yCol = yCol
        if estimatorType is None:
            estimatorType = "predictor" if yCol is not None else (
                "clusterer" if hasattr(sklearnEstimator, "predict")
                and not hasattr(sklearnEstimator, "transform")
                else "transformer")
        if estimatorType not in self._TYPES:
            raise ValueError(
                f"estimatorType must be one of {self._TYPES}, "
                f"got {estimatorType!r}")
        if yCol is not None and estimatorType != "predictor":
            raise ValueError(
                "estimatorType must be 'predictor' when yCol is given")
        self.estimatorType = estimatorType

    def fit(self, df: pd.DataFrame) -> "KeyedModel":
        missing = [c for c in self.keyCols + [self.xCol] if c not in df]
        if self.yCol is not None and self.yCol not in df:
            missing.append(self.yCol)
        if missing:
            raise KeyError(f"DataFrame is missing columns: {missing}")

        models: Dict[tuple, Any] = {}
        for key, pdf in df.groupby(self.keyCols, sort=True):
            if not isinstance(key, tuple):
                key = (key,)
            X = _stack_x(pdf[self.xCol])
            est = clone(self.sklearnEstimator)
            if self.yCol is not None:
                est.fit(X, np.asarray(pdf[self.yCol]))
            else:
                est.fit(X)
            models[key] = est
        return KeyedModel(
            keyCols=self.keyCols, xCol=self.xCol, yCol=self.yCol,
            outputCol=self.outputCol,
            estimatorType=self.estimatorType, models=models)


class KeyedModel:
    """The fitted per-key fleet.  `keyedModels` exposes the per-key
    estimators as a DataFrame like the reference's model DataFrame (minus
    the pickling)."""

    def __init__(self, keyCols, xCol, yCol, outputCol, estimatorType,
                 models: Dict[tuple, Any]):
        self.keyCols = list(keyCols)
        self.xCol = xCol
        self.yCol = yCol
        self.outputCol = outputCol
        self.estimatorType = estimatorType
        self.models = models

    @property
    def keyedModels(self) -> pd.DataFrame:
        rows = []
        for key, est in self.models.items():
            rows.append(dict(zip(self.keyCols, key), estimator=est))
        return pd.DataFrame(rows)

    def transform(self, df: pd.DataFrame) -> pd.DataFrame:
        """Per-key apply: predictor -> predict (float), clusterer -> predict
        (int), transformer -> transform (vector).  Keys never seen in fit
        yield NaN/None rows (the reference's join drops them; keeping the
        row with a null is the friendlier DataFrame-native contract)."""
        # positional reassembly: robust to duplicate index labels and to
        # NaN keys (groupby(dropna=False) keeps those rows; their key has no
        # fitted model so they get null output)
        orig_index = df.index
        work = df.reset_index(drop=True)
        out_values: List[Any] = [None] * len(work)
        for key, pdf in work.groupby(self.keyCols, sort=False, dropna=False):
            if not isinstance(key, tuple):
                key = (key,)
            est = self.models.get(key)
            pos = pdf.index.to_numpy()
            if est is None:
                fill = None if self.estimatorType == "transformer" else np.nan
                for p in pos:
                    out_values[p] = fill
            else:
                X = _stack_x(pdf[self.xCol])
                if self.estimatorType == "transformer":
                    vals = list(np.asarray(est.transform(X)))
                elif self.estimatorType == "clusterer":
                    vals = list(np.asarray(est.predict(X), dtype=np.int64))
                else:
                    pred = np.asarray(est.predict(X))
                    if np.issubdtype(pred.dtype, np.number):
                        pred = pred.astype(np.float64)
                    vals = list(pred)  # string labels pass through as-is
                for p, v in zip(pos, vals):
                    out_values[p] = v
        res = df.copy()
        res[self.outputCol] = pd.Series(out_values, index=orig_index)
        return res
