"""KeyedEstimator / KeyedModel — per-key model fleets.

Reference: python/spark_sklearn/keyed_models.py — a pyspark.ml Estimator that
fits one sklearn estimator per key group of a DataFrame and stores the
fitted, *pickled* estimator inside a DataFrame column; transform joins on the
keys and applies per-row Python UDFs (call stack SURVEY §3.2).

TPU-native redesign: models live as **stacked parameter pytrees** with a
leading key axis when the estimator maps to a compiled family — one `vmap`
over keys replaces the per-key executor loop, and transform is one batched
gather + predict instead of a join shipping pickles.  Estimators outside the
registry fall back to per-key host fits (full sklearn generality, same as
the reference's semantics minus Spark).

API mirrors the reference's Params:
  KeyedEstimator(sklearnEstimator=, keyCols=, xCol=, yCol=, outputCol=,
                 estimatorType=)   with estimatorType in
  {"predictor", "transformer", "clusterer"} (inferred when yCol is given).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

import numpy as np
import pandas as pd

from sklearn.base import BaseEstimator, clone


def _stack_x(col) -> np.ndarray:
    """Column of vectors/scalars -> 2-D float array."""
    first = col.iloc[0]
    if np.isscalar(first) or (hasattr(first, "shape") and
                              np.asarray(first).ndim == 0):
        return np.asarray(col, dtype=np.float64)[:, None]
    return np.stack([np.asarray(v, dtype=np.float64) for v in col])


class KeyedEstimator(BaseEstimator):
    """Fits one estimator per distinct key of a DataFrame.

    >>> ke = KeyedEstimator(sklearnEstimator=LinearRegression(),
    ...                     keyCols=["user"], xCol="x", yCol="y")
    >>> model = ke.fit(df)          # df: pandas DataFrame
    >>> model.transform(df2)        # adds model.outputCol per-key predictions
    """

    _TYPES = ("predictor", "transformer", "clusterer")

    def __init__(self, sklearnEstimator=None,
                 keyCols: Sequence[str] = ("key",),
                 xCol: str = "features", yCol: Optional[str] = None,
                 outputCol: str = "output",
                 estimatorType: Optional[str] = None):
        self.outputCol = outputCol
        if sklearnEstimator is None:
            raise ValueError("sklearnEstimator must be provided")
        if not hasattr(sklearnEstimator, "fit"):
            raise ValueError("sklearnEstimator must implement fit()")
        if yCol is not None and not hasattr(sklearnEstimator, "predict"):
            raise ValueError(
                "supervised (yCol given) requires a predictor estimator")
        self.sklearnEstimator = sklearnEstimator
        self.keyCols = list(keyCols)
        self.xCol = xCol
        self.yCol = yCol
        if estimatorType is None:
            estimatorType = "predictor" if yCol is not None else (
                "clusterer" if hasattr(sklearnEstimator, "predict")
                and not hasattr(sklearnEstimator, "transform")
                else "transformer")
        if estimatorType not in self._TYPES:
            raise ValueError(
                f"estimatorType must be one of {self._TYPES}, "
                f"got {estimatorType!r}")
        if yCol is not None and estimatorType != "predictor":
            raise ValueError(
                "estimatorType must be 'predictor' when yCol is given")
        # transform-time requirements checked up front (the reference's
        # Param validation equivalent): predictor/clusterer apply predict,
        # transformer applies transform — transductive estimators like
        # DBSCAN (no predict) cannot serve as keyed clusterers
        needed = ("transform" if estimatorType == "transformer"
                  else "predict")
        if not hasattr(sklearnEstimator, needed):
            raise ValueError(
                f"estimatorType={estimatorType!r} requires an estimator "
                f"with a {needed}() method; "
                f"{type(sklearnEstimator).__name__} has none")
        self.estimatorType = estimatorType

    def fit(self, df: pd.DataFrame) -> "KeyedModel":
        missing = [c for c in self.keyCols + [self.xCol] if c not in df]
        if self.yCol is not None and self.yCol not in df:
            missing.append(self.yCol)
        if missing:
            raise KeyError(f"DataFrame is missing columns: {missing}")

        fleet = None
        if self.estimatorType in ("predictor", "clusterer"):
            fleet = self._try_fit_compiled(df)
        if fleet is not None:
            return fleet

        models: Dict[tuple, Any] = {}
        for key, pdf in df.groupby(self.keyCols, sort=True):
            if not isinstance(key, tuple):
                key = (key,)
            X = _stack_x(pdf[self.xCol])
            est = clone(self.sklearnEstimator)
            if self.yCol is not None:
                est.fit(X, np.asarray(pdf[self.yCol]))
            else:
                est.fit(X)
            models[key] = est
        return KeyedModel(
            keyCols=self.keyCols, xCol=self.xCol, yCol=self.yCol,
            outputCol=self.outputCol,
            estimatorType=self.estimatorType, models=models)

    def _try_fit_compiled(self, df) -> Optional["KeyedModel"]:
        """The TPU-native per-key fleet: keys become ONE vmap axis.

        Groups are padded to the longest group with zero sample weights
        (same fixed-shape trick as CV fold masks), every key's estimator is
        fitted by one jitted vmapped program, and the fleet lives as a
        stacked parameter pytree with a leading key axis — replacing the
        reference's pickled-estimator-per-row DataFrame column (reference:
        keyed_models.py stores cloudpickled sklearn models; SURVEY §3.2).
        Returns None when the estimator has no compiled family (-> host
        loop, full sklearn generality).
        """
        from spark_sklearn_tpu.models.base import resolve_family

        family = resolve_family(self.sklearnEstimator)
        if family is None or not family.has_per_task_fit() or \
                not getattr(family, "keyed_compatible", True):
            return None
        import jax
        import jax.numpy as jnp

        work = df.reset_index(drop=True)   # positional index for gathers
        keys, slices = [], []
        for key, pdf in work.groupby(self.keyCols, sort=True):
            if not isinstance(key, tuple):
                key = (key,)
            keys.append(key)
            slices.append(pdf)
        G = len(keys)
        L = max(len(p) for p in slices)

        X_all = _stack_x(work[self.xCol]).astype(np.float32)
        static_probe = family.extract_params(self.sklearnEstimator)
        min_needed = (family.min_group_size(static_probe)
                      if hasattr(family, "min_group_size") else 1)
        if min(len(p) for p in slices) < min_needed:
            # some key has too few rows for this estimator (e.g. fewer
            # samples than n_clusters) — host loop raises per key the way
            # sklearn would
            return None
        d = X_all.shape[1]
        unsupervised = self.yCol is None
        y_all = None if unsupervised else np.asarray(work[self.yCol])
        try:
            _, meta = family.prepare_data(X_all, y_all)
        except Exception:
            return None
        static = family.extract_params(self.sklearnEstimator)

        if unsupervised:
            enc = np.zeros(len(work), np.float64)
        elif family.is_classifier:
            lookup = {v: i for i, v in enumerate(meta["classes"])}
            enc = np.array([lookup[v] for v in y_all], np.float64)
            # per-key classes_ semantics: a key whose group lacks some of
            # the global classes must be fitted over its OWN label set (the
            # host loop does that); the stacked fleet label-encodes
            # globally, so it only applies when every key saw every class
            for pdf in slices:
                if len(set(enc[pdf.index.to_numpy()])) < meta["n_classes"]:
                    return None
        else:
            enc = np.asarray(y_all, np.float64)
        Xs = np.zeros((G, L, d), np.float32)
        ys = np.zeros((G, L), np.float64)
        ws = np.zeros((G, L), np.float32)
        for i, pdf in enumerate(slices):
            m = len(pdf)
            pos = pdf.index.to_numpy()
            Xs[i, :m] = X_all[pos]
            ys[i, :m] = enc[pos]
            ws[i, :m] = 1.0

        def fit_one(Xg, yg, wg):
            if unsupervised:
                data_g = {"X": Xg}
            elif family.is_classifier:
                k = meta["n_classes"]
                data_g = {"X": Xg, "y": yg.astype(jnp.int32),
                          "y1h": jax.nn.one_hot(
                              yg.astype(jnp.int32), k, dtype=Xg.dtype)}
            else:
                data_g = {"X": Xg, "y": yg.astype(Xg.dtype)}
            return family.fit({}, static, data_g, wg, meta)

        # ys already holds encoded class indices (classifiers) or raw
        # targets (regressors) from the fill loop above
        ys_dev = jnp.asarray(ys, jnp.int32 if family.is_classifier
                             else jnp.float32)

        try:
            models = jax.jit(jax.vmap(fit_one))(
                jnp.asarray(Xs), ys_dev, jnp.asarray(ws))
        except Exception as exc:
            import warnings
            warnings.warn(
                f"compiled keyed fleet failed ({exc!r}); falling back to "
                "per-key host fits", UserWarning)
            return None
        return KeyedModel(
            keyCols=self.keyCols, xCol=self.xCol, yCol=self.yCol,
            outputCol=self.outputCol, estimatorType=self.estimatorType,
            models=None, fleet=dict(
                family=family, models=models, meta=meta, static=static,
                key_index={k: i for i, k in enumerate(keys)}))


class KeyedModel:
    """The fitted per-key fleet.  `keyedModels` exposes the per-key
    estimators as a DataFrame like the reference's model DataFrame (minus
    the pickling)."""

    def __init__(self, keyCols, xCol, yCol, outputCol, estimatorType,
                 models: Optional[Dict[tuple, Any]], fleet=None):
        self.keyCols = list(keyCols)
        self.xCol = xCol
        self.yCol = yCol
        self.outputCol = outputCol
        self.estimatorType = estimatorType
        self.models = models            # host fleet: {key: fitted sklearn}
        self.fleet = fleet              # compiled fleet: stacked pytrees

    @property
    def backend(self) -> str:
        return "tpu" if self.fleet is not None else "host"

    @property
    def keyedModels(self) -> pd.DataFrame:
        """One row per key with an `estimator` cell that supports
        `.predict` on BOTH backends (fitted sklearn estimator on the host
        path, a TpuModel view of the stacked pytree on the fleet path)."""
        rows = []
        if self.fleet is not None:
            import jax
            from spark_sklearn_tpu.convert.converter import TpuModel
            fam = self.fleet["family"]
            for key, i in self.fleet["key_index"].items():
                leaf = jax.tree_util.tree_map(
                    lambda a: a[i], self.fleet["models"])
                rows.append(dict(
                    zip(self.keyCols, key),
                    estimator=TpuModel(fam, leaf, self.fleet["static"],
                                       self.fleet["meta"])))
            return pd.DataFrame(rows)
        for key, est in self.models.items():
            rows.append(dict(zip(self.keyCols, key), estimator=est))
        return pd.DataFrame(rows)

    def transform(self, df: pd.DataFrame) -> pd.DataFrame:
        """Per-key apply: predictor -> predict (float), clusterer -> predict
        (int), transformer -> transform (vector).  Keys never seen in fit
        yield NaN/None rows (the reference's join drops them; keeping the
        row with a null is the friendlier DataFrame-native contract)."""
        # positional reassembly: robust to duplicate index labels and to
        # NaN keys (groupby(dropna=False) keeps those rows; their key has no
        # fitted model so they get null output)
        orig_index = df.index
        work = df.reset_index(drop=True)
        out_values: List[Any] = [None] * len(work)
        for key, pdf in work.groupby(self.keyCols, sort=False, dropna=False):
            if not isinstance(key, tuple):
                key = (key,)
            pos = pdf.index.to_numpy()
            if self.fleet is not None:
                vals = self._fleet_predict(key, pdf)
                if vals is None:
                    for p in pos:
                        out_values[p] = np.nan
                else:
                    for p, v in zip(pos, vals):
                        out_values[p] = v
                continue
            est = self.models.get(key)
            if est is None:
                fill = None if self.estimatorType == "transformer" else np.nan
                for p in pos:
                    out_values[p] = fill
            else:
                X = _stack_x(pdf[self.xCol])
                if self.estimatorType == "transformer":
                    vals = list(np.asarray(est.transform(X)))
                elif self.estimatorType == "clusterer":
                    vals = list(np.asarray(est.predict(X), dtype=np.int64))
                else:
                    pred = np.asarray(est.predict(X))
                    if np.issubdtype(pred.dtype, np.number):
                        pred = pred.astype(np.float64)
                    vals = list(pred)  # string labels pass through as-is
                for p, v in zip(pos, vals):
                    out_values[p] = v
        res = df.copy()
        res[self.outputCol] = pd.Series(out_values, index=orig_index)
        return res

    def _fleet_predict(self, key, pdf):
        """Batched predict from the stacked-pytree fleet (one gather on the
        key axis + the family's compiled predict)."""
        import jax
        import jax.numpy as jnp
        idx = self.fleet["key_index"].get(key)
        if idx is None:
            return None
        fam = self.fleet["family"]
        model = jax.tree_util.tree_map(
            lambda a: a[idx], self.fleet["models"])
        X = jnp.asarray(_stack_x(pdf[self.xCol]), jnp.float32)
        pred = np.asarray(fam.predict(
            model, self.fleet["static"], X, self.fleet["meta"]))
        if fam.is_classifier:
            return list(self.fleet["meta"]["classes"][pred])
        if self.estimatorType == "clusterer":
            return list(pred.astype(np.int64))
        return list(pred.astype(np.float64))
