"""KeyedEstimator / KeyedModel — per-key model fleets.

Reference: python/spark_sklearn/keyed_models.py — a pyspark.ml Estimator that
fits one sklearn estimator per key group of a DataFrame and stores the
fitted, *pickled* estimator inside a DataFrame column; transform joins on the
keys and applies per-row Python UDFs (call stack SURVEY §3.2).

TPU-native redesign: models live as **stacked parameter pytrees** with a
leading key axis when the estimator maps to a compiled family — one `vmap`
over keys replaces the per-key executor loop, and transform is one batched
gather + predict instead of a join shipping pickles.  Estimators outside the
registry fall back to per-key host fits (full sklearn generality, same as
the reference's semantics minus Spark).

API mirrors the reference's Params:
  KeyedEstimator(sklearnEstimator=, keyCols=, xCol=, yCol=, outputCol=,
                 estimatorType=)   with estimatorType in
  {"predictor", "transformer", "clusterer"} (inferred when yCol is given).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

import numpy as np
import pandas as pd

from sklearn.base import BaseEstimator, clone


def _stack_x(col) -> np.ndarray:
    """Column of vectors/scalars -> 2-D float array."""
    first = col.iloc[0]
    if np.isscalar(first) or (hasattr(first, "shape") and
                              np.asarray(first).ndim == 0):
        return np.asarray(col, dtype=np.float64)[:, None]
    return np.stack([np.asarray(v, dtype=np.float64) for v in col])


def bucket_len(m: int, floor: int = 8) -> int:
    """Pad length for a group of m rows: next power of two (>= floor).

    Bucketed padding bounds the waste at 2x per group, so one huge key
    among thousands of small ones costs O(G_small * L_small + L_big)
    memory instead of the O(G * L_max) a single global pad would
    (SURVEY §3.2 redesign note; the round-1 fleet padded globally).
    Shared by the keyed fleets and gapply's compiled segment path.
    """
    L = floor
    while L < m:
        L *= 2
    return L


def run_bucketed(mats, encs, y_dtype, fit_one, launch=None):
    """The bucketed-fleet launcher shared by keyed fleets and gapply.

    mats: per-group (m_i, d) float32 arrays; encs: matching (m_i,) target
    arrays, or None for target-less fits (transformer steps, gapply
    segment funcs — `fit_one` then takes (Xg, wg) instead of
    (Xg, yg, wg)).  Each group is zero-padded to its bucket length, each
    bucket runs as one jit(vmap(fit_one)) program, and the stacked result
    pytrees are concatenated on the group axis.  `launch` overrides the
    per-bucket callable (callers that reuse a cached jit across calls).

    Returns (order, stacked): order[j] = index into `mats` of stacked
    row j.
    """
    import jax
    import jax.numpy as jnp

    if launch is None:
        launch = jax.jit(jax.vmap(fit_one))

    buckets: Dict[int, list] = {}
    for i, m in enumerate(mats):
        buckets.setdefault(bucket_len(len(m)), []).append(i)

    d = mats[0].shape[1]
    order, stacked = [], []
    for L in sorted(buckets):
        idxs = buckets[L]
        Xs = np.zeros((len(idxs), L, d), np.float32)
        ws = np.zeros((len(idxs), L), np.float32)
        ys = None if encs is None else np.zeros((len(idxs), L), y_dtype)
        for j, gi in enumerate(idxs):
            m = len(mats[gi])
            Xs[j, :m] = mats[gi]
            ws[j, :m] = 1.0
            if ys is not None:
                ys[j, :m] = encs[gi]
        args = [jnp.asarray(Xs)]
        if ys is not None:
            args.append(jnp.asarray(ys))
        args.append(jnp.asarray(ws))
        stacked.append(launch(*args))
        order.extend(idxs)
    if jax.tree_util.tree_leaves(stacked[0]):
        models = jax.tree_util.tree_map(
            lambda *leaves: jnp.concatenate(leaves, axis=0), *stacked)
    else:
        models = stacked[0]   # stateless result (e.g. Normalizer step)
    return order, models


class KeyedEstimator(BaseEstimator):
    """Fits one estimator per distinct key of a DataFrame.

    >>> ke = KeyedEstimator(sklearnEstimator=LinearRegression(),
    ...                     keyCols=["user"], xCol="x", yCol="y")
    >>> model = ke.fit(df)          # df: pandas DataFrame
    >>> model.transform(df2)        # adds model.outputCol per-key predictions
    """

    _TYPES = ("predictor", "transformer", "clusterer")

    def __init__(self, sklearnEstimator=None,
                 keyCols: Sequence[str] = ("key",),
                 xCol: str = "features", yCol: Optional[str] = None,
                 outputCol: str = "output",
                 estimatorType: Optional[str] = None):
        self.outputCol = outputCol
        if sklearnEstimator is None:
            raise ValueError("sklearnEstimator must be provided")
        if not hasattr(sklearnEstimator, "fit"):
            raise ValueError("sklearnEstimator must implement fit()")
        if yCol is not None and not hasattr(sklearnEstimator, "predict"):
            raise ValueError(
                "supervised (yCol given) requires a predictor estimator")
        self.sklearnEstimator = sklearnEstimator
        self.keyCols = list(keyCols)
        self.xCol = xCol
        self.yCol = yCol
        if estimatorType is None:
            estimatorType = "predictor" if yCol is not None else (
                "clusterer" if hasattr(sklearnEstimator, "predict")
                and not hasattr(sklearnEstimator, "transform")
                else "transformer")
        if estimatorType not in self._TYPES:
            raise ValueError(
                f"estimatorType must be one of {self._TYPES}, "
                f"got {estimatorType!r}")
        if yCol is not None and estimatorType != "predictor":
            raise ValueError(
                "estimatorType must be 'predictor' when yCol is given")
        # transform-time requirements checked up front (the reference's
        # Param validation equivalent): predictor/clusterer apply predict,
        # transformer applies transform — transductive estimators like
        # DBSCAN (no predict) cannot serve as keyed clusterers
        needed = ("transform" if estimatorType == "transformer"
                  else "predict")
        if not hasattr(sklearnEstimator, needed):
            raise ValueError(
                f"estimatorType={estimatorType!r} requires an estimator "
                f"with a {needed}() method; "
                f"{type(sklearnEstimator).__name__} has none")
        self.estimatorType = estimatorType

    def fit(self, df: pd.DataFrame) -> "KeyedModel":
        missing = [c for c in self.keyCols + [self.xCol] if c not in df]
        if self.yCol is not None and self.yCol not in df:
            missing.append(self.yCol)
        if missing:
            raise KeyError(f"DataFrame is missing columns: {missing}")

        work = df.reset_index(drop=True)   # positional index for gathers
        keys, slices = [], []
        for key, pdf in work.groupby(self.keyCols, sort=True):
            if not isinstance(key, tuple):
                key = (key,)
            keys.append(key)
            slices.append(pdf)

        if self.estimatorType == "transformer":
            fleet, host_pairs = self._fit_transformer_fleet(
                work, keys, slices)
        else:
            fleet, host_pairs = self._fit_family_fleet(work, keys, slices)

        models: Optional[Dict[tuple, Any]] = None
        if host_pairs:
            models = {}
            for key, pdf in host_pairs:
                X = _stack_x(pdf[self.xCol])
                est = clone(self.sklearnEstimator)
                if self.yCol is not None:
                    est.fit(X, np.asarray(pdf[self.yCol]))
                else:
                    est.fit(X)
                models[key] = est
        return KeyedModel(
            keyCols=self.keyCols, xCol=self.xCol, yCol=self.yCol,
            outputCol=self.outputCol,
            estimatorType=self.estimatorType, models=models, fleet=fleet)

    _bucket_len = staticmethod(bucket_len)

    def _fit_family_fleet(self, work, keys, slices):
        """The TPU-native per-key fleet: keys become vmap axes.

        Groups are padded to per-bucket maxima with zero sample weights
        (same fixed-shape trick as CV fold masks), each bucket's keys are
        fitted by one jitted vmapped program, and the fleet lives as ONE
        stacked parameter pytree with a leading key axis (bucket results
        are concatenated — model shapes depend on d/k, never on group
        length) — replacing the reference's pickled-estimator-per-row
        DataFrame column (reference: keyed_models.py stores cloudpickled
        sklearn models; SURVEY §3.2).

        Returns (fleet | None, host_pairs): keys the compiled path cannot
        serve — no compiled family, too few rows for the estimator, or a
        classifier key lacking some of the global classes (per-key
        classes_ semantics) — are returned for per-key host fits instead
        of failing the whole fleet to the host loop.
        """
        from spark_sklearn_tpu.models.base import resolve_family

        pairs = list(zip(keys, slices))
        if not pairs:
            return None, pairs
        family = resolve_family(self.sklearnEstimator)
        if family is None or not family.has_per_task_fit() or \
                not getattr(family, "keyed_compatible", True):
            return None, pairs

        X_all = _stack_x(work[self.xCol]).astype(np.float32)
        unsupervised = self.yCol is None
        y_all = None if unsupervised else np.asarray(work[self.yCol])
        try:
            _, meta = family.prepare_data(X_all, y_all)
        except Exception as exc:
            # unsupported data shape/labels for the compiled family —
            # fall back to per-key host fits, but leave a trace of why
            # instead of a silent swallow
            from spark_sklearn_tpu.obs.log import get_logger
            get_logger(__name__).debug(
                "keyed fleet: prepare_data rejected the stacked data "
                "(%r); using per-key host fits", exc)
            return None, pairs
        static = family.extract_params(self.sklearnEstimator)
        min_needed = (family.min_group_size(static)
                      if hasattr(family, "min_group_size") else 1)

        if unsupervised:
            enc = None   # no targets: _fit_bucketed uses 2-arg fit_one
        elif family.is_classifier:
            lookup = {v: i for i, v in enumerate(meta["classes"])}
            enc = np.array([lookup[v] for v in y_all], np.float64)
        else:
            enc = np.asarray(y_all, np.float64)

        eligible, host_pairs = [], []
        for key, pdf in pairs:
            if len(pdf) < min_needed:
                # too few rows for this estimator on the compiled path
                # (e.g. fewer samples than n_clusters) — host fit raises
                # per key the way sklearn would
                host_pairs.append((key, pdf))
            elif not unsupervised and family.is_classifier and \
                    len(set(enc[pdf.index.to_numpy()])) < meta["n_classes"]:
                # per-key classes_ semantics: a key whose group lacks some
                # of the global classes must be fitted over its OWN label
                # set, which only the host loop does
                host_pairs.append((key, pdf))
            else:
                eligible.append((key, pdf))
        if not eligible:
            return None, host_pairs

        if unsupervised:
            def fit_one(Xg, wg):
                return family.fit(
                    {}, static, family.build_fit_data(Xg, None, meta),
                    wg, meta)
        else:
            def fit_one(Xg, yg, wg):
                return family.fit(
                    {}, static, family.build_fit_data(Xg, yg, meta),
                    wg, meta)

        y_dtype = np.int32 if (not unsupervised and family.is_classifier) \
            else np.float32
        try:
            fleet_keys, models = self._fit_bucketed(
                eligible, X_all, enc, y_dtype, fit_one)
        except Exception as exc:
            import warnings
            warnings.warn(
                f"compiled keyed fleet failed ({exc!r}); falling back to "
                "per-key host fits", UserWarning)
            return None, host_pairs + eligible
        return dict(
            kind="family", family=family, models=models, meta=meta,
            static=static,
            key_index={k: i for i, k in enumerate(fleet_keys)}), host_pairs

    def _fit_bucketed(self, eligible, X_all, enc, y_dtype, fit_one):
        """Adapter over the module-level `run_bucketed` launcher: slices
        per-key group matrices/targets out of the full arrays and maps the
        launcher's order back to keys.  Returns (keys_in_fleet_order,
        stacked_models)."""
        mats = [X_all[pdf.index.to_numpy()] for _, pdf in eligible]
        encs = None if enc is None else \
            [enc[pdf.index.to_numpy()] for _, pdf in eligible]
        order, models = run_bucketed(mats, encs, y_dtype, fit_one)
        return [eligible[i][0] for i in order], models

    def _fit_transformer_fleet(self, work, keys, slices):
        """Compiled transformer-type fleets: one vmapped weighted-stats fit
        per bucket over the preprocessing steps (StandardScaler and
        friends), stored as a stacked state pytree — transform is a gather
        on the key axis + the step's pure apply."""
        from spark_sklearn_tpu.models.preprocessing import resolve_step

        pairs = list(zip(keys, slices))
        if not pairs:
            return None, pairs
        step = resolve_step(self.sklearnEstimator)
        if step is None:
            return None, pairs

        static = dict(self.sklearnEstimator.get_params(deep=False))
        X_all = _stack_x(work[self.xCol]).astype(np.float32)
        if hasattr(step, "check_static"):
            try:
                step.check_static(static, X_all.shape[1])
            except ValueError:
                # configs the compiled path cannot serve (PCA 'mle'/None
                # n_components, out-of-range widths) go straight to the
                # host loop — sklearn raises its own error there if the
                # config is genuinely invalid; the warning below is
                # reserved for unexpected fleet failures
                return None, pairs
        min_needed = (step.min_group_size(static)
                      if hasattr(step, "min_group_size") else 1)

        eligible, host_pairs = [], []
        for key, pdf in pairs:
            (eligible if len(pdf) >= min_needed else host_pairs).append(
                (key, pdf))
        if not eligible:
            return None, host_pairs

        try:
            fleet_keys, states = self._fit_bucketed(
                eligible, X_all, None, None,
                lambda Xg, wg: step.fit(static, Xg, wg))
        except Exception as exc:
            # unsupported static config (e.g. PCA 'mle') -> host loop
            import warnings
            warnings.warn(
                f"compiled keyed transformer fleet failed ({exc!r}); "
                "falling back to per-key host fits", UserWarning)
            return None, host_pairs + eligible
        return dict(
            kind="step", step=step, models=states, meta={}, static=static,
            key_index={k: i for i, k in enumerate(fleet_keys)}), host_pairs


class TpuTransformer:
    """A fitted transformer state as its device representation — the
    transformer-type counterpart of converter.TpuModel, exposed per key by
    `KeyedModel.keyedModels`."""

    def __init__(self, step, state, static):
        self.step = step
        self.state = state
        self.static = static

    def transform(self, X):
        import jax.numpy as jnp
        X = jnp.asarray(np.asarray(X), jnp.float32)
        return np.asarray(self.step.apply(self.static, self.state, X))

    def __repr__(self):
        return f"TpuTransformer(step={self.step.name})"


class KeyedModel:
    """The fitted per-key fleet.  `keyedModels` exposes the per-key
    estimators as a DataFrame like the reference's model DataFrame (minus
    the pickling)."""

    def __init__(self, keyCols, xCol, yCol, outputCol, estimatorType,
                 models: Optional[Dict[tuple, Any]], fleet=None):
        self.keyCols = list(keyCols)
        self.xCol = xCol
        self.yCol = yCol
        self.outputCol = outputCol
        self.estimatorType = estimatorType
        self.models = models            # host fleet: {key: fitted sklearn}
        self.fleet = fleet              # compiled fleet: stacked pytrees

    @property
    def backend(self) -> str:
        """"tpu" (all keys in the compiled fleet), "host" (all keys fitted
        by the per-key sklearn loop), or "hybrid" (keys the compiled path
        cannot serve — too small, missing classes — were host-fitted while
        the rest stayed on the fleet)."""
        if self.fleet is not None and self.models:
            return "hybrid"
        return "tpu" if self.fleet is not None else "host"

    @property
    def keyedModels(self) -> pd.DataFrame:
        """One row per key with an `estimator` cell that supports
        `.predict`/`.transform` on BOTH backends (fitted sklearn estimator
        on the host path, a TpuModel/TpuTransformer view of the stacked
        pytree on the fleet path)."""
        rows = []
        if self.fleet is not None:
            import jax
            from spark_sklearn_tpu.convert.converter import TpuModel
            for key, i in self.fleet["key_index"].items():
                leaf = jax.tree_util.tree_map(
                    lambda a: a[i], self.fleet["models"])
                if self.fleet["kind"] == "step":
                    view: Any = TpuTransformer(
                        self.fleet["step"], leaf, self.fleet["static"])
                else:
                    view = TpuModel(self.fleet["family"], leaf,
                                    self.fleet["static"], self.fleet["meta"])
                rows.append(dict(zip(self.keyCols, key), estimator=view))
        if self.models:
            for key, est in self.models.items():
                rows.append(dict(zip(self.keyCols, key), estimator=est))
        return pd.DataFrame(rows)

    def transform(self, df: pd.DataFrame) -> pd.DataFrame:
        """Per-key apply: predictor -> predict (float), clusterer -> predict
        (int), transformer -> transform (vector).  Keys never seen in fit
        yield NaN/None rows (the reference's join drops them; keeping the
        row with a null is the friendlier DataFrame-native contract)."""
        # positional reassembly: robust to duplicate index labels and to
        # NaN keys (groupby(dropna=False) keeps those rows; their key has no
        # fitted model so they get null output)
        orig_index = df.index
        work = df.reset_index(drop=True)
        out_values: List[Any] = [None] * len(work)
        fleet_groups = []
        for key, pdf in work.groupby(self.keyCols, sort=False, dropna=False):
            if not isinstance(key, tuple):
                key = (key,)
            pos = pdf.index.to_numpy()
            if self.fleet is not None and \
                    key in self.fleet["key_index"]:
                # deferred: all fleet keys predict together, bucketed —
                # one device launch per bucket instead of one per key
                fleet_groups.append((key, pdf, pos))
                continue
            est = self.models.get(key) if self.models else None
            if est is None:
                fill = None if self.estimatorType == "transformer" else np.nan
                for p in pos:
                    out_values[p] = fill
            else:
                X = _stack_x(pdf[self.xCol])
                if self.estimatorType == "transformer":
                    vals = list(np.asarray(est.transform(X)))
                elif self.estimatorType == "clusterer":
                    vals = list(np.asarray(est.predict(X), dtype=np.int64))
                else:
                    pred = np.asarray(est.predict(X))
                    if np.issubdtype(pred.dtype, np.number):
                        pred = pred.astype(np.float64)
                    vals = list(pred)  # string labels pass through as-is
                for p, v in zip(pos, vals):
                    out_values[p] = v
        if fleet_groups:
            for (key, pdf, pos), vals in zip(
                    fleet_groups, self._fleet_predict_all(fleet_groups)):
                for p, v in zip(pos, vals):
                    out_values[p] = v
        res = df.copy()
        res[self.outputCol] = pd.Series(out_values, index=orig_index)
        return res

    def _fleet_predict_all(self, fleet_groups):
        """Bucketed batch predict/transform from the stacked-pytree
        fleet: groups are padded to bucket lengths, each bucket runs ONE
        vmapped program over (gathered model, padded rows) — a per-key
        device dispatch (~ms of tunnel latency each) would dominate
        transform wall at fleet scale.  Yields one value list per group,
        in `fleet_groups` order."""
        import jax
        import jax.numpy as jnp

        fleet = self.fleet
        static = fleet["static"]
        if fleet["kind"] == "step":
            step = fleet["step"]

            def predict_one(model, X):
                return step.apply(static, model, X)
        else:
            fam = fleet["family"]
            meta = fleet["meta"]

            def predict_one(model, X):
                return fam.predict(model, static, X, meta)

        launch = jax.jit(jax.vmap(predict_one))
        mats = [_stack_x(pdf[self.xCol]).astype(np.float32)
                for _, pdf, _ in fleet_groups]
        midx = np.asarray([fleet["key_index"][key]
                           for key, _, _ in fleet_groups])
        buckets: Dict[int, list] = {}
        for i, m in enumerate(mats):
            buckets.setdefault(bucket_len(len(m)), []).append(i)
        outs: List[Any] = [None] * len(mats)
        d = mats[0].shape[1]
        for L in sorted(buckets):
            idxs = buckets[L]
            Xs = np.zeros((len(idxs), L, d), np.float32)
            for j, gi in enumerate(idxs):
                Xs[j, :len(mats[gi])] = mats[gi]
            models = jax.tree_util.tree_map(
                lambda a: a[midx[np.asarray(idxs)]], fleet["models"])
            Y = np.asarray(launch(models, jnp.asarray(Xs)))
            for j, gi in enumerate(idxs):
                outs[gi] = Y[j, :len(mats[gi])]
        for out in outs:
            if fleet["kind"] == "step":
                yield list(out.astype(np.float64))
            elif fleet["family"].is_classifier:
                yield list(fleet["meta"]["classes"][out.astype(np.int64)])
            elif self.estimatorType == "clusterer":
                yield list(out.astype(np.int64))
            else:
                yield list(out.astype(np.float64))
