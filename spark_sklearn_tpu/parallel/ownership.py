"""Launch-ownership protocol — who owns a launch's scoped resources.

ROADMAP open item 3 ("one launch, many journals/supervisors") needs a
name for the thing that owns launch-scoped state ACROSS the pipeline
seam: the chunk-id namespace journal lines resume under, the shared
pipeline and counter baselines a multi-rung search accumulates into,
and — since cross-search launch fusion — the member set of one device
program serving several searches' chunks at once.

Before this module that contract was duck typing: ``search/halving.py``
stuffed a ``_RungContext`` onto the search object and ``search/grid.py``
probed ``getattr(self, "_rung_ctx", None)`` for whatever attributes it
hoped were there.  Now the contract is explicit:

  - :class:`LaunchOwner` is the base type.  It declares the attributes
    the engine (grid) reads from an attached owner, with inert
    defaults, so a new owner kind cannot silently miss part of the
    contract — and ``isinstance`` replaces attribute-probing.
  - :func:`attach_owner` / :func:`detach_owner` / :func:`current_owner`
    are the ONLY way owners travel on a search object.  halving
    attaches its rung context around the rung loop; grid consults
    ``current_owner`` instead of a private attribute it does not own.
  - ``parallel/pipeline.py``'s :class:`~spark_sklearn_tpu.parallel.
    pipeline.FusedLaunch` is the other owner kind: ONE launch whose
    members keep their own journals and fault supervisors — the
    scatter side of cross-search fusion (``serve/executor.py``).

Deliberately import-light (stdlib only): halving, grid, pipeline and
the executor all import this module, so it must never pull jax.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

__all__ = [
    "LaunchOwner",
    "attach_owner",
    "current_owner",
    "detach_owner",
]

#: the single, documented attribute owners travel on (set/cleared only
#: through attach_owner/detach_owner below)
_ATTR = "_launch_owner"


class LaunchOwner:
    """Base of the launch-ownership protocol.

    An owner is the object holding launch-scoped resources that outlive
    (or span) individual ``LaunchItem``s:

      - a halving **rung context** owns the whole multi-rung search's
        shared pipeline, report registry and counter baselines, plus
        the per-rung chunk-id namespace (``ns``) journal lines resume
        under;
      - a **fused launch** owns one device program executing several
        searches' chunks — each member keeps its own journal lines and
        fault supervisor (one launch, many journals/supervisors).

    The class attributes below are the contract ``grid._run_groups``
    reads from an attached owner; subclasses override what they mean.
    ``kind`` names the owner flavor for logs and tests.
    """

    kind: str = "owner"
    #: chunk-id namespace prefix ("" = the search's root namespace)
    ns: str = ""
    #: rung/iteration index (0 for single-shot owners)
    itr: int = 0
    #: budgeted resource name (halving), "" when not resource-scoped
    resource: str = ""
    #: mid-search geometry re-planning enabled for this owner
    replan: bool = False
    min_rung_width: int = 0
    n_resources: int = 0
    #: shared cross-rung resources (None = per-call, grid's default)
    pipeline: Any = None
    registry: Any = None
    #: counter baselines shared across the owner's scope
    cache0: Any = None
    builds0: Any = None
    dp_before: Any = None
    ps_before: Any = None
    mem_before: Any = None
    #: per-scope bookkeeping grid accumulates into
    planned_total: int = 0
    launches_seen: int = 0
    prev_pipe_wall: float = 0.0
    lanes_reclaimed_total: int = 0

    def members(self) -> List["LaunchOwner"]:
        """The owners sharing this launch scope (a fused launch returns
        its member specs; scalar owners return themselves)."""
        return [self]


def attach_owner(search: Any, owner: LaunchOwner) -> LaunchOwner:
    """Attach ``owner`` to ``search`` for the duration of its scope.
    Rejects non-:class:`LaunchOwner` objects — the protocol is explicit
    now, never duck-typed — and nested attachment (an owner must be
    detached before the next one attaches)."""
    if not isinstance(owner, LaunchOwner):
        raise TypeError(
            f"launch owner must be a LaunchOwner, got "
            f"{type(owner).__name__} (the duck-typed _rung_ctx seam "
            "was replaced by parallel/ownership.py)")
    if getattr(search, _ATTR, None) is not None:
        raise RuntimeError(
            f"search already has an attached {current_owner(search).kind}"
            " owner; detach_owner() it before attaching another")
    setattr(search, _ATTR, owner)
    return owner


def detach_owner(search: Any) -> Optional[LaunchOwner]:
    """Clear and return the search's attached owner (None if none)."""
    owner = getattr(search, _ATTR, None)
    if owner is not None:
        setattr(search, _ATTR, None)
    return owner


def current_owner(search: Any,
                  kind: Optional[str] = None) -> Optional[LaunchOwner]:
    """The owner attached to ``search`` (optionally filtered by
    ``kind``), or None.  This is the engine's read side: grid consults
    it where it used to probe the private ``_rung_ctx`` attribute."""
    owner = getattr(search, _ATTR, None)
    if owner is None:
        return None
    if not isinstance(owner, LaunchOwner):
        raise TypeError(
            f"search carries a non-protocol launch owner "
            f"({type(owner).__name__}); attach it through "
            "parallel/ownership.attach_owner")
    if kind is not None and owner.kind != kind:
        return None
    return owner
