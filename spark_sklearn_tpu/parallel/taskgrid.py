"""Lowering of the (ParameterGrid x KFold) task list onto arrays.

The reference builds `[(params, train, test) for params in grid for train,
test in cv.split(X, y)]` and ships one pickled closure per element to a Spark
executor (reference: grid_search.py _fit; call stack SURVEY §3.1).  Under XLA
the same grid must become *arrays*:

  - candidate params split into a STATIC part (changes the traced program:
    strings, bools, shape-determining ints) and a DYNAMIC part (numeric leaves
    that can batch under `vmap`).  Candidates sharing a static signature form
    one **compile group** — one XLA program, vmapped over the group.
  - folds become fixed-shape **masks** (n_folds, n_samples): 1.0 where the
    sample is in the train (resp. test) split.  Ragged train splits all get
    identical shapes this way (SURVEY §7.3 hard part #2), and every estimator
    fit is a weighted fit with the mask as sample_weight.
"""

from __future__ import annotations

import dataclasses
import os
import time
from typing import Any, Dict, List, Mapping, Optional, Sequence, Tuple

import numpy as np

from spark_sklearn_tpu.obs.trace import get_tracer
from spark_sklearn_tpu.parallel.mesh import pad_to_multiple as _pad_up
from spark_sklearn_tpu.utils import keycheck as _keycheck
from spark_sklearn_tpu.utils.locks import named_lock


@dataclasses.dataclass
class CompileGroup:
    """One statically-shaped batch of candidates: a single jit program,
    vmapped over `n_candidates`."""

    static_params: Dict[str, Any]                # shared by every candidate
    dynamic_params: Dict[str, np.ndarray]        # each shape (n_candidates,)
    candidate_indices: np.ndarray                # (n_candidates,) into the
                                                 # original candidate order
    params_list: List[Dict[str, Any]]            # original dicts, group order

    @property
    def n_candidates(self) -> int:
        return len(self.candidate_indices)


def _is_dynamic_value(v: Any) -> bool:
    """A value can batch under vmap iff it is a real number that does not
    change the traced program.  Bools and ints used as sizes/switches are
    conservatively static unless the family says otherwise."""
    return isinstance(v, (float, np.floating)) and not isinstance(v, bool)


def build_compile_groups(
    candidate_params: Sequence[Mapping[str, Any]],
    dynamic_names: Optional[Sequence[str]] = None,
    dynamic_dtypes: Optional[Mapping[str, Any]] = None,
) -> List[CompileGroup]:
    """Partition candidates into compile groups by static signature.

    `dynamic_names`: param names the estimator family promises are pure
    numeric leaves of the traced fit (e.g. C, alpha, l1_ratio, tol,
    learning_rate_init).  Anything else — and any dynamic-name whose value is
    non-numeric (e.g. C="auto") — is static for that candidate.
    """
    t_span0 = time.perf_counter()
    dynamic_names = set(dynamic_names or ())
    dynamic_dtypes = dict(dynamic_dtypes or {})
    groups: Dict[Tuple, Dict[str, Any]] = {}
    for idx, params in enumerate(candidate_params):
        static, dynamic = {}, {}
        for k, v in params.items():
            if k in dynamic_names and (
                _is_dynamic_value(v)
                or isinstance(v, (int, np.integer))
                and not isinstance(v, bool)
            ):
                dynamic[k] = v
            else:
                static[k] = v
        key = (
            tuple(sorted((k, _hashable(v)) for k, v in static.items())),
            tuple(sorted(dynamic)),
        )
        g = groups.setdefault(
            key, {"static": static, "dyn": {k: [] for k in dynamic},
                  "idx": [], "plist": []})
        for k, v in dynamic.items():
            g["dyn"][k].append(v)
        g["idx"].append(idx)
        g["plist"].append(dict(params))
    out = []
    for g in groups.values():
        dyn = {
            k: np.asarray(v, dtype=dynamic_dtypes.get(k, np.float32))
            for k, v in g["dyn"].items()
        }
        out.append(
            CompileGroup(
                static_params=g["static"],
                dynamic_params=dyn,
                candidate_indices=np.asarray(g["idx"], dtype=np.int64),
                params_list=g["plist"],
            )
        )
    # deterministic order: by first candidate index
    out.sort(key=lambda g: g.candidate_indices[0])
    get_tracer().record_span(
        "build_compile_groups", t_span0, time.perf_counter(),
        n_candidates=len(candidate_params), n_groups=len(out))
    return out


def pad_chunk(arr: np.ndarray, lo: int, hi: int, width: int,
              repeat: int = 1, out: Optional[np.ndarray] = None
              ) -> np.ndarray:
    """Slice `arr[lo:hi]` and pad it to the launch's uniform `width` by
    repeating the last row, so every chunk of a compile group reuses ONE
    compiled program.  `repeat > 1` additionally repeats each row that
    many times (the task-batched layout's candidate-major fold axis).
    Pure host work: this is the "candidate stacking" phase the pipeline
    runs on its stage thread.

    Writes into ONE preallocated output buffer (the old concatenate-
    then-repeat shape allocated twice per chunk); pass `out` — shaped
    ``(width * repeat,) + arr.shape[1:]`` — to reuse a caller-owned
    staging buffer (the donate_chunk_buffers double-buffer ring)."""
    with get_tracer().span("pad_chunk", lo=lo, hi=hi, width=width):
        n = hi - lo
        shape = (width * repeat,) + arr.shape[1:]
        if out is None:
            out = np.empty(shape, arr.dtype)
        elif out.shape != shape or out.dtype != arr.dtype:
            raise ValueError(
                f"pad_chunk out buffer has shape {out.shape}/{out.dtype}, "
                f"expected {shape}/{arr.dtype}")
        chunk = arr[lo:hi]
        if repeat == 1:
            out[:n] = chunk
        else:
            # candidate-major fold axis: row c lands at [c*repeat,
            # (c+1)*repeat) — identical to np.repeat(chunk, repeat, 0)
            out[:n * repeat].reshape((n, repeat) + arr.shape[1:])[:] = \
                chunk[:, None]
        if n < width:
            out[n * repeat:] = arr[hi - 1]
        return out


def split_range(lo: int, hi: int) -> Tuple[int, int, int]:
    """Bisect the candidate range [lo, hi) for OOM recovery: returns
    (lo, mid, hi) with both halves non-empty.  Callers re-pad each half
    to its own launch width via :func:`pad_chunk` — the supervisor's
    half-chunks are ordinary (narrower) chunks of the same compile
    group."""
    if hi - lo < 2:
        raise ValueError(f"range [{lo}, {hi}) cannot be bisected")
    return lo, lo + (hi - lo) // 2, hi


def freeze(v: Any, strict: bool = False):
    """Recursively hashable view of nested params/arrays.

    Shared by compile-group keying (repr fallback: grouping by repr of an
    exotic value is safe — worst case two groups that could have been
    one) and the search's cross-search program cache (`strict=True`:
    raises TypeError so unkeyable captures skip the cache instead of
    aliasing).  Object-dtype ndarrays hash by ELEMENT — ``tobytes()`` on
    them is raw PyObject pointers, and a recycled address would alias two
    different values."""
    if isinstance(v, dict):
        # key by (type, str) so {1: v} and {"1": v} freeze differently —
        # a str(k) collision would alias two distinct cache keys
        return tuple(sorted((type(k).__name__, str(k), freeze(x, strict))
                            for k, x in v.items()))
    if isinstance(v, (list, tuple)):
        return ("__seq__",) + tuple(freeze(x, strict) for x in v)
    if isinstance(v, (set, frozenset)):
        return ("__set__",) + tuple(
            sorted((freeze(x, strict) for x in v), key=repr))
    if isinstance(v, np.ndarray):
        if v.dtype == object:
            return ("__ndo__", v.shape,
                    tuple(freeze(x, strict) for x in v.ravel().tolist()))
        return ("__nd__", v.shape, str(v.dtype), v.tobytes())
    try:
        hash(v)
        return v
    except TypeError:
        if strict:
            raise
        return repr(v)


def _hashable(v: Any):
    return freeze(v)


# ---------------------------------------------------------------------------
# Waste-aware launch geometry
# ---------------------------------------------------------------------------
#
# Chunk width used to be a fixed per-group constant (pad(nc) capped by
# max_tasks_per_batch): every launch paid whatever padding that width
# implied, regardless of the measured launch overhead or per-lane fit
# cost the obs metrics already exposed (the `padding_waste` histogram).
# `plan_geometry` instead chooses each group's width from power-of-two
# buckets by minimizing
#
#     n_launches x launch_overhead  +  padded_lanes x lane_cost
#
# with the cost model fed from measured pipeline timelines
# (`GeometryCostModel.observe`).  The planner is deterministic (same
# inputs -> same plan); the engine additionally reuses the first plan
# computed for a (group structure, constraints) key in-process so a
# later search over the same shapes never recompiles at a new width
# just because the cost model drifted, and pins the chosen plan into
# the checkpoint journal so a resumed search replays the exact same
# chunk ids.


class GeometryMismatchError(RuntimeError):
    """A checkpoint's journalled launch geometry is structurally
    incompatible with the current search (different compile-group sizes
    or sorted-chunking flags): resuming would mix chunk ids across
    geometries.  Delete the checkpoint file or restore the original
    configuration (``sort_candidates`` / the candidate grid)."""


#: planner defaults before any measurement exists: ~10 ms of host-side
#: overhead per launch (dispatch + gather + finalize) and ~1 ms of
#: device compute per (candidate x fold) lane — deliberately
#: padding-averse so the cold plan never inflates a launch by more than
#: the cost of a handful of extra launches.
DEFAULT_LAUNCH_OVERHEAD_S = 0.010
DEFAULT_LANE_COST_S = 1e-3


class GeometryCostModel:
    """Measured per-launch overhead and per-lane cost, EMA-updated from
    each search's pipeline timeline (`observe`).  One process-global
    instance (:func:`geometry_cost_model`) feeds the planner."""

    def __init__(self,
                 launch_overhead_s: float = DEFAULT_LAUNCH_OVERHEAD_S,
                 lane_cost_s: float = DEFAULT_LANE_COST_S):
        #: the process-global instance is observed into at the end of
        #: every search — concurrent searches on different threads
        #: update it through this lock
        self._lock = named_lock("taskgrid.GeometryCostModel._lock")
        self.launch_overhead_s = float(launch_overhead_s)
        self.lane_cost_s = float(lane_cost_s)
        self.compile_wall_s = 0.0
        self.n_observations = 0

    def observe(self, launches,
                n_builds: Optional[int] = None) -> None:
        """Fold one search's per-launch timeline records (the
        ``search_report["pipeline"]["launches"]`` series) into the
        model.  Overhead is the MEDIAN per-launch host-side wall
        (robust to the first launch's trace+compile landing in
        dispatch_s); lane cost is total device compute over total real
        lanes; the excess dispatch over the median is recorded as the
        observed compile wall.

        ``n_builds`` — how many XLA programs were actually built behind
        this timeline slice — normalizes the compile wall to ONE
        program.  The attribution doctor prices modeled compile time as
        ``n_compiles x compile_wall_s``, so an aggregate (per-search)
        excess double-counts whenever several launches share one
        program: a scanned compile group builds one program but runs
        many chunks.  With ``n_builds=0`` the slice compiled nothing
        and its dispatch jitter is NOT folded into the compile wall at
        all.  ``None`` keeps the legacy per-slice aggregate (callers
        that cannot count builds)."""
        recs = [r for r in (launches or []) if r.get("n_tasks", 0) > 0]
        if not recs:
            return
        overheads = sorted(
            r.get("stage_wait_s", 0.0) + r.get("dispatch_s", 0.0)
            + r.get("gather_s", 0.0) + r.get("finalize_s", 0.0)
            for r in recs)
        # LOWER median: with few launches the upper median may itself
        # be a trace+compile outlier
        med_overhead = overheads[(len(overheads) - 1) // 2]
        compute = sum(r.get("compute_s", 0.0) for r in recs)
        lanes = sum(r["n_tasks"] for r in recs)
        compile_excess: Optional[float] = sum(
            max(0.0, o - med_overhead) for o in overheads)
        if n_builds is not None:
            # per-PROGRAM compile lane: divide the slice's excess over
            # the builds that caused it, or skip the EMA entirely when
            # nothing compiled (the excess is then launch jitter, not
            # compile wall)
            compile_excess = (compile_excess / n_builds
                              if n_builds > 0 else None)
        with self._lock:
            lane_cost = compute / lanes if lanes else self.lane_cost_s
            alpha = 0.5 if self.n_observations else 1.0
            self.launch_overhead_s += alpha * (
                med_overhead - self.launch_overhead_s)
            self.lane_cost_s += alpha * (lane_cost - self.lane_cost_s)
            if compile_excess is not None:
                self.compile_wall_s += alpha * (
                    compile_excess - self.compile_wall_s)
            self.n_observations += 1

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "launch_overhead_s": round(self.launch_overhead_s, 6),
                "lane_cost_s": round(self.lane_cost_s, 8),
                "compile_wall_s": round(self.compile_wall_s, 6),
                "n_observations": self.n_observations,
                "source": "measured" if self.n_observations else "default",
            }

    def state_dict(self) -> Dict[str, Any]:
        """Unrounded EMA state for cross-process persistence (the
        program store's ``plans.json``)."""
        with self._lock:
            return {
                "launch_overhead_s": self.launch_overhead_s,
                "lane_cost_s": self.lane_cost_s,
                "compile_wall_s": self.compile_wall_s,
                "n_observations": self.n_observations,
            }

    def load_state(self, state: Mapping[str, Any]) -> bool:
        """Adopt a persisted EMA state when it has seen MORE searches
        than this process — a fresh worker prices its launch geometry
        from the fleet's measured walls instead of the padding-averse
        defaults, while a process with its own (newer) measurements
        keeps them.  Returns whether the state was adopted."""
        try:
            n = int(state["n_observations"])
            overhead = float(state["launch_overhead_s"])
            lane = float(state["lane_cost_s"])
            compile_wall = float(state.get("compile_wall_s", 0.0))
        except (KeyError, TypeError, ValueError):
            return False
        if not (np.isfinite(overhead) and np.isfinite(lane)
                and overhead >= 0.0 and lane >= 0.0):
            return False
        with self._lock:
            if n <= self.n_observations:
                return False
            self.launch_overhead_s = overhead
            self.lane_cost_s = lane
            self.compile_wall_s = compile_wall
            self.n_observations = n
            return True


_COST_MODEL = GeometryCostModel()


def geometry_cost_model() -> GeometryCostModel:
    """The process-global cost model the engine observes into."""
    return _COST_MODEL


@dataclasses.dataclass
class GroupGeometry:
    """One compile group's planned launch shape."""

    group: int
    n_candidates: int
    width: int               # uniform chunk width (padded lane count / fold)
    n_chunks: int
    sorted: bool             # convergence-sorted chunking active
    #: the HBM width ceiling (memledger.width_cap) bound this group's
    #: width below the planner's cost-optimal choice.  Defaulted so
    #: pre-ledger journalled plans still deserialize.
    capped: bool = False

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class GeometryPlan:
    """The planned geometry of a whole search: per-group widths plus
    the cost-model snapshot that produced them.  Serialized verbatim
    into the checkpoint journal (``{"meta": "geometry_plan", ...}``
    line) and rendered as ``search_report["geometry"]``."""

    mode: str                              # "auto" | "fixed"
    groups: List[GroupGeometry]
    cost_model: Dict[str, Any]
    source: str = "computed"               # computed | plan-cache | journal

    def signature(self) -> Tuple:
        """Structure identity for resume-mismatch detection: the widths
        may legitimately differ across plans, the group sizes and
        sorted flags may not."""
        return tuple((g.n_candidates, g.sorted) for g in self.groups)

    def widths(self) -> List[int]:
        return [g.width for g in self.groups]

    def to_dict(self) -> Dict[str, Any]:
        return {"mode": self.mode, "source": self.source,
                "cost_model": dict(self.cost_model),
                "groups": [g.to_dict() for g in self.groups]}

    @classmethod
    def from_dict(cls, d: Mapping[str, Any]) -> "GeometryPlan":
        return cls(
            mode=str(d.get("mode", "auto")),
            groups=[GroupGeometry(**g) for g in d.get("groups", [])],
            cost_model=dict(d.get("cost_model", {})),
            source=str(d.get("source", "computed")))

    def report_block(self) -> Dict[str, Any]:
        """The ``search_report["geometry"]`` block (schema pinned in
        ``obs.metrics.GEOMETRY_BLOCK_SCHEMA``)."""
        lanes = sum(g.n_chunks * g.width for g in self.groups)
        real = sum(g.n_candidates for g in self.groups)
        return {
            "mode": self.mode,
            "source": self.source,
            "planned_launches": sum(g.n_chunks for g in self.groups),
            "planned_waste_frac": round(
                (lanes - real) / lanes, 6) if lanes else 0.0,
            "cost_model": dict(self.cost_model),
            "groups": [g.to_dict() for g in self.groups],
        }


def _chunk_cost(nc: int, width: int, n_folds: int, overhead: float,
                lane_cost: float) -> Tuple[float, int, int]:
    """(cost, n_chunks, width) of running `nc` candidates at `width`:
    launches pay `overhead` each, padded lanes pay `lane_cost` each."""
    n_chunks = -(-nc // width)
    waste_lanes = (n_chunks * width - nc) * n_folds
    return (n_chunks * overhead + waste_lanes * lane_cost,
            n_chunks, width)


#: chunk-loop strategies: "per_chunk" dispatches one launch per chunk
#: (the default, resumable/faultable at chunk granularity); "scan"
#: rolls a compile group's chunk loop into the program via ``lax.scan``
#: so a whole scan segment executes as ONE launch.
CHUNK_LOOP_MODES = ("per_chunk", "scan")


def resolve_chunk_loop(config) -> str:
    """The search's chunk-loop strategy: ``TpuConfig.chunk_loop`` wins,
    then the ``SST_CHUNK_LOOP`` env mirror, then ``"per_chunk"`` (the
    byte-identical legacy path)."""
    mode = getattr(config, "chunk_loop", None)
    if mode is None:
        mode = os.environ.get("SST_CHUNK_LOOP", "").strip().lower() or None
    if mode is None:
        return "per_chunk"
    mode = str(mode).strip().lower()
    if mode not in CHUNK_LOOP_MODES:
        raise ValueError(
            f"chunk_loop={mode!r} is not a chunk-loop strategy; "
            f"expected one of {CHUNK_LOOP_MODES}")
    return mode


@dataclasses.dataclass(frozen=True)
class PlanKey:
    """Named-field identity of one geometry plan.

    The plan-cache key grew positionally for five PRs (min_width as a
    bolted-on 9th element, HBM width caps the 10th, the fusion lane
    discount the 11th) until every new planner input meant another
    length-gated ``j[k] if len(j) > k`` in the JSON decoder.  This
    struct names the fields; new planner inputs (``chunk_loop`` is the
    first) arrive as defaulted fields instead of positional appendage.

    Frozen + all-hashable fields, so instances key ``_PLAN_CACHE``
    directly.  :meth:`from_json` is the ONE back-compat decoder: it
    accepts both the named-dict form this process writes and the legacy
    positional list (8/9/10/11 elements) older processes persisted into
    the program store's ``plans.json``.
    """

    sizes: Tuple[int, ...]
    sorted_caps: Tuple[Optional[int], ...]
    n_folds: int
    n_task_shards: int
    max_width: int
    mode: str
    overhead_override: Optional[float]
    lane_cost_override: Optional[float]
    min_width: int = 0
    width_caps: Tuple[Optional[int], ...] = ()
    fusion_lane_discount: float = 0.0
    #: the chunk-loop strategy the plan was priced under ("per_chunk" |
    #: "scan").  Scan-mode plans cache separately — their segment
    #: planning (``plan_scan_segments``) and any future scan-aware
    #: pricing must never alias a per-chunk plan — but today's pricing
    #: is identical by construction: chunk BOUNDARIES have to match
    #: across modes so the checkpoint journal and the per-chunk OOM
    #: fallback stay chunk-id-compatible.
    chunk_loop: str = "per_chunk"
    #: per-group shared-prefix digest (None per group when the group
    #: runs the atomic pipeline path; empty tuple = planner predates
    #: prefixes / non-pipeline search).  Joins the identity so a plan
    #: priced for prefix-staged groups — whose stage-2 chunks carry a
    #: prefix-buffer dependency — never aliases an atomic plan with the
    #: same sizes, and so the journaled geometry replay
    #: (``GeometryMismatchError``) catches a resume whose prefix
    #: grouping drifted from the killed run's.
    prefix: Tuple[Optional[str], ...] = ()

    def to_json(self) -> Dict[str, Any]:
        d = dataclasses.asdict(self)
        d["sizes"] = list(self.sizes)
        d["sorted_caps"] = list(self.sorted_caps)
        d["width_caps"] = list(self.width_caps)
        d["prefix"] = list(self.prefix)
        return d

    @classmethod
    def from_json(cls, j: Any) -> "PlanKey":
        """Decode a persisted key: named dict (current) or legacy
        positional list.  Raises KeyError/IndexError/TypeError/
        ValueError on malformed records (``import_plan_state`` skips
        those)."""
        if isinstance(j, Mapping):
            return cls(
                sizes=tuple(int(x) for x in j["sizes"]),
                sorted_caps=tuple(None if c is None else int(c)
                                  for c in j["sorted_caps"]),
                n_folds=int(j["n_folds"]),
                n_task_shards=int(j["n_task_shards"]),
                max_width=int(j["max_width"]),
                mode=str(j["mode"]),
                overhead_override=(
                    None if j["overhead_override"] is None
                    else float(j["overhead_override"])),
                lane_cost_override=(
                    None if j["lane_cost_override"] is None
                    else float(j["lane_cost_override"])),
                min_width=int(j.get("min_width", 0)),
                width_caps=tuple(
                    None if c is None else int(c)
                    for c in j.get("width_caps",
                                   [None] * len(j["sizes"]))),
                fusion_lane_discount=float(
                    j.get("fusion_lane_discount", 0.0)),
                chunk_loop=str(j.get("chunk_loop", "per_chunk")),
                prefix=tuple(None if p is None else str(p)
                             for p in j.get("prefix", [])))
        # legacy positional lists, length-gated exactly as the old
        # decoder was: min_width rode in after plans.json shipped (8
        # elements = floor 0), HBM caps later still (= uncapped), the
        # fusion discount with cross-search fusion (= solo pricing)
        return cls(
            sizes=tuple(int(x) for x in j[0]),
            sorted_caps=tuple(None if c is None else int(c)
                              for c in j[1]),
            n_folds=int(j[2]), n_task_shards=int(j[3]),
            max_width=int(j[4]), mode=str(j[5]),
            overhead_override=None if j[6] is None else float(j[6]),
            lane_cost_override=None if j[7] is None else float(j[7]),
            min_width=int(j[8]) if len(j) > 8 else 0,
            width_caps=tuple(None if c is None else int(c) for c in j[9])
            if len(j) > 9 else tuple([None] * len(j[0])),
            fusion_lane_discount=float(j[10]) if len(j) > 10 else 0.0)


#: first plan computed for a (structure, constraints) key is reused for
#: the process lifetime — cost-model drift must not re-plan identical
#: searches onto new widths (each new width is a fresh XLA compile).
_PLAN_CACHE: Dict[PlanKey, GeometryPlan] = {}
_PLAN_CACHE_LOCK = named_lock("taskgrid._PLAN_CACHE_LOCK")


def plan_geometry(sizes: Sequence[int], sorted_caps: Sequence[Optional[int]],
                  n_folds: int, n_task_shards: int, max_width: int,
                  mode: str = "auto",
                  cost_model: Optional[GeometryCostModel] = None,
                  overhead_override: Optional[float] = None,
                  lane_cost_override: Optional[float] = None,
                  reuse: bool = False,
                  min_width: int = 0,
                  preferred: Optional[Sequence[Optional[int]]] = None,
                  width_caps: Optional[Sequence[Optional[int]]] = None,
                  fusion_lane_discount: float = 0.0,
                  chunk_loop: str = "per_chunk",
                  prefix: Optional[Sequence[Optional[str]]] = None,
                  ) -> GeometryPlan:
    """Choose every compile group's chunk width.

    ``sizes``: per-group candidate counts; ``sorted_caps``: per-group
    convergence-sorted width (or None when the group is unsorted) —
    sorted groups keep their graded width (the iteration-waste the
    grading removes dominates any padding trade, and the grading IS the
    family's own cost model).  Unsorted groups choose, in ``auto``
    mode, the cheapest of {the legacy zero-padding width} ∪ {power-of-
    two buckets of the task-shard count} under
    ``n_launches x overhead + padded_lanes x lane_cost``; ``fixed``
    reproduces the legacy widths exactly (the bit-compatible escape
    hatch).  Deterministic: same inputs (including the model values)
    -> same plan; ``reuse=True`` additionally serves the first plan
    computed for this structure again for the process lifetime.

    ``width_caps`` gives a per-group HBM width ceiling (the device-
    memory ledger's ``memledger.width_cap``): a capped group's width
    never exceeds it in EITHER mode — a chunk the footprint model says
    cannot fit is never planned, so OOM bisection becomes the fallback
    instead of the discovery mechanism.  Caps bound the floor and the
    preferred-width affinity too, and join the plan-cache key.

    ``fusion_lane_discount`` prices fleet-wide padding: under
    cross-search launch fusion (``serve/executor.py``) a chunk's padded
    lanes are not pure waste — a same-program peer search can fill them
    in a fused launch — so ``auto`` mode scales ``lane_cost`` by
    ``(1 - discount)``, tilting unsorted groups toward the
    fewer-launches/wider-chunks end that fusion amortizes across the
    coalesced width.  0.0 (fusion off, or solo sessions) is exact
    pre-fusion pricing, byte-identical plans.  The discount joins the
    plan-cache key, so fusion-on and fusion-off searches in one
    process never share plans.

    ``chunk_loop`` names the chunk-loop strategy the caller will run
    the plan under ("per_chunk" | "scan") and joins the plan-cache key
    as a named :class:`PlanKey` field.  It does NOT change the chosen
    widths: a scanned group's chunk boundaries must be byte-identical
    to the per-chunk path's, because the checkpoint journal addresses
    results by chunk id and a scanned segment that OOMs falls back to
    per-chunk launches over the SAME chunks.  What scan mode prices
    differently — the carry buffer and the stacked per-segment
    operands — is planned separately by :func:`plan_scan_segments`.

    ``prefix`` names each group's shared-prefix digest (None for
    atomic groups) when the caller runs a prefix-staged Pipeline
    search (``search/prefix.py``).  Like ``chunk_loop`` it does not
    change the chosen widths — suffix chunks cover the same candidate
    ranges either way — but it joins the :class:`PlanKey` so
    prefix-staged plans journal, cache and replay separately from
    atomic plans over the same sizes, and a resume whose prefix
    grouping drifted trips the journaled-geometry check.

    ``min_width`` floors every auto-chosen unsorted width (rounded up
    to the shard multiple, capped by ``max_width``) — the halving
    scheduler's ``TpuConfig.min_rung_width`` guard against
    pathologically narrow late-rung launches.  ``preferred`` gives a
    per-group already-compiled width: a valid preferred width whose
    plan cost is within the model's measured ``compile_wall_s`` of the
    optimum wins, so a mid-search re-plan (halving rung k+1) reuses
    the program compiled at rung k's width instead of paying a fresh
    trace+compile for a marginal padding saving.  Preferences are
    process-history-dependent, so a ``preferred`` plan is never cached
    (callers pass ``reuse=False``).
    """
    if mode not in ("auto", "fixed"):
        raise ValueError(
            f"geometry_mode must be 'auto' or 'fixed', got {mode!r}")
    sizes = [int(n) for n in sizes]
    sorted_caps = [None if c is None else int(c) for c in sorted_caps]
    if preferred is not None and reuse:
        raise ValueError(
            "preferred widths depend on process compile history and "
            "must not enter the plan cache; pass reuse=False")
    caps = [None] * len(sizes)
    if width_caps is not None:
        for gi, c in enumerate(width_caps):
            if c is None:
                continue
            c = int(c)
            # normalize to a launchable width: shard-multiple, at least
            # one shard stripe, never beyond the task cap
            c -= c % max(1, n_task_shards)
            caps[gi] = max(n_task_shards, min(int(max_width), c))
    fusion_lane_discount = min(1.0, max(0.0, float(fusion_lane_discount)))
    if chunk_loop not in CHUNK_LOOP_MODES:
        raise ValueError(
            f"chunk_loop must be one of {CHUNK_LOOP_MODES}, "
            f"got {chunk_loop!r}")
    prefix_key: Tuple[Optional[str], ...] = ()
    if prefix is not None:
        if len(prefix) != len(sizes):
            raise ValueError(
                f"prefix digests ({len(prefix)}) must match groups "
                f"({len(sizes)})")
        prefix_key = tuple(None if p is None else str(p) for p in prefix)
    cache_key = PlanKey(
        sizes=tuple(sizes), sorted_caps=tuple(sorted_caps),
        n_folds=int(n_folds), n_task_shards=int(n_task_shards),
        max_width=int(max_width), mode=mode,
        overhead_override=overhead_override,
        lane_cost_override=lane_cost_override,
        min_width=int(min_width), width_caps=tuple(caps),
        fusion_lane_discount=fusion_lane_discount,
        chunk_loop=str(chunk_loop), prefix=prefix_key)
    # record-only: PlanKey's named fields ARE the declared planner
    # inputs, so the SST_KEYCHECK log just tracks which plans a run
    # keyed (the toggle-a-knob tests diff these sets across configs)
    _keycheck.note("plan_key", cache_key, detail=mode)
    if reuse:
        with _PLAN_CACHE_LOCK:
            hit = _PLAN_CACHE.get(cache_key)
        if hit is not None:
            # plans seeded from the persistent program store keep their
            # provenance so search_report["geometry"] shows the fresh
            # process replayed the fleet's widths, not its own pricing
            return dataclasses.replace(
                hit, source="store" if hit.source == "store"
                else "plan-cache")

    model = cost_model or geometry_cost_model()
    overhead = (overhead_override if overhead_override is not None
                else model.launch_overhead_s)
    lane_cost = (lane_cost_override if lane_cost_override is not None
                 else model.lane_cost_s)
    # fleet-wide padding: fused peers can fill padded lanes, so they
    # price below solo waste (0.0 = exact pre-fusion costing)
    lane_cost *= (1.0 - fusion_lane_discount)
    snap = model.snapshot()
    if overhead_override is not None or lane_cost_override is not None:
        snap = {**snap, "launch_overhead_s": overhead,
                "lane_cost_s": lane_cost, "source": "override"}

    # width floor: shard-multiple, never beyond the HBM bound
    floor_w = 0
    if min_width:
        floor_w = min(max_width, _pad_up(int(min_width), n_task_shards))
    # the width-affinity allowance: a preferred (already-compiled)
    # width may cost up to this much more than the optimum before a
    # fresh compile is judged worth it.  Manual overhead/lane overrides
    # pin the geometry deterministically (tests, operators who know
    # their costs), so they zero the allowance too — otherwise a
    # measured compile wall would silently re-widen "deterministic"
    # plans.
    compile_cost = 0.0 if (overhead_override is not None
                           or lane_cost_override is not None) \
        else float(snap.get("compile_wall_s", 0.0) or 0.0)

    groups = []
    for gi, nc in enumerate(sizes):
        base_w = min(_pad_up(nc, n_task_shards), max_width)
        base_w = max(base_w, n_task_shards)
        hbm_cap = caps[gi]
        cap = sorted_caps[gi]
        if cap is not None:
            # convergence grading pins the width in both modes — the
            # HBM ceiling still bounds it (memory beats grading)
            width = cap if hbm_cap is None else min(cap, hbm_cap)
        elif mode == "fixed":
            width = base_w if hbm_cap is None else min(base_w, hbm_cap)
        else:
            # power-of-two buckets of the shard count, capped by the
            # HBM bound and by the first bucket able to hold the whole
            # group (wider would only add padding); the legacy width
            # competes too, so a zero-waste single launch is never lost
            candidates = {base_w}
            w = n_task_shards
            hold_all = _pad_up(nc, n_task_shards)
            while w <= max_width:
                candidates.add(w)
                if w >= hold_all:
                    break
                w *= 2
            if floor_w:
                candidates = {w_ for w_ in candidates if w_ >= floor_w}
                candidates.add(floor_w)
            if hbm_cap is not None:
                # the HBM ceiling wins over the min-width floor: a
                # floor the budget cannot hold would plan a chunk the
                # model already knows will not fit
                candidates = {w_ for w_ in candidates if w_ <= hbm_cap}
                candidates.add(hbm_cap)
            # total order (cost, n_chunks, width): ties prefer fewer
            # launches, then the narrower (cheaper-HBM) width
            width = min(
                sorted(candidates),
                key=lambda w_: _chunk_cost(nc, w_, n_folds, overhead,
                                           lane_cost))
            pref = preferred[gi] if preferred is not None else None
            if pref is not None:
                pref = int(pref)
                if pref >= max(n_task_shards, floor_w) \
                        and pref <= max_width \
                        and (hbm_cap is None or pref <= hbm_cap) \
                        and pref % n_task_shards == 0 and pref != width:
                    # width affinity: an already-compiled width wins
                    # when its extra plan cost is under the measured
                    # compile wall a new width would pay
                    c_pref = _chunk_cost(nc, pref, n_folds, overhead,
                                         lane_cost)[0]
                    c_opt = _chunk_cost(nc, width, n_folds, overhead,
                                        lane_cost)[0]
                    if c_pref <= c_opt + compile_cost:
                        width = pref
        groups.append(GroupGeometry(
            group=gi, n_candidates=nc, width=int(width),
            n_chunks=-(-nc // int(width)), sorted=cap is not None,
            capped=hbm_cap is not None and int(width) == hbm_cap
            and hbm_cap < base_w))
    plan = GeometryPlan(mode=mode, groups=groups, cost_model=snap)
    if reuse:
        with _PLAN_CACHE_LOCK:
            # first plan computed for a structure wins: a concurrent
            # search that raced this one keeps serving the earlier
            # entry so widths never flap mid-process
            plan = _PLAN_CACHE.setdefault(cache_key, plan)
    return plan


# ---------------------------------------------------------------------------
# Cross-process plan persistence (the program store's plans.json)
# ---------------------------------------------------------------------------
#
# The in-process plan cache pins "first plan for a structure wins" so
# cost-model drift never recompiles known shapes at new widths.  The
# program store extends that guarantee ACROSS processes: a fresh worker
# imports the persisted plans before its first search, so it requests
# the same chunk widths — and therefore the same stored AOT programs —
# the publishing process ran, instead of re-pricing from scratch.


def _plan_key_to_json(key: PlanKey) -> Dict[str, Any]:
    return key.to_json()


def _plan_key_from_json(j: Any) -> PlanKey:
    """Named-dict (current) or legacy positional-list (pre-PlanKey)
    records — :meth:`PlanKey.from_json` is the one decoder."""
    return PlanKey.from_json(j)


def export_plan_state() -> Dict[str, Any]:
    """JSON-able snapshot of the process's geometry knowledge: the plan
    cache (structure key -> chosen plan) plus the cost model's EMA
    state."""
    with _PLAN_CACHE_LOCK:
        items = list(_PLAN_CACHE.items())
    return {
        "cost_model": geometry_cost_model().state_dict(),
        "plans": [{"key": _plan_key_to_json(k), "plan": p.to_dict()}
                  for k, p in items],
    }


def import_plan_state(state: Mapping[str, Any]) -> int:
    """Seed the plan cache (and cost model) from a persisted snapshot.
    In-process plans always win (``setdefault`` — widths never flap
    mid-process); malformed records are skipped, never errors.  Returns
    how many plans were newly seeded."""
    cm = state.get("cost_model")
    if cm:
        geometry_cost_model().load_state(cm)
    n = 0
    for rec in state.get("plans", ()):
        try:
            key = _plan_key_from_json(rec["key"])
            plan = GeometryPlan.from_dict(rec["plan"])
        except (KeyError, IndexError, TypeError, ValueError):
            continue
        plan = dataclasses.replace(plan, source="store")
        with _PLAN_CACHE_LOCK:
            if _PLAN_CACHE.setdefault(key, plan) is plan:
                n += 1
    return n


def build_fold_masks(
    cv_splits: Sequence[Tuple[np.ndarray, np.ndarray]],
    n_samples: int,
    dtype=np.float32,
) -> Tuple[np.ndarray, np.ndarray]:
    """(train_idx, test_idx) pairs -> dense (n_folds, n_samples) masks.

    Reference counterpart: each Spark task slices X[train]/X[test] with ragged
    index arrays (grid_search.py -> sklearn _fit_and_score).  Fixed-shape
    masks keep every (candidate x fold) XLA program identical.
    """
    n_folds = len(cv_splits)
    train = np.zeros((n_folds, n_samples), dtype=dtype)
    test = np.zeros((n_folds, n_samples), dtype=dtype)
    for i, (tr, te) in enumerate(cv_splits):
        train[i, tr] = 1.0
        test[i, te] = 1.0
    return train, test


class StreamPlanError(RuntimeError):
    """The streaming-fold planner cannot produce a shard geometry that
    fits the HBM budget (the reserved program footprint alone exceeds
    it, or a single double-buffered row does).  Raise the budget, lower
    the chunk width (``max_tasks_per_batch``), or run ``data_mode=
    "device"`` on hardware that holds the dataset."""


@dataclasses.dataclass
class StreamPlan:
    """The planned sample-shard geometry of one streamed search.

    Like :class:`GeometryPlan` this is a *planning* artifact: the shard
    width is an analytic decision made before the first upload (budget
    minus the modeled resident program footprint, double-buffered), not
    something discovered by OOM trial-and-error.  Serialized verbatim
    into the checkpoint journal (``{"meta": "stream_plan", ...}``) so a
    resumed search replays the EXACT same shard boundaries — per-shard
    partial-statistics journal entries are only addressable under the
    geometry that wrote them."""

    n_samples: int
    shard_rows: int            # uniform rows per shard (last one padded)
    n_shards: int
    row_bytes: int             # modeled host bytes per row, all operands
    target_shard_bytes: int    # the knob that sized it (pre-cap)
    budget_bytes: int          # resolved HBM budget (0 = unbounded)
    reserved_bytes: int        # modeled non-shard resident footprint
    capped: bool = False       # True when the budget shrank the shard

    def signature(self) -> Tuple:
        """Resume identity: shard boundaries may not move between the
        journalling run and the resuming run."""
        return (int(self.n_samples), int(self.shard_rows),
                int(self.n_shards))

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: Mapping[str, Any]) -> "StreamPlan":
        return cls(**{k: d[k] for k in (
            "n_samples", "shard_rows", "n_shards", "row_bytes",
            "target_shard_bytes", "budget_bytes", "reserved_bytes",
            "capped") if k in d})

    def report_block(self) -> Dict[str, Any]:
        """The ``search_report["streaming"]`` planning facts (schema
        pinned in ``obs.metrics.STREAMING_BLOCK_SCHEMA``; an explicit
        literal so sstlint's schema-drift producer reads the keys)."""
        return {
            "n_samples": int(self.n_samples),
            "shard_rows": int(self.shard_rows),
            "n_shards": int(self.n_shards),
            "row_bytes": int(self.row_bytes),
            "target_shard_bytes": int(self.target_shard_bytes),
            "budget_bytes": int(self.budget_bytes),
            "reserved_bytes": int(self.reserved_bytes),
            "capped": bool(self.capped),
        }


#: headroom factor on the modeled shard residency: two staged shard
#: slabs (the pipeline's upload-ahead slot plus the one in compute)
#: never plan past budget/_STREAM_SLAB_MARGIN of the free bytes
_STREAM_SLOTS = 2
_STREAM_SLAB_MARGIN = 1.25


def plan_stream_shards(n_samples: int, row_bytes: int,
                       target_shard_bytes: int, *,
                       budget_bytes: int = 0,
                       reserved_bytes: int = 0,
                       margin: float = _STREAM_SLAB_MARGIN) -> StreamPlan:
    """Analytically size the sample shards of a streamed search.

    ``row_bytes`` is the summed host bytes of ONE row across every
    per-sample operand the engine will slice (X, y, one-hot labels,
    per-shard mask slices) — the ledger's pricing, so sparse X enters
    nnz-proportionally.  The shard width is ``target_shard_bytes``
    worth of rows, shrunk (``capped=True``) when the HBM budget minus
    the ``reserved_bytes`` program footprint cannot double-buffer two
    slabs that big.  Raises :class:`StreamPlanError` instead of
    planning a geometry the model already knows cannot fit."""
    n_samples = int(n_samples)
    row_bytes = max(1, int(row_bytes))
    target = max(1, int(target_shard_bytes))
    rows = max(1, min(n_samples, target // row_bytes))
    capped = False
    budget_bytes = int(budget_bytes or 0)
    if budget_bytes:
        free = budget_bytes - int(reserved_bytes)
        rows_budget = int(free // (_STREAM_SLOTS * row_bytes
                                   * max(1.0, float(margin))))
        if rows_budget < 1:
            raise StreamPlanError(
                "streaming-fold plan cannot fit the HBM budget: "
                f"budget={budget_bytes}B, reserved program footprint="
                f"{reserved_bytes}B leaves no room for "
                f"{_STREAM_SLOTS} x {row_bytes}B-row shard slabs; "
                "raise hbm_budget_bytes, shrink max_tasks_per_batch, "
                "or use data_mode='device'")
        if rows_budget < rows:
            rows = rows_budget
            capped = True
    rows = min(rows, n_samples)
    n_shards = -(-n_samples // rows)
    return StreamPlan(
        n_samples=n_samples, shard_rows=int(rows),
        n_shards=int(n_shards), row_bytes=int(row_bytes),
        target_shard_bytes=int(target), budget_bytes=budget_bytes,
        reserved_bytes=int(reserved_bytes), capped=bool(capped))


# ---------------------------------------------------------------------------
# Scan-segment planning (chunk_loop="scan")
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class ScanSegmentPlan:
    """The planned scan-segment geometry of one compile group under
    ``chunk_loop="scan"``.

    A scanned launch stacks ``segment_len`` chunks' dynamic operands
    into one ``(segment_len, lanes, ...)`` upload and carries the
    score buffer through ``lax.scan`` — the whole slab plus the carry
    is resident for the launch's lifetime, so the segment length is an
    analytic decision against the memory ledger made BEFORE the first
    upload, like :class:`StreamPlan`'s shard width.  ``capped=True``
    records that the HBM budget split the group into more than one
    segment; a budget that cannot even hold a single-chunk segment
    plans ``segment_len=1`` rather than failing — the per-chunk OOM
    fallback (bisection, host bottom-out) takes over from there, which
    is exactly the path an OOMing scanned segment degrades to anyway.
    """

    n_chunks: int
    segment_len: int           # chunks folded into one launch
    n_segments: int
    chunk_bytes: int           # modeled slab bytes per stacked chunk
    carry_bytes: int           # modeled scan-carry residency
    budget_bytes: int          # resolved HBM budget (0 = unbounded)
    reserved_bytes: int        # modeled non-scan resident footprint
    capped: bool = False

    def segments(self) -> List[Tuple[int, int]]:
        """``[lo, hi)`` chunk-index ranges, in launch order."""
        return [(lo, min(lo + self.segment_len, self.n_chunks))
                for lo in range(0, self.n_chunks, self.segment_len)]

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


#: headroom factor on the modeled scanned-slab residency — the stacked
#: operands plus scan outputs never plan past budget/margin of the
#: free bytes (same safety style as the streaming planner's slabs)
_SCAN_SLAB_MARGIN = 1.25


def plan_scan_segments(n_chunks: int, *, chunk_bytes: int,
                       carry_bytes: int = 0,
                       budget_bytes: int = 0,
                       reserved_bytes: int = 0,
                       margin: float = _SCAN_SLAB_MARGIN
                       ) -> ScanSegmentPlan:
    """Analytically size the scan segments of a device-resident chunk
    loop.

    ``chunk_bytes`` is the summed modeled bytes ONE chunk contributes
    to a scanned launch (stacked dynamic operands + its slice of the
    stacked outputs — ``memledger.model_group_footprint``'s pricing);
    ``carry_bytes`` the scan carry (the on-device score buffer a
    halving rung accumulates for its device-resident ``top_k``);
    ``reserved_bytes`` everything already resident (data plane, masks,
    program footprint).  No budget plans ONE segment holding the whole
    group — the melt-the-launch-boundary ideal."""
    n_chunks = max(1, int(n_chunks))
    chunk_bytes = max(1, int(chunk_bytes))
    seg = n_chunks
    budget_bytes = int(budget_bytes or 0)
    if budget_bytes:
        free = (budget_bytes // max(1.0, float(margin))
                - int(reserved_bytes) - int(carry_bytes))
        seg = max(1, min(n_chunks, int(free // chunk_bytes)))
    n_segments = -(-n_chunks // seg)
    return ScanSegmentPlan(
        n_chunks=n_chunks, segment_len=int(seg),
        n_segments=int(n_segments), chunk_bytes=chunk_bytes,
        carry_bytes=int(carry_bytes), budget_bytes=budget_bytes,
        reserved_bytes=int(reserved_bytes), capped=seg < n_chunks)
