"""Lowering of the (ParameterGrid x KFold) task list onto arrays.

The reference builds `[(params, train, test) for params in grid for train,
test in cv.split(X, y)]` and ships one pickled closure per element to a Spark
executor (reference: grid_search.py _fit; call stack SURVEY §3.1).  Under XLA
the same grid must become *arrays*:

  - candidate params split into a STATIC part (changes the traced program:
    strings, bools, shape-determining ints) and a DYNAMIC part (numeric leaves
    that can batch under `vmap`).  Candidates sharing a static signature form
    one **compile group** — one XLA program, vmapped over the group.
  - folds become fixed-shape **masks** (n_folds, n_samples): 1.0 where the
    sample is in the train (resp. test) split.  Ragged train splits all get
    identical shapes this way (SURVEY §7.3 hard part #2), and every estimator
    fit is a weighted fit with the mask as sample_weight.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Dict, List, Mapping, Optional, Sequence, Tuple

import numpy as np

from spark_sklearn_tpu.obs.trace import get_tracer


@dataclasses.dataclass
class CompileGroup:
    """One statically-shaped batch of candidates: a single jit program,
    vmapped over `n_candidates`."""

    static_params: Dict[str, Any]                # shared by every candidate
    dynamic_params: Dict[str, np.ndarray]        # each shape (n_candidates,)
    candidate_indices: np.ndarray                # (n_candidates,) into the
                                                 # original candidate order
    params_list: List[Dict[str, Any]]            # original dicts, group order

    @property
    def n_candidates(self) -> int:
        return len(self.candidate_indices)


def _is_dynamic_value(v: Any) -> bool:
    """A value can batch under vmap iff it is a real number that does not
    change the traced program.  Bools and ints used as sizes/switches are
    conservatively static unless the family says otherwise."""
    return isinstance(v, (float, np.floating)) and not isinstance(v, bool)


def build_compile_groups(
    candidate_params: Sequence[Mapping[str, Any]],
    dynamic_names: Optional[Sequence[str]] = None,
    dynamic_dtypes: Optional[Mapping[str, Any]] = None,
) -> List[CompileGroup]:
    """Partition candidates into compile groups by static signature.

    `dynamic_names`: param names the estimator family promises are pure
    numeric leaves of the traced fit (e.g. C, alpha, l1_ratio, tol,
    learning_rate_init).  Anything else — and any dynamic-name whose value is
    non-numeric (e.g. C="auto") — is static for that candidate.
    """
    t_span0 = time.perf_counter()
    dynamic_names = set(dynamic_names or ())
    dynamic_dtypes = dict(dynamic_dtypes or {})
    groups: Dict[Tuple, Dict[str, Any]] = {}
    for idx, params in enumerate(candidate_params):
        static, dynamic = {}, {}
        for k, v in params.items():
            if k in dynamic_names and (
                _is_dynamic_value(v)
                or isinstance(v, (int, np.integer))
                and not isinstance(v, bool)
            ):
                dynamic[k] = v
            else:
                static[k] = v
        key = (
            tuple(sorted((k, _hashable(v)) for k, v in static.items())),
            tuple(sorted(dynamic)),
        )
        g = groups.setdefault(
            key, {"static": static, "dyn": {k: [] for k in dynamic},
                  "idx": [], "plist": []})
        for k, v in dynamic.items():
            g["dyn"][k].append(v)
        g["idx"].append(idx)
        g["plist"].append(dict(params))
    out = []
    for g in groups.values():
        dyn = {
            k: np.asarray(v, dtype=dynamic_dtypes.get(k, np.float32))
            for k, v in g["dyn"].items()
        }
        out.append(
            CompileGroup(
                static_params=g["static"],
                dynamic_params=dyn,
                candidate_indices=np.asarray(g["idx"], dtype=np.int64),
                params_list=g["plist"],
            )
        )
    # deterministic order: by first candidate index
    out.sort(key=lambda g: g.candidate_indices[0])
    get_tracer().record_span(
        "build_compile_groups", t_span0, time.perf_counter(),
        n_candidates=len(candidate_params), n_groups=len(out))
    return out


def pad_chunk(arr: np.ndarray, lo: int, hi: int, width: int,
              repeat: int = 1) -> np.ndarray:
    """Slice `arr[lo:hi]` and pad it to the launch's uniform `width` by
    repeating the last row, so every chunk of a compile group reuses ONE
    compiled program.  `repeat > 1` additionally repeats each row that
    many times (the task-batched layout's candidate-major fold axis).
    Pure host work: this is the "candidate stacking" phase the pipeline
    runs on its stage thread."""
    with get_tracer().span("pad_chunk", lo=lo, hi=hi, width=width):
        chunk = arr[lo:hi]
        if len(chunk) != width:
            chunk = np.concatenate(
                [chunk, np.repeat(chunk[-1:], width - len(chunk), axis=0)])
        if repeat > 1:
            chunk = np.repeat(chunk, repeat, axis=0)
        return chunk


def split_range(lo: int, hi: int) -> Tuple[int, int, int]:
    """Bisect the candidate range [lo, hi) for OOM recovery: returns
    (lo, mid, hi) with both halves non-empty.  Callers re-pad each half
    to its own launch width via :func:`pad_chunk` — the supervisor's
    half-chunks are ordinary (narrower) chunks of the same compile
    group."""
    if hi - lo < 2:
        raise ValueError(f"range [{lo}, {hi}) cannot be bisected")
    return lo, lo + (hi - lo) // 2, hi


def freeze(v: Any, strict: bool = False):
    """Recursively hashable view of nested params/arrays.

    Shared by compile-group keying (repr fallback: grouping by repr of an
    exotic value is safe — worst case two groups that could have been
    one) and the search's cross-search program cache (`strict=True`:
    raises TypeError so unkeyable captures skip the cache instead of
    aliasing).  Object-dtype ndarrays hash by ELEMENT — ``tobytes()`` on
    them is raw PyObject pointers, and a recycled address would alias two
    different values."""
    if isinstance(v, dict):
        # key by (type, str) so {1: v} and {"1": v} freeze differently —
        # a str(k) collision would alias two distinct cache keys
        return tuple(sorted((type(k).__name__, str(k), freeze(x, strict))
                            for k, x in v.items()))
    if isinstance(v, (list, tuple)):
        return ("__seq__",) + tuple(freeze(x, strict) for x in v)
    if isinstance(v, (set, frozenset)):
        return ("__set__",) + tuple(
            sorted((freeze(x, strict) for x in v), key=repr))
    if isinstance(v, np.ndarray):
        if v.dtype == object:
            return ("__ndo__", v.shape,
                    tuple(freeze(x, strict) for x in v.ravel().tolist()))
        return ("__nd__", v.shape, str(v.dtype), v.tobytes())
    try:
        hash(v)
        return v
    except TypeError:
        if strict:
            raise
        return repr(v)


def _hashable(v: Any):
    return freeze(v)


def build_fold_masks(
    cv_splits: Sequence[Tuple[np.ndarray, np.ndarray]],
    n_samples: int,
    dtype=np.float32,
) -> Tuple[np.ndarray, np.ndarray]:
    """(train_idx, test_idx) pairs -> dense (n_folds, n_samples) masks.

    Reference counterpart: each Spark task slices X[train]/X[test] with ragged
    index arrays (grid_search.py -> sklearn _fit_and_score).  Fixed-shape
    masks keep every (candidate x fold) XLA program identical.
    """
    n_folds = len(cv_splits)
    train = np.zeros((n_folds, n_samples), dtype=dtype)
    test = np.zeros((n_folds, n_samples), dtype=dtype)
    for i, (tr, te) in enumerate(cv_splits):
        train[i, tr] = 1.0
        test[i, te] = 1.0
    return train, test
