"""Pipelined chunk executor — overlap host work with device compute.

The search engine launches its (candidate x fold) grid as a sequence of
chunked XLA programs.  Run synchronously (stage -> dispatch -> block ->
gather, one chunk at a time) every host phase serializes with the device:
staging chunk k+1's dynamic params, gathering chunk k-1's scores, and
lowering the NEXT compile group's program all stall the accelerator —
exactly the executor-overlap problem of distributed-Spark ML (arXiv:
1612.01437) and the pipelined-dispatch answer of MPMD pipeline training
(arXiv:2412.14374).

`ChunkPipeline` runs the same launch sequence double-buffered:

  - a *stage* thread prepares chunk k+1's host inputs (mask tiling,
    candidate stacking, `device_put`) while chunk k executes;
  - the main thread dispatches launches in order (JAX dispatch is async:
    the call returns as soon as the program is enqueued), so a trace or
    compile triggered by the next compile group's first chunk runs while
    the device is still busy with the previous group;
  - a *gather* thread blocks on each launch's outputs, timestamps device
    readiness, runs the (blocking) `device_get` transfer, and finalizes
    results in dispatch order;
  - a *compile* thread AOT-lowers the next compile group's program
    (`jit(...).lower(...).compile()`) so group boundaries stop stalling
    the device; the persistent compilation cache (below) makes the same
    walk survive process restarts.

`depth=0` is the escape hatch: every phase runs inline on the calling
thread in today's synchronous order, bit-for-bit, for debugging and A/B
benchmarks.  Scores are identical at any depth — the pipeline reorders
*host* work only; every launch sees the same program and the same
inputs.

A per-launch timeline (stage/dispatch/compute/gather walls and the
overlap fraction) accumulates into `pipeline_report()` so the win — or
its absence on a host-bound box — is observable in `search_report`.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import deque
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Any, Callable, Dict, List, Optional

import jax

from spark_sklearn_tpu.obs import heartbeat as _heartbeat
from spark_sklearn_tpu.obs import telemetry as _telemetry
from spark_sklearn_tpu.obs.log import get_logger
from spark_sklearn_tpu.obs.trace import (
    current_correlation,
    get_tracer,
    set_correlation,
)
from spark_sklearn_tpu.parallel import dataplane as _dataplane
from spark_sklearn_tpu.parallel import memledger as _memledger
from spark_sklearn_tpu.parallel import ownership
from spark_sklearn_tpu.utils.locks import named_lock

_slog = get_logger(__name__)

__all__ = [
    "ChunkPipeline",
    "FuseSpec",
    "FusedLaunch",
    "LaunchItem",
    "LaunchTimings",
    "enable_persistent_cache",
    "persistent_cache_counts",
    "precompile",
]


# ---------------------------------------------------------------------------
# Persistent XLA compilation cache
# ---------------------------------------------------------------------------

#: process-wide persistent-cache traffic, fed by jax's monitoring events
#: (compiler.py records /jax/compilation_cache/cache_{hits,misses} on
#: every compile request once a cache dir is configured)
_CACHE_EVENTS = {"hits": 0, "misses": 0}
_LISTENER_LOCK = named_lock("pipeline._LISTENER_LOCK")
_LISTENER_INSTALLED = False


def _install_cache_listener() -> None:
    global _LISTENER_INSTALLED
    with _LISTENER_LOCK:
        if _LISTENER_INSTALLED:
            return
        try:
            from jax._src import monitoring
        except ImportError:      # jax moved the module: counts stay zero
            _LISTENER_INSTALLED = True
            return

        def _on_event(event: str, **kwargs) -> None:
            # jax may fire this from whichever thread compiles (the
            # sst-compile worker or the dispatching main thread), so
            # the read-modify-write increments need the lock
            if event == "/jax/compilation_cache/cache_hits":
                with _LISTENER_LOCK:
                    _CACHE_EVENTS["hits"] += 1
            elif event == "/jax/compilation_cache/cache_misses":
                with _LISTENER_LOCK:
                    _CACHE_EVENTS["misses"] += 1

        monitoring.register_event_listener(_on_event)
        _LISTENER_INSTALLED = True


def persistent_cache_counts() -> Dict[str, int]:
    """Cumulative persistent-compile-cache hits/misses this process.
    Callers snapshot before/after a search and report the delta."""
    return dict(_CACHE_EVENTS)


def enable_persistent_cache(cache_dir: Optional[str],
                            min_compile_time_s: float = 0.5) -> bool:
    """Point jax's persistent compilation cache at `cache_dir`.

    Amortizes the cold python->jaxpr->HLO->binary walk across processes
    (bench cold runs, gate re-runs, checkpoint-resume restarts): the
    first process pays the XLA compile, every later process with the
    same program shapes reloads the serialized executable.

    Only-if-different semantics: a search that did not ask for a cache
    never clobbers a user's own `jax_compilation_cache_dir` setting.
    Returns True when a cache directory is active after the call.
    """
    if not cache_dir:
        # a cache the USER configured directly still deserves hit/miss
        # accounting in search_report
        if jax.config.jax_compilation_cache_dir:
            _install_cache_listener()
            return True
        return False
    _install_cache_listener()
    if jax.config.jax_compilation_cache_dir != cache_dir:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        # the threshold rides along only when WE (re)configure the dir —
        # an unchanged cache never clobbers out-of-band tuning
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          float(min_compile_time_s))
    return True


def precompile(jit_fn, *args):
    """AOT-lower and compile `jit_fn` for the given (abstract or
    concrete) arguments; returns the compiled executable, which produces
    bit-identical results to calling `jit_fn` (same jaxpr, same compile
    options).  Raises whatever tracing/compilation raises — callers fall
    back to the plain jit path.

    Store-backed programs (parallel/programstore.StoredProgram, exposed
    via their ``resolve`` hook) consult the persistent artifact store
    BEFORE any lowering: a hit substitutes the deserialized artifact's
    wrapper — no python->jaxpr walk at all — and a miss exports and
    publishes the program so the next cold process hits.  Either way
    the lower+compile below still AOT-compiles the resulting callable
    on this (compile) thread, so group boundaries never stall the
    device, and the persistent XLA cache covers the binary."""
    resolve = getattr(jit_fn, "resolve", None)
    if resolve is not None:
        jit_fn = resolve(*args)
    return jit_fn.lower(*args).compile()


# ---------------------------------------------------------------------------
# Launch pipeline
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class LaunchTimings:
    """Per-launch wall breakdown.  `compute_s` is the device-occupancy
    estimate: time from this launch becoming the head of the device
    queue (max of its dispatch time and the previous launch's readiness)
    to its outputs being ready."""

    stage_s: float = 0.0      # host staging work (thread-side wall)
    stage_wait_s: float = 0.0  # un-hidden staging wait on the dispatcher
    dispatch_s: float = 0.0
    compute_s: float = 0.0
    gather_s: float = 0.0
    finalize_s: float = 0.0
    stage_bytes: int = 0      # host->device bytes the stage transferred
    #: time this launch's dispatch spent waiting in the multi-tenant
    #: fair-share queue (serve/executor.py) — subtracted out of
    #: dispatch_s by the executor's item wrapper so contention never
    #: poisons the geometry cost model's launch-overhead estimate
    queue_wait_s: float = 0.0


@dataclasses.dataclass
class LaunchItem:
    """One device launch plus its host-side phases.

    stage    () -> staged payload (host prep + device_put); optional.
    launch   (staged) -> device outputs.  Runs on the dispatching thread
             in submission order; JAX dispatch is async so it returns as
             soon as the program is enqueued (first call may trace and
             compile — that wall lands in `dispatch_s`).
    wait     (device outputs) -> device outputs, blocking until ready;
             optional (default `jax.block_until_ready`).  The fault
             supervisor (parallel/faults.py) installs its watchdog /
             retry / bisection recovery here — errors from async
             dispatch surface at this blocking point.
    gather   (device outputs) -> host results (the blocking transfer);
             optional.
    finalize (host results, LaunchTimings) -> None.  Runs in submission
             order; result-array writes, checkpointing, and report
             accounting belong here.

    `bisect` / `host_fallback` are recovery hooks consumed by the fault
    supervisor, never by the pipeline itself: `bisect(supervisor)`
    re-runs the launch as narrower half-chunks after an OOM and returns
    the merged result in `gather`'s output shape; `host_fallback()`
    computes the same shape per-candidate on the host (exact sklearn
    error_score semantics) when bisection bottoms out.
    """

    key: str
    launch: Callable[[Any], Any]
    stage: Optional[Callable[[], Any]] = None
    gather: Optional[Callable[[Any], Any]] = None
    finalize: Optional[Callable[[Any, LaunchTimings], None]] = None
    group: int = 0
    kind: str = "launch"
    n_tasks: int = 0
    #: chunks this launch serves — 1 for the per-chunk paths, the
    #: scan-segment member count for kind="scan" items (search/grid.py
    #: chunk_loop="scan"): the timeline then pins the launch-boundary
    #: collapse (one record, many chunks)
    n_chunks: int = 1
    wait: Optional[Callable[[Any], Any]] = None
    bisect: Optional[Callable[[Any], Any]] = None
    host_fallback: Optional[Callable[[], Any]] = None
    #: cross-search fusion handle (a FuseSpec) — present only on items
    #: whose launch may be coalesced with same-key peers from OTHER
    #: searches by the multi-tenant executor (serve/executor.py); the
    #: pipeline itself never reads it
    fuse: Optional["FuseSpec"] = None


@dataclasses.dataclass
class FuseSpec:
    """One search's offer to share a device launch with same-program
    peers from other searches.

    The multi-tenant executor groups queued specs by ``key`` — two specs
    with equal keys run the SAME compiled program on concatenable inputs
    (family + compile-group structure + geometry + broadcast-plane
    identity) — and hands each group to a :class:`FusedLaunch`.

    ``run``/``slice_out`` keep the device details inside the member's
    own closure (search/grid.py builds them next to the solo launch
    path), so this layer stays jax-shape-agnostic:

    run(specs)            stage + execute ONE wide launch covering every
                          member's real rows, in list order, padded once
                          at the coalesced width; returns raw device
                          outputs.
    slice_out(out, off, n) a member's view of those outputs — the rows
                          [off, off+n) — in exactly the shape its solo
                          ``gather`` expects.  vmap lanes are
                          independent, so each member's lanes are
                          bit-identical to its solo launch.
    rows()                the member's real (unpadded) host rows per
                          dynamic param — what ``run`` concatenates.
    """

    key: Any                       # hashable program-identity tuple
    n: int                         # real candidate rows this member adds
    shard: int                     # task-shard multiple widths pad to
    max_width: int                 # member's HBM width ceiling (0 = none)
    rows: Callable[[], Dict[str, Any]]
    run: Callable[[List["FuseSpec"]], Any]
    slice_out: Callable[[Any, int, int], Any]


class FusedLaunch(ownership.LaunchOwner):
    """ONE device launch serving many searches' chunks.

    This is the launch-ownership refactor's second owner kind (the first
    is halving's rung context): the fused launch owns the shared device
    program invocation, while every member search keeps its own journal
    lines, fault supervisor and result buffers — one launch, many
    journals/supervisors.  The executor builds one per coalesced group,
    calls :meth:`run` once on its dispatch loop, and scatters the
    per-member outputs back through each member's reply.

    Fault scatter needs no machinery here: an exception from the wide
    launch is delivered to EVERY member, and each member's supervisor
    recovers by re-running only its OWN [lo, hi) range through its solo
    bisect hook — so an OOM/FATAL bisects to member boundaries first,
    then within the faulting member, and one tenant's poison candidate
    never retries another tenant's rows.
    """

    kind = "fused"

    def __init__(self, specs: List[FuseSpec]):
        if not specs:
            raise ValueError("FusedLaunch needs at least one member")
        self.specs = list(specs)
        self.offsets: List[int] = []
        off = 0
        for s in self.specs:
            self.offsets.append(off)
            off += int(s.n)
        #: total real rows across members (pre-padding)
        self.n_total = off
        self._out: Any = None

    def members(self) -> List[FuseSpec]:
        return list(self.specs)

    def padded_width(self) -> int:
        """The coalesced launch width: total real rows padded up to the
        members' (shared) task-shard multiple."""
        shard = max(1, int(self.specs[0].shard))
        return max(shard, -(-self.n_total // shard) * shard)

    def lanes_padding(self) -> int:
        """Padded-lane waste of the fused launch (the A/B quantity vs
        each member padding separately)."""
        return self.padded_width() - self.n_total

    def run(self) -> Any:
        """Execute the one wide launch (lead member's closure does the
        concatenate/pad/upload/dispatch) and memoize the raw output."""
        self._out = self.specs[0].run(self.specs)
        return self._out

    def member_result(self, i: int) -> Any:
        """Member ``i``'s slice of the fused output, in the exact shape
        its solo launch would have produced."""
        if self._out is None:
            raise RuntimeError("FusedLaunch.run() has not been called")
        s = self.specs[i]
        return s.slice_out(self._out, self.offsets[i], int(s.n))


class ChunkPipeline:
    """Run `LaunchItem`s with staging/compile/gather overlapped against
    device compute (`depth` >= 1), or fully synchronously (`depth` == 0).

    `depth` bounds how many launches may be in flight (dispatched, not
    yet finalized) beyond the one being gathered — double buffering at
    depth 1, deeper lookahead beyond.
    """

    def __init__(self, depth: int = 2, verbose: int = 0,
                 heartbeat: bool = False):
        self.depth = max(0, int(depth))
        self.verbose = int(verbose)
        # in-flight heartbeats (obs/heartbeat.py): per-chunk launches
        # emit a cheap dispatch-time beat when the constructing search
        # resolved heartbeat on (scan segments beacon from the device
        # instead); False keeps the exact-no-op default
        self.heartbeat = bool(heartbeat)
        self.timeline: List[Dict[str, Any]] = []
        self._wall_t0: Optional[float] = None
        # the run epoch: the FIRST run()'s start, stable across rung
        # barriers — per-launch t0_s/t1_s are relative to it, so the
        # attribution analyzer can slice the timeline (and clip tracer
        # spans, which carry the same perf_counter timebase) per rung
        self._epoch: Optional[float] = None
        self._wall_s = 0.0
        self._n_precompiled = 0
        self._compile_executor: Optional[ThreadPoolExecutor] = None
        self._compile_futures: List[Future] = []
        self._tracer = get_tracer()
        # the constructing thread's tenant/handle correlation, applied
        # to the stage/gather/compile worker threads so every span and
        # log line they emit attributes to the owning search
        self._corr = current_correlation()
        # per compile group: [first dispatch t, last finalize t] — the
        # compile-group boundary spans of the exported trace
        self._group_bounds: Dict[int, List[float]] = {}

    # -- compile-ahead ---------------------------------------------------
    def submit_precompile(self, jit_fn, *args,
                          label: str = "") -> Optional[Future]:
        """Queue an AOT lower+compile on the compile thread (pipelined
        mode only; at depth 0 programs compile where they always did —
        at first dispatch).  Returns a Future of the executable, or None
        when running synchronously."""
        if self.depth == 0:
            return None
        if self._compile_executor is None:
            self._compile_executor = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="sst-compile")

        def job():
            set_correlation(self._corr)
            with self._tracer.span("compile", label=label):
                exe = precompile(jit_fn, *args)
            self._n_precompiled += 1
            # device-memory ledger: harvest the compiled executable's
            # XLA memory_analysis (argument/output/temp bytes) where
            # the backend provides one — ground truth for the parts
            # the shape-level footprint model cannot see (exact no-op
            # when no ledger-enabled search is active)
            _memledger.note_compiled(label, exe)
            return exe

        fut = self._compile_executor.submit(job)
        self._compile_futures.append(fut)
        return fut

    # -- execution -------------------------------------------------------
    def run(self, items) -> None:
        """Consume an iterable of LaunchItems.  Exceptions from any
        phase propagate to the caller (first one wins) after the
        pipeline drains; partial results written by earlier finalizes
        remain (checkpoint-resume picks them up)."""
        self._wall_t0 = time.perf_counter()
        if self._epoch is None:
            self._epoch = self._wall_t0
        try:
            if self.depth == 0:
                self._run_sync(items)
            else:
                self._run_pipelined(items)
        finally:
            self._wall_s += time.perf_counter() - self._wall_t0
            self._wall_t0 = None
            # compile-group boundary spans (async: group g+1's first
            # stage may overlap group g's last finalize)
            for g, (t0, t1) in sorted(self._group_bounds.items()):
                self._tracer.record_async(
                    f"compile-group {g}", t0, t1, track="compile-groups",
                    group=g)
            self._group_bounds.clear()

    def drain(self) -> None:
        """Rung barrier (search/halving.py): block until every queued
        compile-ahead job has finished WITHOUT shutting the compile
        executor down.  The halving scheduler drains between rungs so
        a straggler AOT job can never trace under the next rung's jax
        config (e.g. a wants_float64 family's temporarily-enabled x64
        mode restored at the rung boundary), while the compile thread
        stays warm for the next rung's programs.  `run()` may be
        called again afterwards — the timeline and wall accumulate, so
        one report covers every rung."""
        for fut in self._compile_futures:
            if fut.cancelled():
                continue
            try:
                fut.result()
            # AOT compile-ahead is an optimization only: a failed
            # future's consumer already fell back to the jit path, and
            # an unconsumed failure means nothing needed the executable
            # sstlint: disable=launch-except-taxonomy,swallowed-exception
            except Exception:
                pass
        self._compile_futures = []

    def close(self) -> None:
        """Join the compile thread (AOT jobs trace under the caller's
        jax config — e.g. a temporarily-enabled x64 mode — so they must
        not outlive the enclosing search)."""
        if self._compile_executor is not None:
            for fut in self._compile_futures:
                fut.cancel()
            self._compile_executor.shutdown(wait=True)
            self._compile_executor = None
            self._compile_futures = []

    # -- reporting -------------------------------------------------------
    def report(self) -> Dict[str, Any]:
        tl = self.timeline
        walls = {
            "stage_wall_s": sum(t["stage_s"] for t in tl),
            "dispatch_wall_s": sum(t["dispatch_s"] for t in tl),
            "compute_wall_s": sum(t["compute_s"] for t in tl),
            "gather_wall_s": sum(t["gather_s"] for t in tl),
            "finalize_wall_s": sum(t["finalize_s"] for t in tl),
        }
        busy = sum(walls.values())
        wall = self._wall_s
        if self._wall_t0 is not None:     # mid-run snapshot
            wall += time.perf_counter() - self._wall_t0
        host = busy - walls["compute_wall_s"]
        # host work hidden behind device compute, as a fraction of all
        # host work (0 when synchronous: wall ~= busy by construction)
        overlap = 0.0
        if host > 0.0 and wall > 0.0:
            overlap = min(1.0, max(0.0, (busy - wall) / host))
        return {
            "depth": self.depth,
            "n_launches": len(tl),
            "wall_s": round(wall, 4),
            **{k: round(v, 4) for k, v in walls.items()},
            "queue_wait_wall_s": round(
                sum(t.get("queue_wait_s", 0.0) for t in tl), 4),
            "overlap_frac": round(overlap, 4),
            "n_precompiled": self._n_precompiled,
            "stage_bytes_total": sum(
                t.get("stage_bytes", 0) for t in tl),
            "epoch_s": round(self._epoch or 0.0, 6),
            "launches": tl,
        }

    # -- internals -------------------------------------------------------
    @staticmethod
    def _wait_item(item: LaunchItem, out):
        """Block until `out` is ready via the item's wait hook (the
        fault supervisor's interception point) or the plain jax wait.
        Returns the outputs to gather — a recovery may substitute
        them."""
        if item.wait is not None:
            return item.wait(out)
        return jax.block_until_ready(out)

    def _record(self, item: LaunchItem, tm: LaunchTimings,
                t0: Optional[float] = None,
                t1: Optional[float] = None) -> None:
        # fleet telemetry: the launch's device-busy estimate feeds the
        # rolling device-occupancy series (exact no-op when disabled)
        _telemetry.note_launch(tm.compute_s)
        # device-memory ledger: reconcile model vs allocator at the
        # launch boundary (exact no-op off; unmeasurable backends
        # early-out after the first probe)
        _memledger.note_launch_boundary()
        rec = {
            "key": item.key, "group": item.group, "kind": item.kind,
            "n_tasks": item.n_tasks, "n_chunks": int(item.n_chunks),
            "stage_bytes": int(tm.stage_bytes),
            "stage_s": round(tm.stage_s, 6),
            "stage_wait_s": round(tm.stage_wait_s, 6),
            "queue_wait_s": round(tm.queue_wait_s, 6),
            "dispatch_s": round(tm.dispatch_s, 6),
            "compute_s": round(tm.compute_s, 6),
            "gather_s": round(tm.gather_s, 6),
            "finalize_s": round(tm.finalize_s, 6),
        }
        epoch = self._epoch
        if t0 is not None and t1 is not None and epoch is not None:
            rec["t0_s"] = round(t0 - epoch, 6)
            rec["t1_s"] = round(t1 - epoch, 6)
        self.timeline.append(rec)
        if self.verbose > 0:
            # logging channel only (never stdout: launch records have
            # no legacy print contract to preserve)
            _slog.debug(
                "launch %s kind=%s group=%d compute=%.4fs gather=%.4fs",
                item.key, item.kind, item.group, tm.compute_s,
                tm.gather_s, **rec)

    def _note_group(self, group: int, t0: float, t1: float) -> None:
        if not self._tracer.enabled:
            return
        b = self._group_bounds.get(group)
        if b is None:
            self._group_bounds[group] = [t0, t1]
        else:
            b[0] = min(b[0], t0)
            b[1] = max(b[1], t1)

    def _run_sync(self, items) -> None:
        tr = self._tracer
        for item in items:
            tm = LaunchTimings()
            t0 = time.perf_counter()
            if item.stage is not None:
                b0 = _dataplane.bytes_uploaded()
                with tr.span("stage", key=item.key, kind=item.kind,
                             group=item.group):
                    staged = item.stage()
                tm.stage_bytes = _dataplane.bytes_uploaded() - b0
            else:
                staged = None
            t1 = time.perf_counter()
            tm.stage_s = t1 - t0
            with tr.span("dispatch", key=item.key, kind=item.kind,
                         group=item.group):
                out = item.launch(staged)
            if self.heartbeat and item.kind != "scan":
                _heartbeat.note_chunk(item.key, item.group)
            t2 = time.perf_counter()
            tm.dispatch_s = t2 - t1
            with tr.span("compute.wait", key=item.key):
                out = self._wait_item(item, out)
            t3 = time.perf_counter()
            tm.compute_s = t3 - t2
            tr.record_span("compute", t2, t3, track="device",
                           key=item.key, kind=item.kind, group=item.group)
            if item.gather is not None:
                with tr.span("gather", key=item.key):
                    host = item.gather(out)
            else:
                host = None
            t4 = time.perf_counter()
            tm.gather_s = t4 - t3
            if item.finalize is not None:
                with tr.span("finalize", key=item.key):
                    item.finalize(host, tm)
            tm.finalize_s = time.perf_counter() - t4
            t_end = time.perf_counter()
            tr.record_async(f"launch {item.key}", t1, t_end,
                            track="launches", key=item.key,
                            kind=item.kind, group=item.group,
                            n_tasks=item.n_tasks)
            self._note_group(item.group, t1, t_end)
            self._record(item, tm, t0, t_end)

    def _run_pipelined(self, items) -> None:
        depth = self.depth
        tr = self._tracer
        stage_ex = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="sst-stage")
        gather_ex = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="sst-gather")
        # readiness timestamp of the most recently completed launch —
        # owned by the (single) gather thread
        last_ready = [0.0]
        staged: deque = deque()      # (item, stage Future, t_submitted)
        inflight: deque = deque()    # gather Futures, dispatch order
        it = iter(items)
        exhausted = False

        def staged_call(item):
            set_correlation(self._corr)
            t0 = time.perf_counter()
            # bytes accounted via the (single) stage thread's delta of
            # the process-wide data-plane counter — supervisor re-stages
            # on recovery threads land in the global counter only
            b0 = _dataplane.bytes_uploaded()
            with tr.span("stage", key=item.key, kind=item.kind,
                         group=item.group):
                payload = item.stage()
            return (payload, time.perf_counter() - t0,
                    _dataplane.bytes_uploaded() - b0)

        def top_up():
            nonlocal exhausted
            while not exhausted and len(staged) < depth + 1:
                try:
                    nxt = next(it)
                except StopIteration:
                    exhausted = True
                    return
                fut = (stage_ex.submit(staged_call, nxt)
                       if nxt.stage is not None else None)
                staged.append((nxt, fut))

        def gather_job(item, out, t_dispatch0, t_dispatched, tm):
            set_correlation(self._corr)
            with tr.span("compute.wait", key=item.key):
                out = self._wait_item(item, out)
            t_ready = time.perf_counter()
            t_head = max(t_dispatched, last_ready[0])
            tm.compute_s = t_ready - t_head
            last_ready[0] = t_ready
            tr.record_span("compute", t_head, t_ready, track="device",
                           key=item.key, kind=item.kind, group=item.group)
            if item.gather is not None:
                with tr.span("gather", key=item.key):
                    host = item.gather(out)
            else:
                host = None
            t_got = time.perf_counter()
            tm.gather_s = t_got - t_ready
            if item.finalize is not None:
                with tr.span("finalize", key=item.key):
                    item.finalize(host, tm)
            tm.finalize_s = time.perf_counter() - t_got
            t_end = time.perf_counter()
            tr.record_async(f"launch {item.key}", t_dispatch0, t_end,
                            track="launches", key=item.key,
                            kind=item.kind, group=item.group,
                            n_tasks=item.n_tasks)
            self._note_group(item.group, t_dispatch0, t_end)
            self._record(item, tm, t_dispatch0, t_end)

        try:
            top_up()
            while staged:
                item, fut = staged.popleft()
                top_up()   # keep the stage thread fed while we dispatch
                tm = LaunchTimings()
                t0 = time.perf_counter()
                payload = None
                if fut is not None:
                    payload, tm.stage_s, tm.stage_bytes = fut.result()
                t1 = time.perf_counter()
                tm.stage_wait_s = t1 - t0
                with tr.span("dispatch", key=item.key, kind=item.kind,
                             group=item.group):
                    out = item.launch(payload)
                if self.heartbeat and item.kind != "scan":
                    _heartbeat.note_chunk(item.key, item.group)
                t2 = time.perf_counter()
                tm.dispatch_s = t2 - t1
                inflight.append(
                    gather_ex.submit(gather_job, item, out, t1, t2, tm))
                while len(inflight) > depth:
                    inflight.popleft().result()
            while inflight:
                inflight.popleft().result()
        finally:
            # on error: stop feeding, let in-flight work drain, then
            # re-raise from the executor futures above
            for _, fut in staged:
                if fut is not None:
                    fut.cancel()
            stage_ex.shutdown(wait=True)
            gather_ex.shutdown(wait=True)
