"""Device-memory ledger — HBM accounting for every launch the engine plans.

The reference ran many sklearn candidates inside FIXED per-executor
memory; this engine runs them inside fixed HBM — and until this module
it was blind to that budget.  The geometry planner picked chunk widths
from a time-only cost model, the dataplane LRU budgeted itself against
a config number with no view of real headroom, and device memory
exhaustion was *discovered* by catching ``RESOURCE_EXHAUSTED`` and
bisecting (``parallel/faults.py``).  The :class:`MemoryLedger` closes
that gap from both ends:

  - **model** — :func:`model_group_footprint` prices each compile
    group's per-chunk device footprint analytically from the same
    abstract shapes the program store keys on (per-candidate dynamic
    params, the task-batched tiled fold masks, score/health outputs;
    all linear in the chunk width), and :func:`precompile-time
    <note_compiled>` XLA ``memory_analysis`` readings (argument/
    output/temp bytes) ride along where the backend exposes them;
  - **measure** — the ledger samples
    :func:`~spark_sklearn_tpu.obs.memory.device_memory_stats` at launch
    boundaries (``parallel/pipeline.py``) and via the PR 8 telemetry
    sampler, keeping a process high-water mark and the model-vs-
    measured error.  Backends without allocator stats (XLA:CPU) run
    ledger-only with ``measured: False`` — nothing raises, nothing is
    sampled per launch after the first probe;
  - **act** — :func:`width_cap` turns the resolved HBM budget
    (``TpuConfig.hbm_budget_bytes`` / ``SST_HBM_BUDGET_BYTES``, default
    a fraction of detected device memory) into a per-group chunk-width
    ceiling for ``taskgrid.plan_geometry``, so chunks that would not
    fit are never launched — OOM bisection becomes the fallback, not
    the discovery mechanism — and :meth:`MemoryLedger.observe_oom`
    trains a safety margin from the bisections that still happen, so
    the model's blind spots (XLA scratch, fusion temps) tighten the
    ceiling instead of repeating.

Observable everywhere an operator looks: ``search_report["memory"]``
(schema pinned in ``obs.metrics.MEMORY_BLOCK_SCHEMA``), per-device
pressure in the telemetry snapshot and the ``/metrics`` Prometheus
families, ``memory.sample``/``memory.footprint`` trace events, modeled-
vs-budget bytes on every OOM fault event, and a full ledger snapshot
stamped into every flight-recorder bundle — an OOM postmortem finally
shows *what was resident and why*.  ``TpuConfig(memory_ledger=False)``
is the exact-no-op escape hatch: reports and ``cv_results_`` are
byte-identical to the pre-ledger engine.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Any, Dict, List, Optional

import numpy as np

from spark_sklearn_tpu.obs import memory as _obs_memory
from spark_sklearn_tpu.obs.trace import get_tracer
from spark_sklearn_tpu.utils.locks import named_lock

__all__ = [
    "MemoryLedger",
    "dataset_nbytes",
    "get_ledger",
    "ledger_for",
    "model_group_footprint",
    "note_compiled",
    "note_launch_boundary",
    "report_block",
    "width_cap",
]

#: bound on the per-group footprint / compiled-analysis records the
#: ledger keeps for forensics (a long-lived session cycling many
#: searches must not grow without bound)
_MAX_RECORDS = 256

#: the safety margin's ceiling: beyond 8x the model is not a model any
#: more and the operator should size the budget explicitly
_MAX_MARGIN = 8.0

#: bytes of score output per (candidate x fold) task per scorer:
#: one f32 test cell (+ one train cell when requested) — the health
#: flags and iteration scalars are noise next to it
_SCORE_CELL_BYTES = 4


def dataset_nbytes(X) -> int:
    """True host bytes of a dataset for footprint pricing.

    Dense arrays report ``nbytes``; CSR-like matrices (scipy sparse,
    ``sparse.csr.CSRMatrix``) report the sum of their component arrays
    — nnz-proportional, NOT ``n x d``.  scipy sparse matrices have no
    ``nbytes`` attribute at all, so the old ``getattr(X, "nbytes", 0)``
    spelling priced them at ZERO, and any dense-equivalent pricing
    would over-reject by orders of magnitude; both are wrong for
    predictive admission (pinned by test_sparse_path.py)."""
    if X is None:
        return 0
    nb = getattr(X, "nbytes", None)
    if nb is not None and isinstance(X, np.ndarray):
        return int(nb)
    if hasattr(X, "indptr") and hasattr(X, "data"):
        total = 0
        for part in (getattr(X, "data", None),
                     getattr(X, "indices", None),
                     getattr(X, "indptr", None)):
            if part is not None:
                total += int(np.asarray(part).nbytes)
        return total
    if nb is not None:
        return int(nb)
    try:
        return int(np.asarray(X).nbytes)
    except (TypeError, ValueError):
        return 0


def model_group_footprint(dynamic_params: Dict[str, np.ndarray],
                          width: int, n_folds: int, *,
                          task_batched: bool, n_samples: int,
                          mask_itemsize: int = 4, n_scorers: int = 1,
                          return_train: bool = False,
                          dtype_itemsize: int = 4) -> Dict[str, Any]:
    """One compile group's modeled per-chunk device bytes at ``width``.

    Everything is linear in the width, derived from the same abstract
    shapes ``precompile`` builds its ``ShapeDtypeStruct`` signature
    from:

      - ``dyn_bytes`` — the staged dynamic-parameter buffers (repeated
        per fold on the task-batched layout; the all-static ``_pad``
        operand when a group has no dynamic params);
      - ``mask_bytes`` — the task-batched tiled fold masks, the
        dominant per-chunk resident on wide launches (``width x
        n_folds x n_samples``); non-task-batched families consume the
        base masks already counted in the broadcast residents;
      - ``out_bytes`` — per-task score cells (+ train cells) and
        health flags the launch materializes.

    Returns the breakdown plus ``per_candidate_bytes`` (the slope the
    width ceiling divides by) and ``chunk_bytes`` (the total at
    ``width``).  Model-pytree and XLA temp bytes are deliberately NOT
    modeled here — they are backend/fusion-dependent; the ledger's
    safety margin (trained by observed OOMs) and the precompile-time
    ``memory_analysis`` readings cover them.
    """
    width = int(width)
    n_folds = max(1, int(n_folds))
    repeat = n_folds if task_batched else 1
    dyn_per_cand = 0
    for arr in dynamic_params.values():
        arr = np.asarray(arr)
        tail = int(np.prod(arr.shape[1:], dtype=np.int64)) \
            if arr.ndim > 1 else 1
        dyn_per_cand += arr.dtype.itemsize * tail * repeat
    if not dynamic_params and not task_batched:
        # the all-static group's `_pad` candidate-axis operand
        dyn_per_cand = int(dtype_itemsize)
    mask_per_cand = (n_folds * int(n_samples) * int(mask_itemsize)
                     if task_batched else 0)
    out_per_cand = n_folds * (
        int(n_scorers) * (2 if return_train else 1) * _SCORE_CELL_BYTES
        + 1)  # + per-task health flag
    per_cand = dyn_per_cand + mask_per_cand + out_per_cand
    return {
        "dyn_bytes": dyn_per_cand * width,
        "mask_bytes": mask_per_cand * width,
        "out_bytes": out_per_cand * width,
        "per_candidate_bytes": per_cand,
        "chunk_bytes": per_cand * width,
    }


def width_cap(budget_bytes: int, resident_bytes: int,
              per_candidate_bytes: int, n_task_shards: int,
              max_width: int, margin: float = 1.0) -> Optional[int]:
    """The widest shard-multiple chunk whose modeled footprint
    (resident broadcast set + ``width x per_candidate_bytes``, scaled
    by the ledger's safety ``margin``) fits ``budget_bytes``.

    ``None`` when no budget applies; never below ``n_task_shards`` —
    the minimum launchable width.  A minimum-width chunk whose model
    still exceeds the budget is *planned* anyway (there is no narrower
    program) and left to the supervisor's bisection/host fallback."""
    if not budget_bytes or per_candidate_bytes <= 0:
        return None
    margin = max(1.0, float(margin))
    avail = budget_bytes - float(resident_bytes) * margin
    w = int(avail // (per_candidate_bytes * margin))
    w -= w % max(1, int(n_task_shards))
    return max(int(n_task_shards), min(int(max_width), w))


class MemoryLedger:
    """Process-global HBM accounting shared by every search.

    Activation is refcounted per running search (the dataplane /
    telemetry pattern): the pipeline's launch-boundary hook early-outs
    unless at least one ledger-enabled search is active, so
    ``TpuConfig(memory_ledger=False)`` stays an exact no-op.  All
    mutable state lives under one named lock; device sampling runs
    outside it."""

    def __init__(self):
        self._lock = named_lock("memledger.MemoryLedger._lock")
        self._active = 0
        #: None = never probed; True/False after the first sample —
        #: unmeasurable backends (XLA:CPU) skip per-launch sampling
        self._measured: Optional[bool] = None
        self.watermark_bytes = 0
        self.peak_modeled_bytes = 0
        self.safety_margin = 1.0
        self.n_samples = 0
        self.n_oom = 0
        self._devices: List[Dict[str, Any]] = []
        self._groups: deque = deque(maxlen=_MAX_RECORDS)
        self._compiled: Dict[str, Dict[str, Any]] = {}

    # -- lifecycle -------------------------------------------------------
    @property
    def active(self) -> bool:
        return self._active > 0

    def activate(self) -> "MemoryLedger":
        with self._lock:
            self._active += 1
        return self

    def deactivate(self) -> None:
        with self._lock:
            self._active = max(0, self._active - 1)

    def reset(self) -> None:
        """Drop accumulated state (test isolation)."""
        with self._lock:
            self._measured = None
            self.watermark_bytes = 0
            self.peak_modeled_bytes = 0
            self.safety_margin = 1.0
            self.n_samples = 0
            self.n_oom = 0
            self._devices = []
            self._groups.clear()
            self._compiled.clear()

    # -- measurement -----------------------------------------------------
    def sample(self, force: bool = False) -> List[Dict[str, Any]]:
        """One reconciliation tick: read every device's allocator
        stats (outside the lock), advance the watermark, and record a
        ``memory.sample`` span carrying the fleet's in-use bytes.
        With ``force=False`` a backend probed unmeasurable is skipped
        (the per-launch hook's cheap path); the telemetry sampler
        passes ``force=True`` so ledger-only gauges stay current."""
        with self._lock:
            if not force and self._measured is False:
                return self._devices
        t0 = time.perf_counter()
        stats = _obs_memory.device_memory_stats()
        measured = any(r["measured"] for r in stats)
        in_use = max((r["bytes_in_use"] for r in stats), default=0)
        get_tracer().record_span(
            "memory.sample", t0, time.perf_counter(),
            bytes_in_use=int(in_use), measured=bool(measured),
            n_devices=len(stats))
        with self._lock:
            self._measured = measured
            self._devices = stats
            self.n_samples += 1
            if in_use > self.watermark_bytes:
                self.watermark_bytes = int(in_use)
        return stats

    @property
    def measured(self) -> bool:
        with self._lock:
            return bool(self._measured)

    # -- model -----------------------------------------------------------
    def note_group(self, record: Dict[str, Any]) -> None:
        """Register one compile group's modeled footprint (the engine
        calls this once per (group, width) as geometry resolves) and
        advance the modeled peak.  ``record`` carries the
        :func:`model_group_footprint` breakdown plus the group/width
        identity and the search's resident broadcast bytes."""
        footprint = int(record.get("chunk_bytes", 0)) \
            + int(record.get("resident_bytes", 0))
        with self._lock:
            self._groups.append(dict(record))
            if footprint > self.peak_modeled_bytes:
                self.peak_modeled_bytes = footprint
        get_tracer().instant(
            "memory.footprint",
            group=record.get("group"), width=record.get("width"),
            chunk_bytes=int(record.get("chunk_bytes", 0)),
            modeled_bytes=footprint,
            capped=bool(record.get("capped", False)))

    def note_compiled(self, label: str, analysis: Dict[str, Any]) -> None:
        """Record an XLA ``memory_analysis`` reading taken at
        precompile time (argument/output/temp/code bytes for one AOT
        program) — ground truth for the parts the shape model cannot
        see, keyed by the compile label for postmortems."""
        with self._lock:
            if len(self._compiled) >= _MAX_RECORDS:
                self._compiled.pop(next(iter(self._compiled)))
            self._compiled[str(label)] = dict(analysis)

    def observe_oom(self, modeled_bytes: int, budget_bytes: int) -> float:
        """Fold one observed OOM back into the safety margin.

        A launch the model said fits (``modeled <= budget``) that still
        exhausted the device proves the model underestimates by at
        least ``budget / modeled`` — future width ceilings scale by the
        learned margin so the same chunk is never planned again.  An
        OOM with no budget (ceiling off) or an over-budget model just
        nudges the margin up.  Returns the new margin."""
        with self._lock:
            self.n_oom += 1
            if modeled_bytes > 0 and budget_bytes > 0 \
                    and modeled_bytes <= budget_bytes:
                implied = 1.25 * budget_bytes / modeled_bytes
                self.safety_margin = min(
                    _MAX_MARGIN, max(self.safety_margin, implied))
            else:
                self.safety_margin = min(
                    _MAX_MARGIN, self.safety_margin * 1.25)
            return self.safety_margin

    # -- views -----------------------------------------------------------
    def counters(self) -> Dict[str, Any]:
        """Cheap per-search baseline (snapshot before / render after)."""
        with self._lock:
            return {
                "n_samples": self.n_samples,
                "watermark_bytes": self.watermark_bytes,
                "n_oom": self.n_oom,
            }

    def gauges(self) -> Dict[str, Any]:
        """The telemetry sampler's provider view: per-device pressure
        plus the modeled state.  Samples the devices itself (the
        sampler thread polls providers outside every lock)."""
        stats = self.sample(force=True)
        with self._lock:
            return {
                "measured": bool(self._measured),
                "watermark_bytes": self.watermark_bytes,
                "modeled_peak_bytes": self.peak_modeled_bytes,
                "safety_margin": round(self.safety_margin, 4),
                "n_oom_observed": self.n_oom,
                "pressure_frac_max": round(
                    max((_obs_memory.pressure(r) for r in stats),
                        default=0.0), 6),
                "devices": {
                    str(r["id"]): {
                        "bytes_in_use": r["bytes_in_use"],
                        "peak_bytes_in_use": r["peak_bytes_in_use"],
                        "bytes_limit": r["bytes_limit"],
                        "pressure_frac": round(
                            _obs_memory.pressure(r), 6),
                    } for r in stats},
            }

    def snapshot(self) -> Dict[str, Any]:
        """The full ledger state — stamped into every flight-recorder
        bundle so an OOM postmortem shows what was resident and why."""
        with self._lock:
            return {
                "active_searches": self._active,
                "measured": bool(self._measured),
                "watermark_bytes": self.watermark_bytes,
                "modeled_peak_bytes": self.peak_modeled_bytes,
                "safety_margin": round(self.safety_margin, 4),
                "n_samples": self.n_samples,
                "n_oom_observed": self.n_oom,
                "devices": [dict(r) for r in self._devices],
                "groups": [dict(g) for g in self._groups],
                "compiled": {k: dict(v)
                             for k, v in self._compiled.items()},
            }


_LEDGER = MemoryLedger()


def get_ledger() -> MemoryLedger:
    """The process-global ledger every hook reports to."""
    return _LEDGER


def ledger_for(config) -> Optional[MemoryLedger]:
    """The ledger a search should use under ``config`` — ``None`` when
    ``TpuConfig(memory_ledger=False)`` disabled it (the byte-identical
    pre-ledger escape hatch)."""
    if not getattr(config, "memory_ledger", True):
        return None
    return _LEDGER


# -- module-level hook spellings (what the producers call) -----------------

def note_launch_boundary() -> None:
    """Pipeline hook: reconcile model vs reality at a launch boundary.
    Exact no-op unless a ledger-enabled search is active; after the
    first probe, unmeasurable backends (XLA:CPU) early-out too."""
    if _LEDGER.active:
        _LEDGER.sample()


def note_compiled(label: str, exe: Any) -> None:
    """Pipeline precompile hook: harvest the compiled executable's XLA
    ``memory_analysis`` (where the backend provides one) into the
    ledger.  Never raises — the analysis is forensics, not control."""
    if not _LEDGER.active:
        return
    analyze = getattr(exe, "memory_analysis", None)
    if analyze is None:
        return
    try:
        ma = analyze()
    except (RuntimeError, NotImplementedError, TypeError, ValueError):
        return
    if ma is None:
        return
    rec = {}
    for field in ("argument_size_in_bytes", "output_size_in_bytes",
                  "temp_size_in_bytes", "alias_size_in_bytes",
                  "generated_code_size_in_bytes"):
        v = getattr(ma, field, None)
        if v is not None:
            rec[field] = int(v)
    if rec:
        _LEDGER.note_compiled(label, rec)


def snapshot_counters(ledger: Optional[MemoryLedger]) -> Dict[str, Any]:
    """Baseline snapshot for per-search deltas (``search_report
    ["memory"]``)."""
    return ledger.counters() if ledger is not None else {}


def report_block(ledger: MemoryLedger, before: Dict[str, Any],
                 ctx: Dict[str, Any]) -> Dict[str, Any]:
    """The rendered ``search_report["memory"]`` block (schema pinned in
    ``obs.metrics.MEMORY_BLOCK_SCHEMA``): this search's modeled
    footprints and budget next to the process watermark.  ``ctx`` is
    the engine's per-search accumulator (group records, resident
    bytes, resolved budget, the search-start measured baseline)."""
    counters = ledger.counters()
    groups = list(ctx.get("groups", ()))
    resident = int(ctx.get("resident_bytes", 0))
    # each group record pairs its chunk bytes with the resident set
    # that was live when it was planned (a halving rung's compacted
    # residents differ from the last rung's), so the peak is the max
    # of footprints that actually coexisted — matching the ledger's
    # own note_group accounting
    peak_modeled = max(
        (int(g.get("chunk_bytes", 0)) + int(g.get("resident_bytes", 0))
         for g in groups), default=resident)
    measured = ledger.measured
    watermark = int(counters.get("watermark_bytes", 0))
    baseline = int(ctx.get("measured_baseline_bytes", 0))
    error_frac = 0.0
    if measured and watermark > baseline and peak_modeled > 0:
        used = watermark - baseline
        error_frac = round(abs(peak_modeled - used) / used, 6)
    return {
        "enabled": True,
        "measured": measured,
        "budget_bytes": int(ctx.get("budget_bytes", 0)),
        "device_limit_bytes": int(ctx.get("device_limit_bytes", 0)),
        "safety_margin": round(ledger.safety_margin, 4),
        "peak_modeled_bytes": int(peak_modeled),
        "resident_bytes": resident,
        "watermark_bytes": watermark,
        "model_error_frac": error_frac,
        "n_samples": int(counters.get("n_samples", 0))
        - int(before.get("n_samples", 0)),
        "groups": groups,
    }
