from spark_sklearn_tpu.parallel.mesh import TpuConfig, build_mesh, replicate, shard_leading
from spark_sklearn_tpu.parallel.pipeline import (
    ChunkPipeline, LaunchItem, enable_persistent_cache,
    persistent_cache_counts)
from spark_sklearn_tpu.parallel.taskgrid import (
    CompileGroup, GeometryCostModel, GeometryMismatchError, GeometryPlan,
    build_compile_groups, build_fold_masks, geometry_cost_model,
    plan_geometry)
from spark_sklearn_tpu.parallel.dataplane import (
    DataPlane, StagingRing, get_dataplane)
