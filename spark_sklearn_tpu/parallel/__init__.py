from spark_sklearn_tpu.parallel.mesh import TpuConfig, build_mesh, replicate, shard_leading
from spark_sklearn_tpu.parallel.pipeline import (
    ChunkPipeline, LaunchItem, enable_persistent_cache,
    persistent_cache_counts)
from spark_sklearn_tpu.parallel.taskgrid import CompileGroup, build_compile_groups, build_fold_masks
