"""Mesh construction and data placement.

This is the TPU-native replacement for the reference's L2/L3 substrate
(reference: grid_search.py uses sc.parallelize / sc.broadcast; the Spark
TorrentBroadcast + BlockManager ship X, y to every executor).  Here the
"cluster" is a `jax.sharding.Mesh` over the chips jax can see, the
"broadcast" is a `device_put` with a fully-replicated NamedSharding over the
ICI mesh, and the "task fan-out" is a sharded leading axis of a vmapped
computation — XLA inserts the collectives.

Two mesh axes:
  - "task": candidates x folds are sharded across this axis (the analog of
    Spark's one-task-per-executor fan-out).
  - "data": optional second axis for sharding samples *within* one fit
    (gradient psum data-parallelism) when X is too large to replicate.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from spark_sklearn_tpu.obs.trace import get_tracer

TASK_AXIS = "task"
DATA_AXIS = "data"


@dataclasses.dataclass
class TpuConfig:
    """Small config dataclass (SURVEY §5.6): defaults to "just works" on
    whatever `jax.devices()` shows.  The reference has no config system of its
    own; constructor kwargs mirror sklearn and cluster behavior came from
    SparkConf.  Here the only knobs are mesh layout and compile behavior.
    """

    devices: Optional[Sequence[Any]] = None   # default: jax.devices()
    n_task_shards: Optional[int] = None       # default: all devices
    n_data_shards: int = 1
    dtype: Any = None                         # default: float32
    # maximum number of (candidate x fold) program instances materialised in
    # one compiled batch; bounds peak HBM for big grids (the search chunks
    # each compile group to at most this many tasks per launch).
    max_tasks_per_batch: int = 8192
    # checkpoint/resume (SURVEY §5.4): completed chunks stream to
    # <checkpoint_dir>/search_<fingerprint>.jsonl and a restarted identical
    # search skips them.
    checkpoint_dir: Optional[str] = None
    # profiling (SURVEY §5.1): wrap the sweep in a jax.profiler trace whose
    # artifacts land here (open with tensorboard / perfetto).
    profile_dir: Optional[str] = None
    # NaN debugging (SURVEY §5.2): raise at the first non-finite value
    # inside compiled fits instead of masking it into error_score — the
    # checkify-style sanitizer for our purely-functional programs.
    debug_nans: bool = False
    # bf16 data matmuls with fp32 accumulation (solver state stays fp32):
    # the MXU's native precision — typically ~2x on v5e for the GLM hot
    # path at a small, oracle-tested score tolerance cost.
    bf16_matmul: bool = False
    # persistent XLA compilation cache: compiled search programs survive
    # process restarts (jax_compilation_cache_dir), so repeated searches
    # over the same shapes skip the cold compile entirely.
    compile_cache_dir: Optional[str] = None
    # preferred spelling of compile_cache_dir (kept above for
    # back-compat); when both are set this one wins.  See
    # parallel/pipeline.py enable_persistent_cache.
    compilation_cache_dir: Optional[str] = None
    # jax only persists programs whose XLA compile took at least this
    # long (jax_persistent_cache_min_compile_time_secs); 0.0 caches
    # everything (tests use this to observe hits on tiny programs).
    persistent_cache_min_compile_s: float = 0.5
    # pipelined chunk executor (parallel/pipeline.py): how many chunk
    # launches may be in flight beyond the one being gathered.  Chunk
    # k+1's host staging, chunk k-1's result gather, and the next
    # compile group's lowering/compile all overlap chunk k's device
    # compute.  0 = fully synchronous (bit-for-bit the pre-pipeline
    # execution order — the debugging/A-B escape hatch); scores are
    # identical at every depth.
    pipeline_depth: int = 2
    # donate each chunk's per-launch dynamic-parameter buffers to XLA.
    # Default off, with the measured reason recorded: these programs'
    # outputs (per-task scores, nc x folds) can never alias the donated
    # inputs, so XLA reports the donation unusable and ignores it — the
    # pipeline already caps allocator pressure by dropping each chunk's
    # staged buffers at dispatch (they free the moment the execution
    # consumes them).  The knob exists for backends/families where the
    # aliasing does bind.
    donate_chunk_buffers: bool = False
    # convergence-sorted chunking: when a family exposes a difficulty
    # proxy (GLM: larger C / smaller alpha converges slower), big compile
    # groups are sorted by it and split into ~8 narrower launches so the
    # easy launches early-exit instead of paying the slowest candidate's
    # lockstep iterations.  Same compiled program, same cv_results_
    # order; False restores single-width unsorted chunking.
    sort_candidates: bool = True
    # span tracing (obs/): record host-side spans of the search into
    # the in-memory ring buffer.  None defers to the SST_TRACE env var;
    # True records (export later via obs.export.export_chrome_trace);
    # a string records AND writes a Perfetto/chrome://tracing-loadable
    # trace to that path after each fit.  Off is bit-exact with
    # untraced behavior; on is budgeted <2% overhead (obs/trace.py,
    # enforced by test).
    trace: Any = None
    # tracer ring-buffer capacity (events) while this search records
    trace_buffer_size: int = 65536
    # fold fit + NaN-health + scoring into ONE compiled launch per chunk
    # (models never reach the host; XLA fuses the scoring epilogue into
    # the solver).  Timing contract (sklearn _search.py fit/score time
    # columns): the FIRST chunk of each compile group runs as separate
    # fit/score launches, plus one extra WARM score launch that measures
    # the steady-state score cost per task; later fused chunks attribute
    # that measured cost out of their single-launch wall, so
    # mean_score_time is an estimate calibrated per compile group, never
    # a silent 0.0 (single-chunk groups simply run unfused and report
    # exact split timings).  Set False to restore separate launches
    # everywhere.  Applies to the wide score path only (custom scorers
    # keep separate launches).
    fuse_fit_score: bool = True
    # chunk-loop strategy (parallel/taskgrid.resolve_chunk_loop):
    # "per_chunk" dispatches one launch per chunk — the default, the
    # resumable/faultable baseline, and the fallback for a scanned
    # segment that OOMs.  "scan" rolls a compile group's chunk loop
    # into the program via lax.scan (carry buffers donated by XLA
    # across scan steps), so an entire scan segment — a whole group,
    # or a whole halving rung including its on-device top_k
    # elimination — executes as ONE launch.  Requires the fused
    # fit+score path (fuse_fit_score, wide scoring); searches that
    # cannot fuse fall back to per_chunk and record the reason in
    # search_report["chunkloop"].  None defers to SST_CHUNK_LOOP.
    chunk_loop: Optional[str] = None
    # shared-prefix search graphs (search/prefix.py): treat a Pipeline
    # candidate as a DAG, not an atom — group candidates by a content
    # digest of their transformer-chain params, compute each DISTINCT
    # prefix once per fold on device, cache the transformed design
    # matrix in the DataPlane (normal tenant/byte accounting), and fan
    # the suffix candidates over the cached matrices through the
    # existing chunk/scan machinery: an O(candidates) preprocessing
    # bill becomes O(distinct prefixes).  Bit-exact with the atomic
    # path by construction (same ops, same order — pinned by test).
    # False is the exact escape hatch: every candidate runs as one
    # atomic program, byte-identical to pre-prefix behavior.  Searches
    # that cannot stage (non-Pipeline families, task-batched finals,
    # sharded/streamed data) fall back atomically and record the
    # reason in search_report["prefix"].  None defers to
    # SST_PREFIX_REUSE (1/0), then True.
    prefix_reuse: Optional[bool] = None
    # force the nested per-(candidate, fold) score path even when every
    # scorer exposes a task-batched core — the A/B control arm
    # (tools/score_ab.py).  None/False keeps the wide path; the
    # SST_NESTED_SCORE env var is the process-wide spelling.
    nested_score: bool = False
    # ---- fault tolerance (parallel/faults.py LaunchSupervisor) ----
    # transient device errors retry with exponential backoff + jitter;
    # budgets are per launch AND per search (a flapping device must not
    # retry forever).
    max_launch_retries: int = 2
    max_search_retries: int = 16
    retry_backoff_s: float = 0.5
    retry_backoff_mult: float = 2.0
    retry_jitter_frac: float = 0.25
    # watchdog: a launch whose blocking wait exceeds this many seconds
    # fails the search with a clean LaunchTimeoutError naming the chunk
    # and compile group (completed chunks stay resumable) instead of
    # hanging the gather thread forever.  None/0 disables the watchdog
    # (no wait threads are spawned).
    launch_timeout_s: Optional[float] = None
    # heartbeat-aware watchdog (requires heartbeat=True below): a
    # SCANNED launch whose in-flight beats stop arriving for this many
    # seconds is declared HUNG with the last-beat step index stamped
    # into the LaunchTimeoutError, the fault event and the flight
    # bundle — intra-launch liveness instead of a whole-segment
    # wall-clock budget.  Launches with no live heartbeat segment
    # (per-chunk items, heartbeat off) keep the launch_timeout_s
    # behavior unchanged.  None/0 disables the heartbeat mode.
    heartbeat_timeout_s: Optional[float] = None
    # deterministic fault injection for tests/drills: "transient@3,oom@5"
    # style spec (see faults.FaultPlan).  None defers to SST_FAULT_PLAN.
    fault_plan: Any = None
    # ---- device data plane (parallel/dataplane.py) ----
    # byte budget of the session-scoped device-array cache (X/y, fold
    # masks, tiled masks) shared by every search in the process: uploads
    # happen once per content+sharding and are reused across chunks,
    # compile groups, calibration and subsequent searches (the
    # TPU-native sc.broadcast, made persistent).  0 disables the plane
    # and restores per-search device_put.
    dataplane_bytes: int = 256 * 2 ** 20
    # ---- launch geometry (parallel/taskgrid.plan_geometry) ----
    # "auto": per-group chunk widths chosen by power-of-two bucketing
    # over a measured cost model (n_launches x overhead + padded_lanes
    # x lane_cost), recorded in search_report["geometry"] and pinned
    # into the checkpoint journal so resume replays identical chunk
    # ids.  "fixed": the legacy width rule (pad-to-shards capped by
    # max_tasks_per_batch), bit-compatible with pre-planner runs.
    geometry_mode: str = "auto"
    # manual cost-model overrides (seconds); None uses the process
    # model's measured/default values.  Useful for deterministic
    # geometry in tests and for operators who know their launch costs.
    geometry_overhead_s: Optional[float] = None
    geometry_lane_cost_s: Optional[float] = None
    # ---- persistent AOT program store (parallel/programstore.py) ----
    # directory of the versioned artifact store: compiled search
    # programs are jax.export-serialized there and a later process
    # (bench cold runs, checkpoint-resume restarts, fleet workers)
    # loads them instead of re-tracing — with the geometry plan cache
    # and cost-model state persisted alongside, so a fresh process
    # plans the same chunk widths and its first chunk launches without
    # compiling anything.  None defers to SST_PROGRAM_STORE_DIR; unset
    # disables the store (the in-process and persistent-XLA caches
    # still apply).
    program_store_dir: Optional[str] = None
    # prewarm manifest (written by TpuSession.write_prewarm_manifest):
    # a session constructed with this set loads the manifest's
    # artifacts into memory at init, so the first search's programs
    # resolve without touching disk mid-pipeline.  None defers to
    # SST_PREWARM_MANIFEST; a missing file is skipped, never an error.
    prewarm_manifest: Optional[str] = None
    # store byte budget: oldest artifacts evict beyond it.  None defers
    # to SST_PROGRAM_STORE_BYTES (default 512 MiB); 0 disables the
    # store entirely.
    program_store_bytes: Optional[int] = None
    # ---- multi-tenant search service (serve/executor.py) ----
    # tenant identity of searches run under this config: concurrent
    # searches submitted to one TpuSession fair-share the device by
    # tenant (deficit round-robin over per-tenant chunk queues).  None
    # defers to SST_TENANT, then "default".
    tenant: Optional[str] = None
    # fair-share weight of this config's tenant: a weight-3 tenant is
    # granted 3x the dispatched task share of a weight-1 tenant while
    # both have chunks queued.  None defers to SST_TENANT_WEIGHT, then
    # 1.0.
    tenant_weight: Optional[float] = None
    # admission control: how many searches may run concurrently in the
    # session's executor; beyond it submissions queue (up to
    # max_queued_searches) and then reject with a clean AdmissionError.
    max_concurrent_searches: int = 8
    # bounded submission queue: searches waiting for a concurrency slot
    # beyond this count are rejected at submit() time.
    max_queued_searches: int = 16
    # per-tenant cap on chunks in flight (dispatched, not yet
    # finalized) across ALL of the tenant's concurrent searches; the
    # scheduler skips a capped tenant until a chunk completes.
    # 0 = unbounded (the per-search pipeline_depth still bounds each
    # search on its own).
    tenant_max_inflight: int = 0
    # deficit-round-robin quantum in cost units (one unit = one real
    # (candidate x fold) task of a chunk): per scheduling round each
    # tenant accumulates quantum x tenant_weight of dispatch credit.
    scheduler_quantum: int = 64
    # per-tenant byte quota in the device data plane: a tenant over its
    # quota evicts its OWN least-recently-used resident arrays, never
    # another tenant's (parallel/dataplane.py).  0 = no per-tenant
    # quota (the global dataplane_bytes budget still applies).
    dataplane_tenant_bytes: int = 0
    # ---- adaptive search (search/halving.py) ----
    # successive-halving lane reclamation: re-plan each rung's
    # SURVIVING candidates into narrower chunks (plan_geometry over the
    # survivor sizes, width-affine to already-compiled widths priced by
    # the cost model's measured compile wall), so eliminated candidates
    # retire their lanes instead of riding along as padding.  False
    # pins every rung to the rung-0 chunk widths — the A/B control arm
    # and the "survivors ride along" baseline; cv_results_ is identical
    # either way (widths are pure geometry, never scores).
    halving_replan: bool = True
    # lower bound on a re-planned rung's chunk width (rounded up to the
    # task-shard multiple, capped by the HBM bound): keeps late rungs
    # from degrading into matmul-starved slivers on wide meshes.
    # 0 = no floor beyond the shard multiple.
    min_rung_width: int = 0
    # ---- device-memory ledger (parallel/memledger.py) ----
    # HBM accounting: model every launch's device footprint from its
    # abstract shapes, reconcile against jax memory_stats at launch
    # boundaries, render search_report["memory"], and cap planned
    # chunk widths to the HBM budget below.  False is the exact-no-op
    # escape hatch: reports and cv_results_ are byte-identical to the
    # pre-ledger engine (no "memory" block, no sampling, no ceiling).
    memory_ledger: bool = True
    # per-device byte budget the geometry planner fits chunks into:
    # widths are capped so (broadcast residents + the chunk's modeled
    # dyn/mask/output bytes) x the ledger's learned safety margin stay
    # under it — chunks that would not fit are never launched, and OOM
    # bisection becomes the fallback instead of the discovery
    # mechanism.  None defers to SST_HBM_BUDGET_BYTES, then a fraction
    # (obs.memory.DEFAULT_HBM_FRACTION) of the detected device memory;
    # backends with no measurable limit (XLA:CPU) default to 0 = no
    # ceiling.  0 disables the ceiling explicitly.
    hbm_budget_bytes: Optional[int] = None
    # ---- fleet telemetry (obs/telemetry.py + obs/fleet.py) ----
    # localhost metrics endpoint: the session serves Prometheus text at
    # /metrics and the JSON snapshot at /snapshot.json on this port
    # (127.0.0.1 only).  None disables telemetry entirely — an exact
    # no-op, like the tracer — deferring to SST_TELEMETRY_PORT; 0 binds
    # an ephemeral port (read it back from session.fleet_endpoint.port,
    # or point tools/fleet_top.py at it).
    telemetry_port: Optional[int] = None
    # sliding-window span (seconds) the telemetry SLO series cover
    # (per-tenant queue-wait p50/p95, throughput, shares, device
    # occupancy) and the sampler thread's poll period.
    telemetry_window_s: float = 120.0
    telemetry_interval_s: float = 0.5
    # flight recorder: directory black-box bundles dump to on FATAL
    # faults, watchdog timeouts, first OOM recovery, cancellations and
    # program-store quarantines.  None defers to SST_FLIGHT_DIR; unset
    # disables dumping (the bounded in-memory event ring still
    # records).
    flight_dir: Optional[str] = None
    # in-flight device heartbeats (obs/heartbeat.py): thread a
    # jax.debug.callback beacon into the scanned chunk loop's step body
    # (and a cheap host-side beat into per-chunk dispatches) so
    # SearchFuture.progress() reports intra-segment steps_done/ETA,
    # the heartbeat_timeout_s watchdog sees liveness per scan step,
    # and search_report grows a "heartbeat" block.  Off (the default)
    # is an exact no-op: no callback is traced into the program — its
    # presence joins the program cache key, so on/off never alias —
    # and cv_results_/search_report stay byte-identical.  None defers
    # to SST_HEARTBEAT.
    heartbeat: Optional[bool] = None
    # ---- search doctor (obs/attribution.py + obs/runlog.py) ----
    # critical-path attribution: decompose each search's measured wall
    # into pinned cause lanes (compile/stage/compute/gather/queue
    # wait/faults/padding/memory narrowing) rendered as
    # search_report["attribution"] with a one-line verdict.  False is
    # the exact-no-op escape hatch: no block, reports and cv_results_
    # byte-identical to the pre-doctor engine.
    attribution: bool = True
    # run history + regression sentinel: persist every search's
    # attribution/geometry/cost-model record into the run log and
    # compare against the stored baseline for the same (family,
    # structure digest, env fingerprint) key.  False disables both
    # even when a directory is configured — an exact no-op.
    runlog: bool = True
    # run-log directory (ProgramStore-style layout: records live under
    # v<format>/<env_digest>/).  None defers to SST_RUNLOG_DIR; unset
    # disables the run log and the sentinel.
    runlog_dir: Optional[str] = None
    # run-log byte budget: oldest records prune beyond it.  None
    # defers to SST_RUNLOG_BYTES, then the 32 MiB default; <= 0
    # disables the run log.
    runlog_bytes: Optional[int] = None
    # the sentinel's relative noise band: a watched lane (wall /
    # compile / queue wait / padding) must grow beyond baseline x
    # (1 + frac) — and by more than an absolute 50 ms floor — before
    # a regression is flagged.
    runlog_noise_frac: float = 0.25
    # ---- self-protecting service (serve/executor.py + search/grid.py) ----
    # wall-clock deadline (seconds) a search may spend from submit to
    # finish.  For executor-submitted searches the clock starts at
    # submit time (queue wait counts); solo fits start it at fit().
    # None disables the deadline.  On expiry: partial_results decides.
    search_deadline_s: Optional[float] = None
    # what a deadline or a persistent degradable fault does to the
    # search: "raise" (default — SearchDeadlineError / the fault
    # propagates, exact pre-protection behavior) or "best_effort"
    # (return cv_results_ with un-run candidates carrying sklearn-exact
    # error_score semantics and a search_report["protection"] block
    # naming every shed/quarantined candidate).
    partial_results: str = "raise"
    # admission control mode for executor submits: "static" (default —
    # only the max_concurrent/max_queued slot check, exact PR-12
    # behavior) or "predictive" (additionally price the search's
    # ledger-modeled HBM footprint against hbm_budget_bytes and its
    # queue-wait forecast against search_deadline_s, rejecting with a
    # machine-readable AdmissionError before any device work).
    admission_mode: str = "static"
    # poison-candidate quarantine: when partial_results="best_effort",
    # a candidate whose chunk has bottomed out to a single lane and
    # still faults FATAL this many times is quarantined to error_score
    # instead of killing the search.  Ignored under "raise".
    quarantine_fatal_k: int = 3
    # ---- cross-search launch fusion (serve/executor.py + parallel/pipeline.py) ----
    # coalesce same-program chunks from different concurrent searches
    # into one wide device launch (results scattered back per tenant,
    # bit-identical to each member's solo launch).  None defers to
    # SST_FUSION, then True.  False is the exact escape hatch: the
    # scheduler dispatches every chunk solo, byte-identical reports.
    fusion: Optional[bool] = None
    # how long (milliseconds) the dispatch loop holds a fusable chunk
    # at the head of the queue waiting for a same-program peer from
    # another search before launching it solo.  None defers to
    # SST_FUSION_WINDOW_MS, then 5.0.
    fusion_window_ms: Optional[float] = None
    # cap on a fused launch's total candidate width (real lanes across
    # all members, before padding).  None defers to
    # SST_FUSION_MAX_WIDTH, then 0 = bounded only by the member plans'
    # own width caps.
    fusion_max_width: Optional[int] = None
    # ---- out-of-core data plane (search/stream.py + sparse/csr.py) ----
    # how the dataset reaches the device: "device" (default — X is
    # densified and device-resident for the whole search, exact
    # pre-streaming behavior), "stream" (X stays on the host; sample
    # shards stream through the stage/compute overlap and per-shard
    # partial statistics fold on device — families advertising
    # supports_stream only), or "sparse" (scipy CSR X rides the BCOO
    # bridge end to end, no densify — families advertising
    # supports_sparse only).  None defers to SST_DATA_MODE, then
    # "device".
    data_mode: Optional[str] = None
    # target host->device bytes per streamed sample shard.  The stream
    # planner clamps this against hbm_budget_bytes (residency = budget
    # minus the program footprint, double-buffered) so shard width is a
    # planning decision, never OOM trial-and-error.  None defers to
    # SST_STREAM_SHARD_BYTES, then 64 MiB.
    stream_shard_bytes: Optional[int] = None
    # ---- crash-safe service (serve/journal.py) ----
    # durable submission journal: every executor submission and state
    # transition appends a checksummed, fsynced record here, the
    # lease file fences concurrent owners, and a restarted session
    # recovers non-terminal searches via TpuSession.recover().  None
    # defers to SST_SERVICE_JOURNAL_DIR; unset disables the journal
    # entirely — an exact no-op: zero writes, byte-identical reports
    # and cv_results_.
    service_journal_dir: Optional[str] = None
    # how stale the lease's heartbeat stamp may grow before a restarted
    # process may fence a silent owner and take the journal over.  A
    # LIVE owner with a fresh stamp always wins (ServiceLeaseError for
    # the newcomer).  None defers to SST_SERVICE_LEASE_TIMEOUT_S, then
    # 30 seconds.
    service_lease_timeout_s: Optional[float] = None

    def resolve_devices(self):
        return list(self.devices) if self.devices is not None else jax.devices()

    def resolved_cache_dir(self) -> Optional[str]:
        """The persistent compilation cache directory, honoring both
        spellings (`compilation_cache_dir` preferred)."""
        return self.compilation_cache_dir or self.compile_cache_dir


def build_mesh(config: Optional[TpuConfig] = None) -> Mesh:
    """Build a ("task", "data") mesh from the visible devices.

    On the single-chip machine this is a trivial 1x1 mesh; on a v5e-8 slice it
    is 8x1 by default (all chips fan out over tasks), or 4x2/2x4/1x8 when
    `n_data_shards` asks for in-fit data parallelism.
    """
    config = config or TpuConfig()
    with get_tracer().span("build_mesh"):
        devices = config.resolve_devices()
        n = len(devices)
        nd = max(1, config.n_data_shards)
        if n % nd != 0:
            raise ValueError(
                f"n_data_shards={nd} does not divide device count {n}")
        nt = config.n_task_shards or (n // nd)
        if nt * nd != n:
            raise ValueError(
                f"mesh {nt}x{nd} != {n} devices; set "
                "n_task_shards/n_data_shards so their product equals "
                "the device count")
        dev_array = np.asarray(devices).reshape(nt, nd)
        return Mesh(dev_array, axis_names=(TASK_AXIS, DATA_AXIS))


def replicate(mesh: Mesh, *arrays):
    """Place arrays fully replicated over the mesh — the TPU-native
    `sc.broadcast`.  One transfer per device over ICI; no BitTorrent, no
    pickle (reference: grid_search.py X_bc = sc.broadcast(X))."""
    sharding = NamedSharding(mesh, P())
    with get_tracer().span("device_put.replicate", n_arrays=len(arrays)):
        out = tuple(jax.device_put(a, sharding) for a in arrays)
    return out[0] if len(out) == 1 else out


def shard_leading(mesh: Mesh, *arrays, axis: str = TASK_AXIS):
    """Shard the leading axis of each array across `axis` — the analog of
    sc.parallelize(indexed_param_grid, n): each device owns a contiguous
    stripe of the task grid."""
    sharding = NamedSharding(mesh, P(axis))
    with get_tracer().span("device_put.shard", n_arrays=len(arrays),
                           axis=axis):
        out = tuple(jax.device_put(a, sharding) for a in arrays)
    return out[0] if len(out) == 1 else out


def pad_to_multiple(n: int, k: int) -> int:
    return int(math.ceil(n / k) * k) if k > 1 else n


def task_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P(TASK_AXIS))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def device_get_tree(x):
    """`jax.device_get` that also works under multi-controller JAX.

    In a multi-process cluster (jax.distributed, SURVEY §5.8) the
    engine's launch outputs are globally sharded over a mesh spanning
    processes, so a plain device_get would raise on the non-addressable
    shards; process_allgather replicates them across hosts first (one
    XLA all-gather over the cluster's transport — the analog of Spark's
    collect() back to the driver, except every host gets the result).
    Single-process: plain device_get, zero overhead."""
    if jax.process_count() == 1:
        with get_tracer().span("device_get"):
            return jax.device_get(x)
    from jax.experimental import multihost_utils

    def one(a):
        if isinstance(a, jax.Array) and not a.is_fully_addressable:
            return np.asarray(
                multihost_utils.process_allgather(a, tiled=True))
        return jax.device_get(a)

    with get_tracer().span("device_get.allgather"):
        return jax.tree_util.tree_map(one, x)
