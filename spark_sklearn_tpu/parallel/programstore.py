"""Persistent AOT program & plan store — zero-cold-start sessions.

A cold process pays the whole compile wall again: the persistent XLA
compilation cache (parallel/pipeline.py) amortizes the HLO->binary step
across processes, but every fresh worker still re-walks python -> jaxpr
-> StableHLO for every compile group before it can even ASK that cache.
At fleet scale (ROADMAP item 1: many workers serving many users'
searches) that wall is paid per worker, not per program — the same
cost spark-sklearn's shared cluster amortized by keeping one JVM warm,
and the cost DrJAX-style reusable compiled programs remove by making
the compiled artifact itself the shared object.

:class:`ProgramStore` is the on-disk artifact tier under the in-process
program cache (search/grid.py ``_PROGRAM_CACHE``):

  - **artifacts** are ``jax.export``-serialized programs (portable
    StableHLO + calling convention), keyed by (program kind, estimator
    family, compile-group structure digest, launch-geometry width — all
    folded into a content digest — and the abstract input signature),
    stored under a directory versioned by store format and an
    environment fingerprint (jax/jaxlib/package versions, platform,
    device fleet).  ``Compiled.serialize`` — a backend-specific XLA
    executable — is not exposed by this jax version on any backend here;
    the StableHLO artifact skips the expensive python->jaxpr->HLO walk
    and leaves the final HLO->binary step to the persistent XLA cache,
    which both the publishing and the loading process hit with the SAME
    module because both execute the stored bytes (see
    :class:`StoredProgram`).
  - **hardened like the checkpoint journal**: atomic writes (tmp +
    fsync + ``os.replace``), version/topology mismatch -> clean miss
    and JIT fallback, corrupt artifact -> quarantine + recompile —
    never a failed search.
  - **byte-budgeted**: oldest artifacts are evicted once the store
    exceeds ``TpuConfig.program_store_bytes``.
  - **plans ride along**: the launch-geometry plan cache and the
    :class:`~spark_sklearn_tpu.parallel.taskgrid.GeometryCostModel`
    EMA state persist next to the programs (``plans.json``), so a fresh
    process plans the SAME chunk widths — and therefore requests the
    same stored programs — without re-measuring.
  - **prewarmable**: a manifest written by a finished search's session
    (:meth:`~spark_sklearn_tpu.utils.session.TpuSession.
    write_prewarm_manifest`) names the artifacts it used;
    ``TpuSession(config=TpuConfig(prewarm_manifest=...))`` loads them
    at init so the first chunk of the first search resolves from
    memory.
  - **observable**: ``search_report["programstore"]`` (schema pinned in
    ``obs.metrics.PROGRAMSTORE_BLOCK_SCHEMA``) and ``programstore.load``
    / ``programstore.save`` spans carrying byte counts and hit flags
    (``tools/trace_summary.py`` digests them into a compile line).

Execution contract: a process that PUBLISHES an artifact also executes
the published bytes (serialize -> write -> deserialize -> run), so the
loading process compiles the byte-identical module and the persistent
XLA cache covers the binary too.  Results are bit-identical to the jit
path — the artifact is the same jaxpr's StableHLO, and every failure
mode (unsupported export, version drift, corruption) falls back to
plain jit with the same program.
"""

from __future__ import annotations

import hashlib
import json
import os
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np
import jax

from spark_sklearn_tpu.obs import telemetry as _telemetry
from spark_sklearn_tpu.obs.log import get_logger
from spark_sklearn_tpu.obs.trace import get_tracer
# crash-safe publish (tmp + fsync + os.replace): the one hardened
# write path every store file (artifacts, plans.json, manifests) goes
# through — shared with the flight recorder via utils/atomic.py
from spark_sklearn_tpu.utils import keycheck as _keycheck
from spark_sklearn_tpu.utils.atomic import atomic_write as _atomic_write
from spark_sklearn_tpu.utils.locks import named_lock

logger = get_logger(__name__)

__all__ = [
    "DEFAULT_STORE_BUDGET",
    "STORE_FORMAT",
    "ProgramStore",
    "StoredProgram",
    "activate_store",
    "active_store",
    "deactivate_store",
    "maybe_wrap",
    "report_block",
    "snapshot_counters",
]

#: on-disk format version: bump when the artifact layout changes —
#: old stores become clean misses, never parse errors.
STORE_FORMAT = 1

#: artifact file magic (format version baked in).
_MAGIC = b"SSTPROG1"

#: default store byte budget (512 MiB): a few hundred bench-scale
#: programs; oldest artifacts evict beyond it.
DEFAULT_STORE_BUDGET = 512 * 2 ** 20

_SUFFIX = ".sstprog"


class _CorruptArtifact(RuntimeError):
    """An artifact file that cannot be structurally parsed/verified —
    quarantined by the loader (a MISMATCHED artifact is a clean miss,
    not corruption)."""


class _VanishedArtifact(Exception):
    """An artifact that disappeared between the existence check and the
    read (a concurrent process's eviction) — a clean miss, never a
    failed search."""


def _digest(obj: Any, hexchars: int = 16) -> str:
    """Stable content digest of an already-deterministic value (frozen
    tuples, sorted items): blake2b over its repr."""
    h = hashlib.blake2b(repr(obj).encode(), digest_size=hexchars // 2)
    return h.hexdigest()




def env_fingerprint() -> Dict[str, Any]:
    """The environment identity an artifact is only valid under:
    store format, jax/jaxlib/package versions, backend platform and
    device fleet.  A mismatch in ANY field is a clean store miss (the
    jit path recompiles) — stale binaries can never execute."""
    import jaxlib

    from spark_sklearn_tpu import __version__ as _pkg_version
    devs = jax.devices()
    return {
        "format": STORE_FORMAT,
        "jax": jax.__version__,
        "jaxlib": getattr(jaxlib, "__version__", "?"),
        "package": _pkg_version,
        "platform": jax.default_backend(),
        "n_devices": len(devs),
        "device_kinds": sorted({str(d.device_kind) for d in devs}),
        "n_processes": jax.process_count(),
    }


def aval_signature(args: Tuple[Any, ...]) -> str:
    """Digest of the abstract input signature: tree structure plus
    every leaf's (shape, dtype).  Works on concrete arrays and
    ``jax.ShapeDtypeStruct`` specs alike, so the pipeline's
    compile-ahead (abstract avals) and the dispatch path (committed
    arrays) resolve the same artifact."""
    leaves, treedef = jax.tree_util.tree_flatten(args)
    sig = (str(treedef),
           tuple((tuple(np.shape(l)), str(np.dtype(l.dtype)))
                 for l in leaves))
    return _digest(sig, hexchars=12)


class ProgramStore:
    """Versioned on-disk store of AOT-serialized program artifacts.

    Layout::

        <directory>/v<STORE_FORMAT>/<env_digest>/   *.sstprog, plans.json
        <directory>/quarantine/                     corrupt artifacts

    Artifacts from other jax versions / device topologies live under
    other ``env_digest`` directories — loading them is structurally
    impossible, and each artifact's header re-states its environment so
    even a digest collision degrades to a clean miss.  Thread-safe: the
    pipeline's compile thread, the dispatch thread and supervisor
    recovery threads may all resolve programs concurrently.
    """

    def __init__(self, directory: str,
                 byte_budget: int = DEFAULT_STORE_BUDGET,
                 flight_dir: Optional[str] = None):
        self.directory = os.path.abspath(directory)
        #: where a quarantine incident's flight bundle dumps
        #: (TpuConfig.flight_dir of the activating session; the
        #: SST_FLIGHT_DIR env var still applies as the fallback)
        self.flight_dir = flight_dir
        self.env = env_fingerprint()
        self.env_digest = _digest(tuple(sorted(
            (k, tuple(v) if isinstance(v, list) else v)
            for k, v in self.env.items())), hexchars=12)
        self._dir = os.path.join(
            self.directory, f"v{STORE_FORMAT}", self.env_digest)
        os.makedirs(self._dir, exist_ok=True)
        self._lock = named_lock("programstore.ProgramStore._lock")
        self.byte_budget = int(byte_budget)
        #: deserialized artifacts resident in memory (prewarm target)
        self._mem: Dict[str, Any] = {}
        #: artifacts this process served or published — the manifest
        self._used: Dict[str, Dict[str, Any]] = {}
        self._counts = {
            "hits": 0, "misses": 0, "publishes": 0, "bytes_loaded": 0,
            "bytes_saved": 0, "quarantined": 0, "evictions": 0,
            "prewarmed": 0,
        }

    # -- naming ------------------------------------------------------------
    @staticmethod
    def entry_name(kind: str, family: str, parts_digest: str,
                   avals_digest: str) -> str:
        fam = "".join(c if c.isalnum() or c in "-_" else "_"
                      for c in str(family))[:40]
        return f"{kind}-{fam}-{parts_digest}-{avals_digest}{_SUFFIX}"

    def path_for(self, name: str) -> str:
        return os.path.join(self._dir, name)

    # -- artifact IO ---------------------------------------------------------
    def _read_artifact(self, path: str) -> Tuple[Dict[str, Any], bytes]:
        with open(path, "rb") as f:
            raw = f.read()
        if len(raw) < len(_MAGIC) + 4 or not raw.startswith(_MAGIC):
            raise _CorruptArtifact(f"{path}: bad magic")
        off = len(_MAGIC)
        hlen = int.from_bytes(raw[off:off + 4], "big")
        off += 4
        if hlen <= 0 or off + hlen > len(raw):
            raise _CorruptArtifact(f"{path}: truncated header")
        try:
            header = json.loads(raw[off:off + hlen].decode())
        except (UnicodeDecodeError, json.JSONDecodeError) as exc:
            raise _CorruptArtifact(f"{path}: unparseable header") from exc
        payload = raw[off + hlen:]
        if len(payload) != int(header.get("payload_bytes", -1)):
            raise _CorruptArtifact(f"{path}: truncated payload")
        sha = hashlib.sha256(payload).hexdigest()
        if sha != header.get("payload_sha256"):
            raise _CorruptArtifact(f"{path}: payload digest mismatch")
        return header, payload

    def _quarantine(self, path: str) -> None:
        qdir = os.path.join(self.directory, "quarantine")
        os.makedirs(qdir, exist_ok=True)
        target = os.path.join(
            qdir, f"{os.path.basename(path)}.{os.getpid()}")
        try:
            os.replace(path, target)
        except OSError as exc:
            # a concurrent loader may have quarantined it first; either
            # way the artifact is out of the serving path
            logger.debug("quarantine rename failed for %s: %r", path, exc)
        with self._lock:
            self._counts["quarantined"] += 1
        logger.warning(
            "program store: quarantined corrupt artifact %s -> %s",
            os.path.basename(path), target)
        # a quarantine is a black-box incident: something corrupted an
        # on-disk artifact — bundle the recent events for postmortem
        # (the activating config's flight_dir, else SST_FLIGHT_DIR;
        # no-op when neither is set)
        _telemetry.note_programstore("quarantine")
        _telemetry.flight_recorder().dump(
            "quarantine", flight_dir=self.flight_dir,
            context={"artifact": os.path.basename(path),
                     "moved_to": target, "store": self.directory})

    def _note_used(self, name: str, header: Dict[str, Any]) -> None:
        with self._lock:
            self._used.setdefault(name, {
                "file": name,
                "env": self.env_digest,
                "kind": header.get("kind", "?"),
                "family": header.get("family", "?"),
                "bytes": int(header.get("payload_bytes", 0)),
                "meta": dict(header.get("meta") or {}),
            })

    def load(self, name: str, kind: str = "?", family: str = "?",
             prewarm: bool = False):
        """The deserialized ``jax.export.Exported`` stored under
        ``name``, or ``None`` on a (clean) miss.  Environment mismatch
        is a miss; structural corruption quarantines the file and is a
        miss; either way the caller's jit path still runs the search."""
        t0 = time.perf_counter()
        hit_kind = "miss"
        nbytes = 0
        ex = None
        with self._lock:
            ex = self._mem.get(name)
        if ex is not None:
            hit_kind = "memory"
        else:
            path = self.path_for(name)
            if os.path.isfile(path):
                try:
                    try:
                        header, payload = self._read_artifact(path)
                    except OSError:
                        # vanished between the isfile check and the
                        # read (a concurrent publisher's eviction):
                        # clean miss, nothing to quarantine
                        raise _VanishedArtifact
                    if header.get("env") != self.env:
                        # valid artifact from another world: leave it
                        # for that world, miss here
                        header = None
                    if header is not None:
                        nbytes = len(payload)
                        try:
                            from jax import export as _jexport
                            ex = _jexport.deserialize(bytearray(payload))
                        except Exception as exc:
                            # checksummed payload jax cannot deserialize:
                            # written by a broken/foreign producer —
                            # quarantine like any other corruption
                            raise _CorruptArtifact(
                                f"{path}: deserialize failed") from exc
                        hit_kind = "disk"
                        self._note_used(name, header)
                        with self._lock:
                            self._mem[name] = ex
                except _VanishedArtifact:
                    ex = None
                except _CorruptArtifact as exc:
                    logger.warning("program store: %s", exc)
                    self._quarantine(path)
                    ex = None
        with self._lock:
            if ex is not None:
                self._counts["prewarmed" if prewarm else "hits"] += 1
                self._counts["bytes_loaded"] += nbytes
            else:
                self._counts["misses"] += 1
        _telemetry.note_programstore("hit" if ex is not None else "miss")
        get_tracer().record_span(
            "programstore.load", t0, time.perf_counter(), key=name,
            bytes=nbytes, hit=ex is not None, source=hit_kind,
            kind=kind, family=str(family))
        return ex

    def publish(self, name: str, exported, kind: str = "?",
                family: str = "?", meta: Optional[Dict[str, Any]] = None):
        """Serialize ``exported`` and atomically write it under
        ``name``; returns the artifact RE-deserialized from the
        published bytes (the executes-what-it-published contract — the
        loading process compiles the byte-identical module), or ``None``
        when anything fails (the caller stays on the jit path)."""
        t0 = time.perf_counter()
        try:
            blob = bytes(exported.serialize())
            header = {
                "format": STORE_FORMAT,
                "env": self.env,
                "kind": kind,
                "family": str(family),
                "payload_bytes": len(blob),
                "payload_sha256": hashlib.sha256(blob).hexdigest(),
                "meta": dict(meta or {}),
            }
            hbytes = json.dumps(header, sort_keys=True).encode()
            _atomic_write(self.path_for(name),
                          _MAGIC + len(hbytes).to_bytes(4, "big")
                          + hbytes + blob)
            self._evict_over_budget(keep=name)
            from jax import export as _jexport
            ex = _jexport.deserialize(bytearray(blob))
            self._note_used(name, header)
            with self._lock:
                self._counts["publishes"] += 1
                self._counts["bytes_saved"] += len(blob)
                self._mem[name] = ex
            _telemetry.note_programstore("publish")
            get_tracer().record_span(
                "programstore.save", t0, time.perf_counter(), key=name,
                bytes=len(blob), kind=kind, family=str(family))
            return ex
        except Exception as exc:
            # publishing is an optimization only: a full disk, an
            # unserializable program or a deserialize bug must never
            # fail the search — the jit path produces identical results
            logger.warning(
                "program store: publish failed for %s (%r); "
                "continuing on jit", name, exc)
            return None

    def _evict_over_budget(self, keep: Optional[str] = None) -> None:
        try:
            entries = []
            for fn in os.listdir(self._dir):
                if not fn.endswith(_SUFFIX):
                    continue
                st = os.stat(os.path.join(self._dir, fn))
                entries.append((st.st_mtime, st.st_size, fn))
            total = sum(e[1] for e in entries)
            entries.sort()
            evicted = 0
            for mtime, size, fn in entries:
                if total <= self.byte_budget or fn == keep:
                    continue
                os.remove(os.path.join(self._dir, fn))
                with self._lock:
                    self._mem.pop(fn, None)
                total -= size
                evicted += 1
            if evicted:
                with self._lock:
                    self._counts["evictions"] += evicted
        except OSError as exc:
            logger.debug("program store eviction scan failed: %r", exc)

    # -- geometry plans ------------------------------------------------------
    def plan_state_path(self) -> str:
        return os.path.join(self._dir, "plans.json")

    def load_plan_state(self) -> Optional[Dict[str, Any]]:
        """The persisted geometry plan cache + cost-model state written
        by :meth:`save_plan_state`, or ``None`` (missing/corrupt —
        a fresh process simply re-plans from defaults)."""
        path = self.plan_state_path()
        if not os.path.isfile(path):
            return None
        try:
            with open(path) as f:
                return json.load(f)
        except (OSError, UnicodeDecodeError, json.JSONDecodeError) as exc:
            logger.warning(
                "program store: plan state unreadable (%r); re-planning",
                exc)
            return None

    def save_plan_state(self, state: Dict[str, Any]) -> None:
        """Atomically persist the geometry plan cache + cost-model EMA
        state next to the programs, so a fresh process plans the same
        chunk widths without re-measuring."""
        try:
            _atomic_write(self.plan_state_path(),
                          json.dumps(state).encode())
        except (OSError, TypeError, ValueError) as exc:
            # best-effort: a fresh process simply re-plans
            logger.warning(
                "program store: plan-state save failed: %r", exc)

    # -- prewarm manifest ------------------------------------------------------
    def prewarm(self, manifest: Any) -> Dict[str, Any]:
        """Load the artifacts a manifest declares into the in-memory
        cache, so a session's first search resolves its programs
        without touching disk mid-pipeline.  ``manifest`` is a path or
        an already-parsed dict; entries from other environments and
        files that have since been evicted are skipped, never errors."""
        t0 = time.perf_counter()
        if isinstance(manifest, str):
            try:
                with open(manifest) as f:
                    manifest = json.load(f)
            except (OSError, UnicodeDecodeError,
                    json.JSONDecodeError) as exc:
                logger.warning(
                    "program store: prewarm manifest unreadable (%r); "
                    "skipping prewarm", exc)
                manifest = {}
        entries = list((manifest or {}).get("entries", ()))
        loaded = skipped = 0
        nbytes = 0
        for entry in entries:
            name = os.path.basename(str(entry.get("file", "")))
            if not name.endswith(_SUFFIX) or \
                    entry.get("env") not in (None, self.env_digest):
                skipped += 1
                continue
            ex = self.load(name, kind=str(entry.get("kind", "?")),
                           family=str(entry.get("family", "?")),
                           prewarm=True)
            if ex is None:
                skipped += 1
            else:
                loaded += 1
                nbytes += int(entry.get("bytes", 0))
        summary = {"entries": len(entries), "loaded": loaded,
                   "skipped": skipped, "bytes": nbytes}
        get_tracer().record_span(
            "programstore.prewarm", t0, time.perf_counter(), **summary)
        logger.info("program store prewarm: %d/%d artifacts loaded "
                    "(%d skipped)", loaded, len(entries), skipped,
                    **summary)
        return summary

    def write_manifest(self, path: str) -> str:
        """Write the prewarm manifest of every artifact this process
        served or published — what a finished search actually used —
        for the next session's ``TpuConfig(prewarm_manifest=...)``."""
        with self._lock:
            entries = sorted(self._used.values(),
                             key=lambda e: e["file"])
        doc = {"format": STORE_FORMAT, "env": self.env,
               "env_digest": self.env_digest, "entries": entries}
        # unlike plan-state saves this propagates: the caller asked for
        # a manifest and must know it was not written
        _atomic_write(path, json.dumps(
            doc, indent=1, sort_keys=True).encode())
        return path

    # -- introspection -----------------------------------------------------
    def counts(self) -> Dict[str, int]:
        """Cumulative counter snapshot (callers diff before/after a
        search for ``search_report["programstore"]``)."""
        with self._lock:
            return dict(self._counts)

    def disk_stats(self) -> Dict[str, int]:
        """Artifact count and bytes currently resident on disk for this
        environment."""
        n = 0
        total = 0
        try:
            for fn in os.listdir(self._dir):
                if fn.endswith(_SUFFIX):
                    n += 1
                    total += os.stat(os.path.join(self._dir, fn)).st_size
        except OSError as exc:
            logger.debug("program store disk scan failed: %r", exc)
        return {"n_entries": n, "store_bytes": total}


class StoredProgram:
    """Store-backed proxy around one jitted program.

    ``resolve(*args)`` maps the call's abstract input signature to a
    callable, once per signature:

      - store HIT: the deserialized artifact wrapped in
        ``jax.jit(exported.call)`` — no python->jaxpr->HLO walk at all
        (the XLA binary comes from the persistent compilation cache,
        which saw the identical module when the artifact was
        published);
      - store MISS: ``jax.export`` traces the underlying jit program
        once, the serialized artifact is published, and THIS process
        executes the re-deserialized bytes too (so both sides of the
        store compile the same module);
      - export/publish failure: the plain jit program (identical
        results; it traces at first dispatch exactly as without the
        store).

    ``lower(*args)`` resolves first and then lowers whichever callable
    resolution produced, so the pipeline's compile-ahead
    (``parallel/pipeline.precompile``) consults the store on the
    compile thread before any lowering happens.  ``on_trace`` fires
    once per signature that actually traced (miss/fallback) — the
    search report's ``n_compiles``.
    """

    def __init__(self, jit_fn, store: ProgramStore, kind: str,
                 family: str, parts_digest: str,
                 on_trace: Optional[Callable[[], None]] = None,
                 meta: Optional[Dict[str, Any]] = None):
        self._jit = jit_fn
        self._store = store
        self._kind = str(kind)
        self._family = str(family)
        self._parts_digest = parts_digest
        self._on_trace = on_trace
        self._meta = dict(meta or {})
        self._lock = named_lock("programstore.StoredProgram._lock")
        self._resolved: Dict[str, Any] = {}

    def rebind(self, store: ProgramStore) -> None:
        """Point this (cross-search cached) proxy at the CURRENT
        :class:`ProgramStore` instance for its directory.  After a
        deactivate/re-activate cycle the singleton is a fresh object
        with fresh counters and an empty manifest record — future
        resolutions must land there, not on the dead instance (already-
        memoized signatures keep serving: same directory, same
        artifacts)."""
        if store is self._store:
            return
        with self._lock:
            self._store = store

    def resolve(self, *args):
        """The callable serving this input signature (see class
        docstring); memoized per signature."""
        sig = aval_signature(args)
        with self._lock:
            call = self._resolved.get(sig)
        if call is not None:
            return call
        name = self._store.entry_name(
            self._kind, self._family, self._parts_digest, sig)
        ex = self._store.load(name, kind=self._kind, family=self._family)
        if ex is not None:
            call = jax.jit(ex.call)
        else:
            call = None
            try:
                from jax import export as _jexport
                exported = _jexport.export(self._jit)(*args)
                published = self._store.publish(
                    name, exported, kind=self._kind, family=self._family,
                    meta=self._meta)
                if published is not None:
                    call = jax.jit(published.call)
            except Exception as exc:
                # export is an optimization only: a program jax.export
                # cannot serialize (exotic custom call, symbolic shape)
                # keeps its plain jit path — identical results, and the
                # in-process/persistent caches still apply
                logger.debug(
                    "program export failed for %s (%r); staying on jit",
                    name, exc)
            if call is None:
                call = self._jit
            if self._on_trace is not None:
                # a real trace happened (export's, or jit's at first
                # dispatch) — count it outside any lock
                self._on_trace()
        with self._lock:
            call = self._resolved.setdefault(sig, call)
        return call

    def lower(self, *args):
        """AOT seam for ``parallel/pipeline.precompile``: consult the
        store, then lower whichever callable resolution produced."""
        return self.resolve(*args).lower(*args)

    def __call__(self, *args):
        return self.resolve(*args)(*args)


# ---------------------------------------------------------------------------
# Process-global activation (mirrors dataplane.plane_for)
# ---------------------------------------------------------------------------

_STORE: Optional[ProgramStore] = None
_STORE_LOCK = named_lock("programstore._STORE_LOCK")


def _resolve_dir(config) -> Optional[str]:
    d = getattr(config, "program_store_dir", None) if config is not None \
        else None
    if not d:
        d = os.environ.get("SST_PROGRAM_STORE_DIR", "").strip() or None
    return d


def _resolve_budget(config) -> int:
    b = getattr(config, "program_store_bytes", None) if config is not None \
        else None
    if b is None:
        env = os.environ.get("SST_PROGRAM_STORE_BYTES", "").strip()
        if env:
            # a typo'd budget fails loudly at activation, not mid-search
            b = int(env)
    return DEFAULT_STORE_BUDGET if b is None else int(b)


def resolve_manifest(config) -> Optional[str]:
    """The prewarm manifest path under ``config``
    (``TpuConfig.prewarm_manifest``, else ``SST_PREWARM_MANIFEST``)."""
    m = getattr(config, "prewarm_manifest", None) if config is not None \
        else None
    if not m:
        m = os.environ.get("SST_PREWARM_MANIFEST", "").strip() or None
    return m


def activate_store(config=None) -> Optional[ProgramStore]:
    """The program store a search/session should use under ``config``
    — or ``None`` when no directory is configured
    (``TpuConfig.program_store_dir`` / ``SST_PROGRAM_STORE_DIR``), the
    byte budget disables it, or the process is part of a
    multi-controller cluster (per-host artifact stores for sharded
    programs are ROADMAP item 2 territory).  First activation for a
    directory also seeds the geometry plan cache from the persisted
    plan state."""
    directory = _resolve_dir(config)
    if not directory:
        return None
    budget = _resolve_budget(config)
    if budget <= 0:
        return None
    if jax.process_count() > 1:
        return None
    global _STORE
    fresh = False
    with _STORE_LOCK:
        if _STORE is None or \
                _STORE.directory != os.path.abspath(directory):
            _STORE = ProgramStore(
                directory, budget,
                flight_dir=getattr(config, "flight_dir", None))
            fresh = True
        else:
            _STORE.byte_budget = int(budget)
            fd = getattr(config, "flight_dir", None)
            if fd:
                # the latest activating session's flight dir wins
                _STORE.flight_dir = fd
        store = _STORE
    if fresh:
        state = store.load_plan_state()
        if state:
            from spark_sklearn_tpu.parallel.taskgrid import (
                import_plan_state)
            n = import_plan_state(state)
            logger.info("program store: seeded %d geometry plan(s) "
                        "from %s", n, store.plan_state_path())
    return store


def active_store() -> Optional[ProgramStore]:
    """The currently active store (``None`` when never activated)."""
    with _STORE_LOCK:
        return _STORE


def deactivate_store() -> None:
    """Drop the process-global store (tests; a later
    :func:`activate_store` builds a fresh one with an empty memory
    cache)."""
    global _STORE
    with _STORE_LOCK:
        _STORE = None


#: frozen-leaf types whose repr is stable across processes — a store
#: key may only be digested from these (np.generic/np.dtype reprs are
#: value-stable; arbitrary hashable objects repr their ADDRESS, which
#: would mint a key no other process can ever hit).
_STABLE_LEAVES = (str, bytes, bool, int, float, complex, type(None),
                  np.generic, np.dtype)


def _stable(frozen) -> bool:
    if isinstance(frozen, tuple):
        return all(_stable(x) for x in frozen)
    return isinstance(frozen, _STABLE_LEAVES)


def maybe_wrap(jit_fn, store: Optional[ProgramStore], parts,
               on_trace: Optional[Callable[[], None]] = None,
               meta: Optional[Dict[str, Any]] = None):
    """Wrap ``jit_fn`` in a :class:`StoredProgram` keyed by the
    deterministic ``parts`` tuple ``(kind, family, *structure)`` — or
    return it unwrapped when there is no store or the parts cannot be
    frozen deterministically (unhashable or address-repr'd captured
    objects: their digest is process-local, so a store key would never
    match across processes and would only bloat the store)."""
    if store is None:
        return jit_fn
    from spark_sklearn_tpu.parallel.taskgrid import freeze
    try:
        frozen = freeze(tuple(parts), strict=True)
    except TypeError:
        return jit_fn
    if not _stable(frozen):
        return jit_fn
    # record-only (fields=None): the store key IS the digest of every
    # structural part, so the SST_KEYCHECK log tracks which parts
    # tuples a run minted without asserting an effective-input set
    _keycheck.note("program_store", frozen, detail=str(parts[0]))
    return StoredProgram(
        jit_fn, store, kind=str(parts[0]), family=str(parts[1]),
        parts_digest=_digest(frozen), on_trace=on_trace, meta=meta)


# ---------------------------------------------------------------------------
# search_report["programstore"] block
# ---------------------------------------------------------------------------


def snapshot_counters(store: Optional[ProgramStore]) -> Dict[str, int]:
    """Counter snapshot for per-search deltas."""
    return {} if store is None else store.counts()


def report_block(store: Optional[ProgramStore],
                 before: Dict[str, int]) -> Dict[str, Any]:
    """The rendered ``search_report["programstore"]`` block (schema
    pinned in ``obs.metrics.PROGRAMSTORE_BLOCK_SCHEMA``): this search's
    store traffic plus the store's end-of-search state."""
    if store is None:
        return {"enabled": False, "hits": 0, "misses": 0, "publishes": 0,
                "bytes_loaded": 0, "bytes_saved": 0, "quarantined": 0,
                "evictions": 0, "prewarmed": 0, "n_entries": 0,
                "store_bytes": 0, "dir": ""}
    c = store.counts()
    d = store.disk_stats()
    return {
        "enabled": True,
        "hits": c["hits"] - before.get("hits", 0),
        "misses": c["misses"] - before.get("misses", 0),
        "publishes": c["publishes"] - before.get("publishes", 0),
        "bytes_loaded": c["bytes_loaded"] - before.get("bytes_loaded", 0),
        "bytes_saved": c["bytes_saved"] - before.get("bytes_saved", 0),
        "quarantined": c["quarantined"] - before.get("quarantined", 0),
        "evictions": c["evictions"] - before.get("evictions", 0),
        "prewarmed": c["prewarmed"],
        "n_entries": d["n_entries"],
        "store_bytes": d["store_bytes"],
        "dir": store.directory,
    }
