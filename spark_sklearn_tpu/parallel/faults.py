"""Fault-tolerant launch supervisor — the engine's Spark-resilience story.

The reference gets fault tolerance for free from Spark: a failed task is
retried on another executor, and a dead search is re-run wholesale
(SURVEY §5.4).  The TPU-native engine has no executors to lean on — a
single transient ``XlaRuntimeError``, a RESOURCE_EXHAUSTED on an
oversized chunk, or a hung launch used to kill the whole
``GridSearchCV.fit``, with the offline checkpoint as the only recovery.
This module supplies the missing contract around every ``LaunchItem``
the chunk pipeline executes (``parallel/pipeline.py``):

  - **error taxonomy** — every failure classifies as ``TRANSIENT`` /
    ``OOM`` / ``HUNG`` / ``FATAL`` (:func:`classify_error`, extensible
    via :func:`register_classifier`);
  - **retry with exponential backoff + jitter** for ``TRANSIENT``
    faults, under per-launch (``TpuConfig.max_launch_retries``) and
    per-search (``max_search_retries``) budgets.  A retry re-runs the
    item's own ``stage -> launch -> wait`` phases: same program, same
    inputs, bit-identical scores;
  - **graceful OOM degradation** — an ``OOM`` launch is bisected into
    halves (the item's ``bisect`` hook re-pads lanes via
    ``parallel/taskgrid.pad_chunk`` and relaunches at the narrower
    width), recursing down to single candidates and finally falling
    back to per-candidate host execution with exact sklearn
    ``error_score`` semantics (the item's ``host_fallback`` hook);
  - **watchdog timeouts** — ``TpuConfig.launch_timeout_s`` bounds the
    blocking ``jax.block_until_ready`` wait; a launch that exceeds it
    fails the search with a clean :class:`LaunchTimeoutError` naming
    the chunk and compile group instead of hanging the gather thread
    forever (previously-finalized chunks are already durable in the
    checkpoint, so the failed search resumes);
  - **deterministic fault injection** — ``TpuConfig(fault_plan=...)``
    or the ``SST_FAULT_PLAN`` env var inject any taxonomy class at
    chosen launch indices (``"transient@3,oom@5"``), so CPU tests
    exercise every recovery path with no flaky hardware required.

Every recovery event lands in the metrics registry
(``search_report["faults"]`` — schema pinned in
``obs.metrics.FAULTS_BLOCK_SCHEMA``), in ``launch.retry`` /
``launch.bisect`` / ``launch.host_fallback`` trace spans, and in
structured log lines.
"""

from __future__ import annotations

import copy
import dataclasses
import os
import re
import threading
import time
import zlib
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax

from spark_sklearn_tpu.obs import telemetry as _telemetry
from spark_sklearn_tpu.obs.log import get_logger
from spark_sklearn_tpu.obs.trace import get_tracer
from spark_sklearn_tpu.parallel.pipeline import LaunchItem
from spark_sklearn_tpu.utils.locks import named_lock

_slog = get_logger(__name__)

__all__ = [
    "TRANSIENT",
    "OOM",
    "HUNG",
    "FATAL",
    "FaultPlan",
    "FaultSpec",
    "InjectedFault",
    "LaunchTimeoutError",
    "LaunchSupervisor",
    "SearchDeadlineError",
    "classify_error",
    "is_oom",
    "protection_block",
    "protection_enabled",
    "register_classifier",
]


# ---------------------------------------------------------------------------
# Taxonomy
# ---------------------------------------------------------------------------

#: retry with backoff: the device hiccuped but the program is fine
TRANSIENT = "transient"
#: bisect the chunk / fall back to host: the launch was too big
OOM = "oom"
#: fail the search cleanly: the launch never came back
HUNG = "hung"
#: re-raise unchanged: a real bug (or an unsupported combo the search
#: engine's own compiled->host fallback knows how to handle)
FATAL = "fatal"

#: plan-only pseudo-class: OOM that also fails every multi-candidate
#: bisected sub-range, forcing recovery all the way to the host path
OOM_DEEP = "oom_deep"

#: plan-only pseudo-class: FATAL that stays sticky through bisection —
#: every isolated sub-range re-fails down to single-lane, which is how
#: tests drive a poison candidate into quarantine deterministically
FATAL_DEEP = "fatal_deep"

#: plan-only brownout: the launch is not failed, it is STALLED for the
#: token's factor seconds before running (``slow@5:0.05`` = a 50 ms
#: brownout at launch index 5) — the chaos harness's degraded-device
#: event
SLOW = "slow"

_CLASSES = (TRANSIENT, OOM, HUNG, FATAL, OOM_DEEP, FATAL_DEEP, SLOW)

#: message substrings marking a device error as OOM / transient.  XLA
#: runtime errors carry their grpc-style status name in the message
#: (RESOURCE_EXHAUSTED, UNAVAILABLE, ...), so string matching is the
#: stable cross-version classifier.
_OOM_MARKERS = ("RESOURCE_EXHAUSTED", "Out of memory", "out of memory",
                "Resource exhausted", "Failed to allocate")
_TRANSIENT_MARKERS = ("UNAVAILABLE", "ABORTED", "CANCELLED",
                      "DEADLINE_EXCEEDED", "Socket closed",
                      "connection reset", "transient")

#: user-extensible classifiers, consulted first: fn(exc) -> class | None
_CUSTOM_CLASSIFIERS: List[Callable[[BaseException], Optional[str]]] = []


def register_classifier(fn: Callable[[BaseException], Optional[str]]) -> None:
    """Prepend a custom error classifier.  ``fn(exc)`` returns one of
    the taxonomy classes, or None to defer to the built-in rules —
    the extension point for backend-specific error shapes."""
    _CUSTOM_CLASSIFIERS.insert(0, fn)


class InjectedFault(RuntimeError):
    """A fault raised by the deterministic injection plan.  Carries its
    taxonomy class explicitly so classification never guesses."""

    def __init__(self, fault_class: str, message: str):
        super().__init__(message)
        self.fault_class = fault_class
        #: OOM_DEEP faults stay sticky through bisection: every
        #: multi-candidate sub-range re-fails, forcing host fallback
        self.sst_sticky_oom = fault_class == OOM_DEEP
        #: FATAL_DEEP faults stay sticky through isolation: every
        #: sub-range re-fails down to single-lane, so the quarantine
        #: counter deterministically reaches its K
        self.sst_sticky_fatal = fault_class == FATAL_DEEP


class LaunchTimeoutError(TimeoutError):
    """A launch exceeded its watchdog budget.  ``mode="wall"`` is the
    classic whole-launch ``TpuConfig.launch_timeout_s`` expiry;
    ``mode="heartbeat"`` means a scanned launch's in-flight beats
    (``obs/heartbeat.py``) went silent for ``heartbeat_timeout_s`` —
    the error then names the last scan step that beat, so a postmortem
    knows WHERE inside the multi-minute launch the device died.  Never
    silently re-run on the host (a hung device would only hang the
    host re-run's next compiled search)."""

    #: consumed by grid._dispatch: no compiled->host fallback
    _sst_no_fallback = True

    def __init__(self, key: str, group: int, timeout_s: float,
                 injected: bool = False, mode: str = "wall",
                 last_step: Optional[int] = None,
                 steps_total: Optional[int] = None):
        if mode == "heartbeat":
            at = (f"last beat at scan step {last_step}"
                  if last_step is not None
                  else "no beat ever arrived")
            msg = (f"launch {key!r} (compile group {group}) heartbeat "
                   f"went silent for heartbeat_timeout_s={timeout_s}s "
                   f"({at} of {steps_total} step(s))")
        else:
            msg = (f"launch {key!r} (compile group {group}) exceeded "
                   f"launch_timeout_s={timeout_s}s")
        super().__init__(msg + (" [injected]" if injected else ""))
        self.key = key
        self.group = group
        self.timeout_s = timeout_s
        self.injected = injected
        self.mode = mode
        self.last_step = last_step
        self.steps_total = steps_total


class SearchDeadlineError(RuntimeError):
    """The search exceeded ``TpuConfig.search_deadline_s`` under
    ``partial_results="raise"``.  Under ``"best_effort"`` the deadline
    sheds the remaining candidates to ``error_score`` instead of
    raising this."""

    #: consumed by grid._dispatch: an expired budget on the compiled
    #: path must not buy a full host re-run of the same search
    _sst_no_fallback = True

    def __init__(self, deadline_s: float, elapsed_s: float,
                 n_remaining: int = 0):
        super().__init__(
            f"search exceeded search_deadline_s={deadline_s:g}s "
            f"(elapsed {elapsed_s:.3f}s, {n_remaining} candidate(s) "
            "un-run); set partial_results='best_effort' for a declared-"
            "partial cv_results_ instead")
        self.deadline_s = deadline_s
        self.elapsed_s = elapsed_s
        self.n_remaining = n_remaining


def _normalize_class(cls: str) -> str:
    """Collapse the plan-only pseudo-classes onto the 4-way taxonomy
    recovery actually dispatches on."""
    if cls == OOM_DEEP:
        return OOM
    if cls == FATAL_DEEP:
        return FATAL
    if cls == SLOW:
        return TRANSIENT
    return cls


def classify_error(exc: BaseException) -> str:
    """Map an exception to its taxonomy class.

    Conservative by design: anything not positively identified as
    transient or OOM is FATAL, so genuine bugs keep today's behavior
    (propagate immediately; the search engine's own compiled->host
    fallback still applies) instead of burning a retry budget."""
    for fn in _CUSTOM_CLASSIFIERS:
        cls = fn(exc)
        if cls in _CLASSES:
            return _normalize_class(cls)
    if isinstance(exc, InjectedFault):
        return _normalize_class(exc.fault_class)
    if isinstance(exc, LaunchTimeoutError):
        return HUNG
    if isinstance(exc, MemoryError):
        return OOM
    msg = f"{type(exc).__name__}: {exc}"
    if any(m in msg for m in _OOM_MARKERS):
        return OOM
    if any(m in msg for m in _TRANSIENT_MARKERS):
        return TRANSIENT
    return FATAL


def is_oom(exc: BaseException) -> bool:
    return classify_error(exc) == OOM


# ---------------------------------------------------------------------------
# Deterministic fault-injection plan
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class FaultSpec:
    """Inject `fault_class` at launch `index` for its first `count`
    attempts (count=1: the launch fails once and the first retry
    succeeds).  ``factor`` carries the class's scalar knob: for the
    ``slow`` brownout class, absolute seconds the launch is stalled
    before running; for ``hung`` under the heartbeat watchdog
    (``heartbeat_timeout_s`` set and the launch is a live scanned
    segment), the scan STEP after which beats go silent — the drill
    the watchdog must catch naming that step (``hung@IDX:STEP``)."""

    index: int
    fault_class: str
    count: int = 1
    factor: float = 0.0


_PLAN_TOKEN = re.compile(
    r"(?i)^(transient|oom_deep|oom|hung|fatal_deep|fatal|slow)"
    r"@(\d+)(?:x(\d+))?(?::([0-9.]+))?$")


class FaultPlan:
    """Deterministic injection schedule over supervised launch indices.

    Spec forms (``TpuConfig(fault_plan=...)`` / ``SST_FAULT_PLAN``):

      - string: comma-separated ``CLASS@INDEX[xCOUNT]`` tokens, e.g.
        ``"transient@3,oom@5"`` or ``"transient@2x3"`` (fail 3
        consecutive attempts — enough to exhaust a retry budget);
      - sequence of ``FaultSpec`` / ``(index, class[, count])`` tuples /
        ``{"index": .., "class": .., "count": ..}`` dicts.

    Launch indices count the supervised ``LaunchItem``s in dispatch
    order (resumed chunks launch nothing and are not counted), which is
    identical at every ``pipeline_depth`` — so a plan reproduces the
    same faults in the pipelined run and the synchronous escape hatch.
    """

    def __init__(self, specs: Sequence[FaultSpec] = ()):
        self._by_index: Dict[int, FaultSpec] = {}
        for s in specs:
            if s.fault_class not in _CLASSES:
                raise ValueError(
                    f"unknown fault class {s.fault_class!r}; expected one "
                    f"of {_CLASSES}")
            if s.index in self._by_index:
                raise ValueError(
                    f"duplicate fault-plan entry for launch index "
                    f"{s.index}")
            self._by_index[s.index] = s

    def __bool__(self) -> bool:
        return bool(self._by_index)

    def __len__(self) -> int:
        return len(self._by_index)

    @property
    def specs(self) -> Tuple[FaultSpec, ...]:
        return tuple(self._by_index[i] for i in sorted(self._by_index))

    def match(self, index: int, attempt: int) -> Optional[FaultSpec]:
        """The spec to fire for this (launch index, attempt number), or
        None.  attempt counts from 0 (the first try)."""
        spec = self._by_index.get(index)
        if spec is not None and attempt < spec.count:
            return spec
        return None

    @classmethod
    def parse(cls, spec: Any) -> "FaultPlan":
        if spec is None:
            return cls(())
        if isinstance(spec, FaultPlan):
            return spec
        if isinstance(spec, str):
            out = []
            for tok in spec.split(","):
                tok = tok.strip()
                if not tok:
                    continue
                m = _PLAN_TOKEN.match(tok)
                if m is None:
                    raise ValueError(
                        f"bad fault-plan token {tok!r}; expected "
                        "CLASS@INDEX[xCOUNT][:FACTOR] with CLASS in "
                        f"{_CLASSES}, e.g. 'transient@3,oom@5,"
                        "slow@7:0.05'")
                out.append(FaultSpec(int(m.group(2)), m.group(1).lower(),
                                     int(m.group(3) or 1),
                                     float(m.group(4) or 0.0)))
            return cls(out)
        out = []
        for entry in spec:
            if isinstance(entry, FaultSpec):
                out.append(entry)
            elif isinstance(entry, dict):
                out.append(FaultSpec(
                    int(entry["index"]),
                    str(entry.get("class",
                                  entry.get("fault_class"))).lower(),
                    int(entry.get("count", 1)),
                    float(entry.get("factor", 0.0))))
            else:
                idx, fcls = entry[0], entry[1]
                count = entry[2] if len(entry) > 2 else 1
                factor = entry[3] if len(entry) > 3 else 0.0
                out.append(FaultSpec(int(idx), str(fcls).lower(),
                                     int(count), float(factor)))
        return cls(out)

    @classmethod
    def resolve(cls, config=None) -> "FaultPlan":
        """The active plan: ``TpuConfig.fault_plan`` when set, else the
        ``SST_FAULT_PLAN`` environment variable, else empty."""
        spec = getattr(config, "fault_plan", None) if config is not None \
            else None
        if spec is None:
            spec = os.environ.get("SST_FAULT_PLAN") or None
        return cls.parse(spec)


# ---------------------------------------------------------------------------
# Supervisor
# ---------------------------------------------------------------------------


class _Recovered:
    """Marker wrapping an already-gathered HOST result produced by a
    recovery path (bisection merge or host fallback).  The wrapped
    item's wait/gather phases pass it through / unwrap it, so the
    original finalize runs unchanged — writing cells and the checkpoint
    record under the original chunk id."""

    __slots__ = ("value",)

    def __init__(self, value):
        self.value = value


#: indirection so tests can substitute a controllable blocker
_block_until_ready = jax.block_until_ready

#: cap on per-search recovery-event records kept in the report
_MAX_EVENTS = 64

#: process-wide mutex serializing recovery relaunches across
#: concurrently-recovering searches.  A fused-launch fault scatters to
#: every member, so member supervisors routinely bisect at the same
#: moment on their own threads; steady-state launches serialize on the
#: executor's dispatch loop, which makes these recovery attempts the
#: only same-instant device entry from multiple host threads — a
#: combination observed to wedge the CPU backend (both threads parked
#: inside execute, zero progress).  The device is serial anyway, so
#: holding this across an attempt costs recovery nothing, and the loop
#: never takes it: one tenant's recovery still cannot stall another's
#: steady-state dispatch.
_RECOVERY_EXEC_LOCK = named_lock("faults._RECOVERY_EXEC_LOCK")


class LaunchSupervisor:
    """Wrap the search's ``LaunchItem`` stream with retry / bisection /
    watchdog / injection semantics.

    Usage (``search/grid.py _run_groups``)::

        sup = LaunchSupervisor(config, faults=metrics.struct("faults"),
                               ckpt=ckpt)
        pipe.run(sup.wrap(chunk_items()))

    The fault-free fast path costs one try/except per launch phase; the
    watchdog thread only exists while ``launch_timeout_s`` is set.
    Recovery runs on whichever thread hit the failure (the dispatch
    thread for synchronous launch errors, the gather thread for errors
    surfacing at ``block_until_ready``) — already-dispatched launches
    keep computing meanwhile.
    """

    def __init__(self, config=None, faults: Optional[Dict[str, Any]] = None,
                 ckpt=None, verbose: int = 0, reset_faults: bool = True,
                 memory_info: Optional[
                     Callable[[str, int], Dict[str, Any]]] = None):
        self.max_launch_retries = int(
            getattr(config, "max_launch_retries", 2) or 0)
        self.max_search_retries = int(
            getattr(config, "max_search_retries", 16) or 0)
        self.retry_backoff_s = float(
            getattr(config, "retry_backoff_s", 0.5) or 0.0)
        self.retry_backoff_mult = float(
            getattr(config, "retry_backoff_mult", 2.0) or 1.0)
        self.retry_jitter_frac = float(
            getattr(config, "retry_jitter_frac", 0.25) or 0.0)
        self.launch_timeout_s = getattr(config, "launch_timeout_s", None)
        #: heartbeat-aware watchdog (obs/heartbeat.py): a scanned
        #: launch with a live hub segment is declared HUNG when its
        #: beats go silent this long — launches without one (per-chunk
        #: path, heartbeat off) keep the wall-clock semantics above
        self.heartbeat_timeout_s = getattr(
            config, "heartbeat_timeout_s", None)
        #: keys whose hung injection capped the beat stream instead of
        #: raising at launch: wait_ready treats them as wedged even
        #: though the drill's device work completes (guarded by
        #: self._lock)
        self._hb_stall_keys: set = set()
        self.plan = FaultPlan.resolve(config)
        self.verbose = int(verbose)
        self._ckpt = ckpt
        #: kept for flight-recorder dumps (TpuConfig.flight_dir /
        #: SST_FLIGHT_DIR resolve at dump time)
        self._config = config
        #: device-memory forensics hook (search/grid.py): (key, group)
        #: -> {modeled_bytes, budget_bytes, ...} stamped onto every OOM
        #: event, so bisection outcomes show what the footprint model
        #: believed — and train its safety margin
        self._memory_info = memory_info
        #: one OOM bundle per search — a deep bisection storm must not
        #: dump a bundle per sub-range (guarded by self._lock)
        self._oom_dumped = False
        self._tracer = get_tracer()
        self._lock = named_lock("faults.LaunchSupervisor._lock")
        self._seq = 0
        self._retries_used = 0
        # count of in-flight sticky (oom_deep) recoveries, not a bool:
        # concurrent recoveries on the dispatch and gather threads each
        # enter/leave independently, and a saved-prev restore would let
        # one recovery clobber the other's flag
        self._sticky_oom = 0
        # same shape for sticky (fatal_deep) isolations
        self._sticky_fatal = 0
        # poison-candidate quarantine (self-protecting service): active
        # only under partial_results="best_effort".  A launch key whose
        # single-lane range faults FATAL quarantine_k times is written
        # to error_score instead of killing the search.
        self.quarantine_k = (
            int(getattr(config, "quarantine_fatal_k", 3) or 0)
            if str(getattr(config, "partial_results", "raise")
                   or "raise") == "best_effort" else 0)
        self._fatal_counts: Dict[str, int] = {}
        # one FATAL bundle per launch key while quarantine is counting
        # to K — K identical failures must not dump K bundles
        self._fatal_dumped: set = set()
        self.faults: Dict[str, Any] = faults if faults is not None else {}
        defaults = {
            "retries": 0, "bisections": 0, "host_fallbacks": 0,
            "timeouts": 0, "injected": 0, "by_class": {}, "events": [],
        }
        if reset_faults:
            self.faults.update(defaults)
        else:
            # a halving search wraps each rung in its own supervisor
            # over ONE shared faults struct: later rungs keep the
            # earlier rungs' recovery record instead of zeroing it
            for k, v in defaults.items():
                self.faults.setdefault(k, v)

    # -- accounting ------------------------------------------------------
    def _count(self, name: str, n: int = 1) -> None:
        with self._lock:
            self.faults[name] += n

    def _mem_extra(self, key: str, group: int) -> Dict[str, Any]:
        """Modeled-vs-budget bytes for an OOM event (the device-memory
        ledger's forensics; empty when no hook is installed).  Must
        never turn a recovery into a second failure."""
        if self._memory_info is None:
            return {}
        try:
            return dict(self._memory_info(key, group) or {})
        # forensics only: a broken lookup loses the memory annotation,
        # never the recovery itself — the fault being annotated is
        # already classified by the caller
        # sstlint: disable=broad-except-swallow,swallowed-exception,launch-except-taxonomy
        except Exception:
            return {}

    def _hb_extra(self, exc: Optional[BaseException]) -> Dict[str, Any]:
        """The heartbeat watchdog's forensics for a HUNG verdict: which
        scan step last beat before the silence — stamped onto the fault
        event and the flight bundle so a postmortem names the step."""
        if not isinstance(exc, LaunchTimeoutError) or \
                exc.mode != "heartbeat":
            return {}
        return {"watchdog_mode": exc.mode,
                "last_step": exc.last_step,
                "steps_total": exc.steps_total}

    def _record_event(self, key: str, group: int, cls: str, action: str,
                      exc: Optional[BaseException], attempt: int) -> None:
        mem = self._mem_extra(key, group) if cls == OOM else {}
        hb = self._hb_extra(exc) if cls == HUNG else {}
        with self._lock:
            by = self.faults["by_class"]
            by[cls] = by.get(cls, 0) + 1
            ev = self.faults["events"]
            if len(ev) < _MAX_EVENTS:
                ev.append({
                    "key": key, "group": group, "class": cls,
                    "action": action, "attempt": attempt,
                    "error": (f"{type(exc).__name__}: {exc}"[:200]
                              if exc is not None else ""),
                    **mem, **hb})
        if self._ckpt is not None:
            # durable fault journal: a resume after a failed recovery
            # still knows which chunk was in trouble (and the completed
            # chunks' result records are already streamed)
            try:
                self._ckpt.note_fault(key, {
                    "class": cls, "action": action, "attempt": attempt,
                    "error": (f"{type(exc).__name__}: {exc}"[:200]
                              if exc is not None else "")})
            except OSError:
                _slog.warning("fault journal write failed for %s", key)
        # fleet telemetry + the flight recorder's event ring (both
        # called outside self._lock; the telemetry hook is an exact
        # no-op when the service is disabled)
        _telemetry.note_fault(cls, action)
        _telemetry.flight_recorder().note(
            "fault", key=key, group=group, fault_class=cls,
            action=action, attempt=attempt,
            error=(f"{type(exc).__name__}: {exc}"[:200]
                   if exc is not None else ""))
        self._maybe_flight_dump(key, group, cls, action, exc, attempt)

    def _maybe_flight_dump(self, key: str, group: int, cls: str,
                           action: str, exc: Optional[BaseException],
                           attempt: int) -> None:
        """Black-box bundles for the incidents worth a postmortem:
        FATAL raises, watchdog timeouts, and the FIRST OOM recovery of
        the search (the 3 a.m. OOM the flight recorder exists for —
        deduped so a deep bisection storm dumps one bundle, not one
        per sub-range).  No-op unless ``TpuConfig.flight_dir`` /
        ``SST_FLIGHT_DIR`` names a directory — checked FIRST so the
        default no-dump configuration never pays the payload copy."""
        if _telemetry.resolve_flight_dir(self._config) is None:
            return
        reason = None
        if cls == FATAL and action == "raise":
            if self.quarantine_k:
                # quarantine counts the SAME launch key failing K
                # times: one bundle per key, not one per attempt
                with self._lock:
                    if key in self._fatal_dumped:
                        return
                    self._fatal_dumped.add(key)
            reason = "fatal"
        elif cls == HUNG:
            reason = "watchdog-timeout"
        elif cls == OOM and action == "recover":
            with self._lock:
                if self._oom_dumped:
                    return
                self._oom_dumped = True
            reason = "oom"
        if reason is None:
            return
        with self._lock:
            faults_copy = copy.deepcopy(self.faults)
        mem = self._mem_extra(key, group) if cls == OOM else {}
        hb = self._hb_extra(exc) if cls == HUNG else {}
        _telemetry.flight_recorder().dump(
            reason, config=self._config, faults=faults_copy,
            context={"key": key, "group": group, "class": cls,
                     "action": action, "attempt": attempt,
                     "error": (f"{type(exc).__name__}: {exc}"[:300]
                               if exc is not None else ""),
                     **mem, **hb})

    def record_bisection(self, key: str, group: int,
                         fault_class: str = OOM) -> None:
        """Called by the item's bisect hook once per split — OOM
        recovery by default; FATAL when the quarantine path isolates a
        poison range (search/grid.py exec_fused_range)."""
        self._count("bisections")
        self._record_event(key, group, fault_class, "bisect", None, 0)
        _slog.warning("launch %s: %s — bisecting the chunk", key,
                      fault_class, key=key, group=group)

    def record_host_fallback(self, key: str, group: int, n_tasks: int) -> None:
        """Called by recovery paths when a range degrades to per-
        candidate host execution."""
        self._count("host_fallbacks")
        self._record_event(key, group, OOM, "host_fallback", None, 0)
        _slog.warning(
            "launch %s: bisection bottomed out — running %d task(s) on "
            "the host with sklearn error_score semantics", key, n_tasks,
            key=key, group=group, n_tasks=n_tasks)

    # -- poison-candidate quarantine -------------------------------------
    def note_fatal(self, key: str) -> int:
        """Count one FATAL fault on a single-lane range, returning the
        total for that launch key — the quarantine counter the fused-
        range recursion in search/grid.py compares against K."""
        with self._lock:
            n = self._fatal_counts.get(key, 0) + 1
            self._fatal_counts[key] = n
        return n

    def record_quarantine(self, key: str, group: int,
                          exc: BaseException, n_faults: int) -> None:
        """A single-lane range faulted FATAL K times: journal the
        quarantine verdict, tell telemetry, and dump a protection
        bundle — the search itself continues with the candidate
        written to error_score."""
        self._record_event(key, group, FATAL, "quarantine", exc,
                           n_faults)
        _telemetry.note_protection("quarantined")
        self._protection_dump("quarantine", key, group, exc,
                              extra={"n_faults": n_faults,
                                     "quarantine_k": self.quarantine_k})
        _slog.warning(
            "launch %s: single-lane range faulted FATAL %d time(s) — "
            "quarantining the candidate to error_score (the search "
            "continues)", key, n_faults, key=key, group=group)

    def _protection_dump(self, verdict: str, key: str, group: int,
                         exc: Optional[BaseException],
                         extra: Optional[Dict[str, Any]] = None) -> None:
        """One protection-verdict flight bundle (no-op unless a flight
        directory is configured)."""
        if _telemetry.resolve_flight_dir(self._config) is None:
            return
        with self._lock:
            faults_copy = copy.deepcopy(self.faults)
        _telemetry.flight_recorder().protection_dump(
            verdict, config=self._config, faults=faults_copy,
            context={"key": key, "group": group,
                     "error": (f"{type(exc).__name__}: {exc}"[:300]
                               if exc is not None else ""),
                     **(extra or {})})

    # -- injection -------------------------------------------------------
    def _maybe_inject(self, st: Dict[str, Any]) -> None:
        spec = self.plan.match(st["index"], st["attempt"])
        if spec is None:
            return
        self._count("injected")
        item = st["item"]
        _slog.warning(
            "fault plan: injecting %s at launch %d (%s) attempt %d",
            spec.fault_class, st["index"], item.key, st["attempt"],
            key=item.key, fault_class=spec.fault_class,
            attempt=st["attempt"])
        if spec.fault_class == SLOW:
            # a brownout stalls the launch instead of failing it: the
            # chaos harness's degraded-device event — journaled like a
            # fault so soak runs can assert it happened, but the launch
            # itself proceeds and stays bit-exact
            self._record_event(item.key, item.group, SLOW, "brownout",
                               None, st["attempt"])
            if spec.factor > 0.0:
                time.sleep(spec.factor)
            return
        if spec.fault_class == HUNG:
            if self.heartbeat_timeout_s:
                # heartbeat-mode stall drill: instead of failing at
                # launch, silence the beat stream after step FACTOR on
                # the live scanned segment — the heartbeat watchdog in
                # wait_ready must detect the silence and name the step
                from spark_sklearn_tpu.obs import heartbeat as _hb
                if _hb.get_hub().cap_beats(item.key,
                                           int(spec.factor)):
                    with self._lock:
                        self._hb_stall_keys.add(item.key)
                    return
            raise LaunchTimeoutError(
                item.key, item.group, float(self.launch_timeout_s or 0.0),
                injected=True)
        marker = ("RESOURCE_EXHAUSTED: " if spec.fault_class
                  in (OOM, OOM_DEEP) else "")
        raise InjectedFault(
            spec.fault_class,
            f"{marker}injected {spec.fault_class} fault at launch index "
            f"{st['index']} ({item.key}), attempt {st['attempt']}")

    def inject_subrange(self, n_real: int) -> None:
        """Consulted by bisected sub-launches: under a sticky
        (``oom_deep``) fault every sub-range re-fails — single
        candidates included — so the recursion deterministically
        bottoms out into the per-candidate host path.  A sticky
        (``fatal_deep``) fault does the same with FATAL, driving the
        single-lane range into the quarantine counter."""
        if self._sticky_oom:
            self._count("injected")
            raise InjectedFault(
                OOM, "RESOURCE_EXHAUSTED: injected sticky OOM on a "
                     f"bisected sub-range of {n_real} candidate(s)")
        if self._sticky_fatal:
            self._count("injected")
            raise InjectedFault(
                FATAL_DEEP, "injected sticky FATAL on an isolated "
                            f"sub-range of {n_real} candidate(s)")

    # -- watchdog --------------------------------------------------------
    def wait_ready(self, out, key: str = "", group: int = 0):
        """``jax.block_until_ready`` bounded by the watchdog budget.

        Two modes: the classic whole-launch ``launch_timeout_s`` wall
        clock, and — when ``heartbeat_timeout_s`` is set AND the hub
        owns a live scanned segment for ``key`` — a heartbeat poll
        that declares the launch HUNG when in-flight beats go silent,
        naming the last scan step that beat (a scanned rung can
        legitimately run for many minutes; its beats must not).

        The blocking wait runs on a disposable daemon thread; on
        timeout the search fails with :class:`LaunchTimeoutError`
        (naming the chunk and compile group) while the wedged wait
        thread is abandoned — the one leak a hung device costs, instead
        of a gather thread hung forever."""
        if isinstance(out, _Recovered):
            return out
        hub = None
        hb_timeout = float(self.heartbeat_timeout_s or 0.0)
        if hb_timeout > 0.0 and key:
            from spark_sklearn_tpu.obs import heartbeat as _hb
            h = _hb.get_hub()
            if h.live_segment(key):
                hub = h
        if not self.launch_timeout_s and hub is None:
            return _block_until_ready(out)
        box: Dict[str, Any] = {}
        done = threading.Event()

        def blocker():
            try:
                box["out"] = _block_until_ready(out)
            # nothing is swallowed here: the watchdog thread marshals
            # EVERY exception (KeyboardInterrupt included) back to the
            # waiting caller, which re-raises it below
            # sstlint: disable=broad-except-swallow,launch-except-taxonomy
            except BaseException as exc:       # re-raised on the caller
                box["exc"] = exc
            finally:
                done.set()

        threading.Thread(target=blocker, daemon=True,
                         name="sst-watchdog-wait").start()
        if hub is None:
            if not done.wait(float(self.launch_timeout_s)):
                raise LaunchTimeoutError(key, group,
                                         float(self.launch_timeout_s))
        else:
            with self._lock:
                stalled = key in self._hb_stall_keys
            t0 = time.perf_counter()
            poll = max(0.005, min(hb_timeout / 4.0, 0.25))
            while True:
                if done.is_set():
                    # an injected stall's drill work completes; the
                    # watchdog must still see the silence, so keep
                    # polling staleness instead of returning
                    finished = True
                    time.sleep(poll)
                else:
                    finished = done.wait(poll)
                st = hub.staleness(key)
                if finished and (not stalled or st is None):
                    break
                if st is not None and st["age_s"] >= hb_timeout:
                    raise LaunchTimeoutError(
                        key, group, hb_timeout, injected=stalled,
                        mode="heartbeat", last_step=st["last_step"],
                        steps_total=st["n_steps"])
                if self.launch_timeout_s and \
                        time.perf_counter() - t0 \
                        > float(self.launch_timeout_s):
                    raise LaunchTimeoutError(
                        key, group, float(self.launch_timeout_s))
        if "exc" in box:
            raise box["exc"]
        return box["out"]

    # -- retry loop shared by wrapped items and bisected sub-launches ----
    def _backoff_delay(self, key: str, attempt: int) -> float:
        base = self.retry_backoff_s * (
            self.retry_backoff_mult ** max(0, attempt - 1))
        if self.retry_jitter_frac <= 0.0:
            return base
        # deterministic jitter: reproducible runs need reproducible
        # sleeps, so the jitter hashes (key, attempt) instead of
        # sampling a live RNG
        u = zlib.crc32(f"{key}:{attempt}".encode()) / 2 ** 32
        return base * (1.0 + self.retry_jitter_frac * (u - 0.5))

    def _take_retry_budget(self, key: str) -> bool:
        with self._lock:
            if self._retries_used >= self.max_search_retries:
                return False
            self._retries_used += 1
            self.faults["retries"] += 1
        return True

    def _retry_gate(self, key: str, group: int, attempt: int,
                    exc: Exception) -> None:
        """The one transient-retry policy: consume budget, journal the
        event, back off — or re-raise `exc` when a budget is spent.
        Shared by the wrapped-item recovery loop and bisected
        sub-launch retries so the two paths cannot drift."""
        if attempt > self.max_launch_retries or \
                not self._take_retry_budget(key):
            self._record_event(key, group, TRANSIENT,
                               "retries_exhausted", exc, attempt)
            self._protection_dump(
                "retries-exhausted", key, group, exc,
                extra={"attempt": attempt,
                       "retries_used": self._retries_used,
                       "max_launch_retries": self.max_launch_retries,
                       "max_search_retries": self.max_search_retries})
            _slog.warning(
                "launch %s: transient fault but retry budget exhausted "
                "(%d/%d per launch, %d/%d per search)", key,
                attempt - 1, self.max_launch_retries, self._retries_used,
                self.max_search_retries, key=key)
            raise exc
        self._record_event(key, group, TRANSIENT, "retry", exc, attempt)
        delay = self._backoff_delay(key, attempt)
        _slog.warning(
            "launch %s: transient fault (%r), retry %d/%d in %.3fs",
            key, exc, attempt, self.max_launch_retries, delay,
            key=key, attempt=attempt)
        time.sleep(delay)

    def call(self, fn: Callable[[], Any], key: str, group: int = 0,
             n_real: Optional[int] = None):
        """Run ``fn`` (a full stage->launch->wait->gather closure used
        by bisected sub-launches) under transient-retry semantics.  OOM
        and HUNG propagate to the caller — the bisection recursion in
        the item's hook decides what OOM means at its depth."""
        attempt = 0
        while True:
            try:
                if n_real is not None:
                    self.inject_subrange(n_real)
                if attempt == 0:
                    with _RECOVERY_EXEC_LOCK:
                        return fn()
                with self._tracer.span("launch.retry", key=key,
                                       group=group, attempt=attempt):
                    with _RECOVERY_EXEC_LOCK:
                        return fn()
            except Exception as exc:
                if getattr(exc, "_sst_cancelled", False):
                    # a cancelled search (serve.SearchCancelledError) is
                    # an instruction, not a fault: no retry, no event
                    raise
                cls = classify_error(exc)
                if cls != TRANSIENT:
                    if cls != OOM:
                        self._record_event(key, group, cls, "raise", exc,
                                           attempt)
                    if cls == HUNG:
                        self._count("timeouts")
                    raise
                attempt += 1
                self._retry_gate(key, group, attempt, exc)

    # -- item wrapping ---------------------------------------------------
    def wrap(self, items):
        """Wrap an iterable of LaunchItems (lazily — the pipeline's
        stage-ahead behavior is preserved)."""
        for item in items:
            idx = self._seq
            self._seq += 1
            yield self._wrap_one(item, idx)

    def _wrap_one(self, item: LaunchItem, index: int) -> LaunchItem:
        st = {"item": item, "index": index, "attempt": 0}

        def guarded_launch(payload):
            try:
                self._maybe_inject(st)
                return item.launch(payload)
            except Exception as exc:
                return self._recover(st, exc)

        def guarded_wait(out):
            if isinstance(out, _Recovered):
                return out
            try:
                return self.wait_ready(out, key=item.key, group=item.group)
            except Exception as exc:
                return self._recover(st, exc)

        def guarded_gather(out):
            if isinstance(out, _Recovered):
                return out.value
            return item.gather(out) if item.gather is not None else None

        return LaunchItem(
            key=item.key, launch=guarded_launch, stage=item.stage,
            gather=guarded_gather, finalize=item.finalize,
            group=item.group, kind=item.kind, n_tasks=item.n_tasks,
            n_chunks=item.n_chunks, wait=guarded_wait)

    # -- recovery --------------------------------------------------------
    def _recover(self, st: Dict[str, Any], exc: Exception):
        item = st["item"]
        while True:
            if getattr(exc, "_sst_cancelled", False):
                # cancellation (serve.SearchFuture.cancel) must unwind
                # the search promptly: no retry budget, no recovery
                # hooks, no fault journal entry — the checkpoint's
                # completed chunks already make the search resumable
                raise exc
            cls = classify_error(exc)
            if cls == FATAL:
                if self.quarantine_k and item.bisect is not None:
                    # poison-candidate isolation: split the range and
                    # re-run the halves instead of killing the search
                    # — the fused-range recursion in search/grid.py
                    # counts single-lane FATALs into quarantine
                    self._record_event(item.key, item.group, cls,
                                       "isolate", exc, st["attempt"])
                    sticky = bool(getattr(exc, "sst_sticky_fatal",
                                          False))
                    with self._tracer.span("launch.isolate",
                                           key=item.key,
                                           group=item.group):
                        if sticky:
                            with self._lock:
                                self._sticky_fatal += 1
                        try:
                            return _Recovered(item.bisect(self))
                        finally:
                            if sticky:
                                with self._lock:
                                    self._sticky_fatal -= 1
                # a real bug: propagate unchanged (the search engine's
                # compiled->host fallback still applies above us)
                self._record_event(item.key, item.group, cls, "raise",
                                   exc, st["attempt"])
                raise exc
            if cls == HUNG:
                self._count("timeouts")
                self._record_event(item.key, item.group, cls, "fail",
                                   exc, st["attempt"])
                _slog.warning(
                    "launch %s (group %d): watchdog timeout — failing "
                    "the search cleanly (completed chunks are already "
                    "checkpointed)", item.key, item.group, key=item.key)
                if isinstance(exc, LaunchTimeoutError):
                    raise exc
                raise LaunchTimeoutError(
                    item.key, item.group,
                    float(self.launch_timeout_s or 0.0)) from exc
            if cls == OOM:
                return self._recover_oom(st, exc)
            # TRANSIENT: exponential backoff + jitter, then re-run the
            # item's own phases — same program, same inputs
            st["attempt"] += 1
            self._retry_gate(item.key, item.group, st["attempt"], exc)
            try:
                with self._tracer.span("launch.retry", key=item.key,
                                       group=item.group,
                                       attempt=st["attempt"]):
                    self._maybe_inject(st)
                    payload = item.stage() if item.stage is not None \
                        else None
                    out = item.launch(payload)
                    return self.wait_ready(out, key=item.key,
                                           group=item.group)
            except Exception as e:
                exc = e

    def _recover_oom(self, st: Dict[str, Any], exc: Exception):
        item = st["item"]
        self._record_event(item.key, item.group, OOM, "recover", exc,
                           st["attempt"])
        sticky = bool(getattr(exc, "sst_sticky_oom", False))
        if item.bisect is not None:
            with self._tracer.span("launch.bisect", key=item.key,
                                   group=item.group):
                # the sticky count is shared supervisor state read by
                # every bisected sub-launch; recoveries can run on the
                # dispatch AND gather threads concurrently, so each
                # sticky recovery holds its own +1 for its duration
                if sticky:
                    with self._lock:
                        self._sticky_oom += 1
                try:
                    return _Recovered(item.bisect(self))
                finally:
                    if sticky:
                        with self._lock:
                            self._sticky_oom -= 1
        if item.host_fallback is not None:
            self.record_host_fallback(item.key, item.group, item.n_tasks)
            with self._tracer.span("launch.host_fallback", key=item.key,
                                   group=item.group):
                return _Recovered(item.host_fallback())
        _slog.warning(
            "launch %s: OOM with no bisect/host_fallback hook — "
            "propagating", item.key, key=item.key)
        raise exc


# ---------------------------------------------------------------------------
# Protection block (search_report["protection"])
# ---------------------------------------------------------------------------


def protection_enabled(config) -> bool:
    """Whether the self-protecting layer is active for this config.
    False is the exact-no-op escape hatch: no protection block, reports
    and cv_results_ byte-identical to the pre-protection engine."""
    return bool(getattr(config, "search_deadline_s", None)) or \
        str(getattr(config, "partial_results", "raise")
            or "raise") != "raise" or \
        str(getattr(config, "admission_mode", "static")
            or "static") != "static"


def protection_block(config, *, deadline_hit: bool = False,
                     shed: Sequence[Dict[str, Any]] = (),
                     quarantined: Sequence[Dict[str, Any]] = (),
                     elapsed_s: float = 0.0) -> Dict[str, Any]:
    """Render the pinned ``search_report["protection"]`` block (schema:
    ``obs.metrics.PROTECTION_BLOCK_SCHEMA``).  ``shed`` entries name
    candidates written to error_score without running (deadline or
    persistent-fault degradation); ``quarantined`` entries name poison
    candidates isolated after K single-lane FATALs."""
    shed = [dict(e) for e in shed]
    quarantined = [dict(e) for e in quarantined]
    causes = []
    if deadline_hit:
        causes.append("deadline")
    if quarantined:
        causes.append("quarantine")
    if any(e.get("reason") == "fault" for e in shed):
        causes.append("fault")
    partial = bool(shed or quarantined)
    verdict = "complete" if not causes and not partial else \
        "partial-" + "+".join(causes or ["declared"])
    return {
        "enabled": True,
        "mode": str(getattr(config, "admission_mode", "static")
                    or "static"),
        "partial_results": str(getattr(config, "partial_results",
                                       "raise") or "raise"),
        "deadline_s": float(getattr(config, "search_deadline_s", 0.0)
                            or 0.0),
        "deadline_hit": bool(deadline_hit),
        "elapsed_s": float(elapsed_s),
        "partial": partial,
        "n_candidates_shed": sum(
            len(e.get("candidates", ())) for e in shed),
        "n_quarantined": len(quarantined),
        "shed": shed,
        "quarantined": quarantined,
        "verdict": verdict,
    }


# ---------------------------------------------------------------------------
# Crash-marker context (the service journal's unclean-shutdown bundle)
# ---------------------------------------------------------------------------


def crash_marker_context(nonterminal: Dict[str, Dict[str, Any]],
                         lease_info: Optional[Dict[str, Any]] = None,
                         ) -> Dict[str, Any]:
    """The ``context`` block of a crash-marker flight bundle.

    Dumped by a session that fences a stale service-journal lease
    (serve/journal.py): the previous owner died without a clean
    shutdown, and the bundle's context names who it was, how stale its
    heartbeat stamp had grown, and every search it still owed —
    exactly what the postmortem (and ``tools/sst_doctor.py``) needs
    before the recovered searches overwrite the scene."""
    lease_info = dict(lease_info or {})
    prev = dict(lease_info.get("previous") or {})
    owed = []
    for handle in sorted(nonterminal):
        rec = nonterminal[handle]
        owed.append({
            "handle": handle,
            "tenant": str(rec.get("tenant", "")),
            "state": str(rec.get("state", "")),
            "family": str(rec.get("family", "")),
            "structure_digest": str(rec.get("structure_digest", "")),
            "checkpoint_dir": str(rec.get("checkpoint_dir", "")),
        })
    return {
        "crash_marker": True,
        "previous_pid": int(prev.get("pid", 0) or 0),
        "previous_owner": str(prev.get("owner", "")),
        "lease_stamp_unix_s": float(prev.get("ts_unix_s", 0.0) or 0.0),
        "n_nonterminal": len(owed),
        "nonterminal": owed,
    }
