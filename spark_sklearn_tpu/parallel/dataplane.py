"""Device-resident data plane — the session-scoped broadcast cache.

The reference amortized dataset shipping with ``sc.broadcast``: X/y went
to every executor ONCE and every task reused the handle (reference:
grid_search.py ``X_bc = sc.broadcast(X)``).  Before this module the TPU
rebuild re-shipped per search: every ``fit`` re-``device_put`` X/y and
every fold mask even inside one :class:`~spark_sklearn_tpu.utils.
session.TpuSession`, and task-batched families re-tiled the fold masks
on the HOST (``np.tile`` to ``(width x n_folds, n_samples)``) once per
compile group — a multi-MB host allocation plus transfer per group, and
per RELAUNCH in OOM recovery.  Ousterhout-style overhead analysis of
distributed ML (arXiv:1612.01437) and DrJAX's device-resident MapReduce
primitives (arXiv:2403.07128) both land on the same answer: keep
operands resident, size the fan-out to the measured cost, never re-ship
per task.

:class:`DataPlane` is that answer here:

  - **fingerprint-keyed**: entries key on a content digest (blake2b of
    bytes + shape + dtype) so two searches over the same data share one
    upload no matter how the arrays were constructed;
  - **sharding-aware**: the key includes the target sharding (mesh
    device order + partition spec), so a replicated X and a
    data-sharded X are distinct residents and a mesh change can never
    serve a stale layout;
  - **byte-budgeted LRU**: entries are evicted least-recently-used once
    the budget (``TpuConfig.dataplane_bytes``) is exceeded — a
    long-lived session cycling many datasets bounds its own HBM;
  - **on-device mask tiling**: :meth:`DataPlane.tiled` replaces the
    host ``np.tile`` + upload with a one-time base-mask upload plus a
    tiny compiled broadcast per (width, sharding) whose result is
    itself cached — fold masks transfer host->device at most once per
    search, not once per group/launch;
  - **observable**: hits/misses/bytes land in ``search_report
    ["dataplane"]`` (schema pinned in ``obs.metrics``), every real
    transfer records a ``dataplane.upload`` span carrying its byte
    count (``tools/trace_summary.py`` digests them into a "bytes
    host->device" line).

Cache entries fingerprint content AT UPLOAD TIME: mutating an array in
place after a search produces a new fingerprint (and a fresh upload) on
the next search — entries are never revalidated on hit.

Plane entries must never be donated to XLA (donation invalidates the
buffer for every later consumer); the engine only donates per-chunk
dynamic-parameter staging, which bypasses the cache via
:func:`upload`.
"""

from __future__ import annotations

import hashlib
import threading
from collections import OrderedDict
from typing import Any, Dict, Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from spark_sklearn_tpu.obs import telemetry as _telemetry
from spark_sklearn_tpu.obs.trace import get_tracer
from spark_sklearn_tpu.utils import keycheck as _keycheck
from spark_sklearn_tpu.utils.locks import named_lock, named_rlock

__all__ = [
    "DataPlane",
    "StagingRing",
    "bytes_uploaded",
    "fingerprint",
    "get_dataplane",
    "plane_for",
    "upload",
]

#: default byte budget (256 MiB) — enough to keep a bench-scale dataset,
#: its fold masks and a few tiled-mask widths resident, small enough to
#: be harmless on the CPU test mesh.
DEFAULT_BYTE_BUDGET = 256 * 2 ** 20

#: process-wide host->device transfer accounting (every ``upload`` call,
#: cacheable or not) — the pipeline's per-launch ``stage_bytes`` and the
#: trace digest read this.
_TOTALS = {"bytes": 0, "uploads": 0}
_TOTALS_LOCK = named_lock("dataplane._TOTALS_LOCK")


def bytes_uploaded() -> int:
    """Cumulative host->device bytes this process transferred through
    the data plane (cache-miss broadcasts AND per-chunk staging).
    Callers snapshot before/after a phase and report the delta."""
    with _TOTALS_LOCK:
        return _TOTALS["bytes"]


def upload(arr: np.ndarray, sharding=None, label: str = "staging"):
    """``jax.device_put`` with byte accounting and a traced
    ``dataplane.upload`` span (the span carries ``bytes`` so transfer
    regressions show up in the trace digest).  This is the ONLY
    device_put the search engine's data paths use — cached entries go
    through :meth:`DataPlane.put`, which calls this on a miss."""
    nbytes = int(getattr(arr, "nbytes", 0))
    with get_tracer().span("dataplane.upload", bytes=nbytes, label=label):
        out = (jax.device_put(arr, sharding) if sharding is not None
               else jax.device_put(arr))
    with _TOTALS_LOCK:
        _TOTALS["bytes"] += nbytes
        _TOTALS["uploads"] += 1
    # fleet telemetry (outside the totals lock; exact no-op off)
    _telemetry.note_h2d(nbytes)
    return out


def _csr_parts(arr):
    """``(data, indices, indptr, shape)`` of a CSR-like host matrix
    (scipy csr/csc or :class:`~spark_sklearn_tpu.sparse.csr.CSRMatrix`),
    or None for anything else.  Duck-typed so the data plane never
    imports scipy just to recognise its matrices."""
    if isinstance(arr, np.ndarray) or not hasattr(arr, "indptr"):
        return None
    data = getattr(arr, "data", None)
    indices = getattr(arr, "indices", None)
    if data is None or indices is None:
        return None
    return (np.asarray(data), np.asarray(indices),
            np.asarray(arr.indptr), tuple(int(s) for s in arr.shape))


def fingerprint(arr: np.ndarray) -> str:
    """Content digest of a host array: blake2b over the raw bytes plus
    shape/dtype.  Full-content (not sampled) — a wrong cache hit would
    silently corrupt scores, and hashing runs at ~1 GB/s, far cheaper
    than the transfer it saves.

    CSR-like inputs digest their ``(data, indices, indptr, shape)``
    components directly — fingerprinting a wide sparse X must never
    allocate its dense form (pinned by test_dataplane.py)."""
    parts = _csr_parts(arr)
    h = hashlib.blake2b(digest_size=16)
    if parts is not None:
        data, indices, indptr, shape = parts
        h.update(repr(("csr", shape, data.dtype.str,
                       indices.dtype.str)).encode())
        for a in (data, indices, indptr):
            a = np.ascontiguousarray(a)
            h.update(a.data if a.flags["C_CONTIGUOUS"] else a.tobytes())
        return h.hexdigest()
    a = np.ascontiguousarray(arr)
    h.update(repr((a.shape, a.dtype.str)).encode())
    h.update(a.data if a.flags["C_CONTIGUOUS"] else a.tobytes())
    return h.hexdigest()


def _sharding_key(sharding) -> Any:
    """Hashable identity of a placement: device order + partition spec
    (+ memory kind).  Two meshes over the same chips in a different
    order are different placements."""
    if sharding is None:
        return None
    mesh = getattr(sharding, "mesh", None)
    if mesh is not None:
        devs = tuple(d.id for d in np.asarray(mesh.devices).flat)
        shape = tuple(sorted(dict(mesh.shape).items()))
    else:
        devs = tuple(sorted(d.id for d in sharding.device_set))
        shape = None
    return (type(sharding).__name__, devs, shape,
            repr(getattr(sharding, "spec", None)),
            getattr(sharding, "memory_kind", None))


class DataPlane:
    """Fingerprint-keyed, byte-budgeted LRU cache of device arrays.

    One process-global instance (:func:`get_dataplane`) is shared by
    every search; a :class:`~spark_sklearn_tpu.utils.session.TpuSession`
    sizes its budget at construction (``TpuConfig.dataplane_bytes``).
    Thread-safe: the pipeline's stage thread and the fault supervisor's
    recovery threads may all reach it concurrently.
    """

    def __init__(self, byte_budget: int = DEFAULT_BYTE_BUDGET):
        self._lock = named_rlock("dataplane.DataPlane._lock")
        #: key -> (device array, nbytes, tenant, label)
        self._entries: "OrderedDict[Any, Tuple[Any, int, Any, str]]" = \
            OrderedDict()
        self._bytes = 0
        self.byte_budget = int(byte_budget)
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.bytes_uploaded = 0       # miss uploads through put()/zeros()
        self.bytes_tiled = 0          # device-side tile materializations
        self.bytes_derived = 0        # device-computed derived buffers
        #: compiled tile programs keyed by (shape, dtype, reps, sharding)
        self._tile_programs: Dict[Any, Any] = {}
        #: multi-tenant accounting (serve/executor.py): per-tenant byte
        #: quotas and current charged usage.  Entries uploaded with a
        #: tenant are charged to it; a tenant over quota evicts its OWN
        #: LRU entries, and the global budget pass prefers victims that
        #: are unowned, the inserter's own, or over-quota — so one
        #: tenant's pressure cannot evict another's resident X/y while
        #: that tenant stays within its quota.
        self._tenant_quotas: Dict[Any, int] = {}
        self._tenant_bytes: Dict[Any, int] = {}

    # -- sizing ----------------------------------------------------------
    def configure(self, byte_budget: Optional[int]) -> "DataPlane":
        """Set the byte budget (evicting LRU entries if it shrank);
        ``None`` keeps the current budget."""
        if byte_budget is None:
            return self
        with self._lock:
            self.byte_budget = int(byte_budget)
            self._evict_over_budget()
        return self

    def _uncharge(self, tenant, nbytes: int) -> None:
        """Drop ``nbytes`` from a tenant's charged usage; usage
        reaching zero removes the accounting row.  (Callers hold the
        reentrant plane lock; taken again for standalone safety.)"""
        if tenant is None:
            return
        with self._lock:
            left = self._tenant_bytes.get(tenant, 0) - int(nbytes)
            if left > 0:
                self._tenant_bytes[tenant] = left
            else:
                self._tenant_bytes.pop(tenant, None)

    def _pop_entry(self, key) -> None:
        with self._lock:
            _, nbytes, tenant, _ = self._entries.pop(key)
            self._bytes -= nbytes
            self._uncharge(tenant, nbytes)
            self.evictions += 1

    def _over_quota(self, tenant) -> bool:
        quota = self._tenant_quotas.get(tenant)
        return bool(quota) and self._tenant_bytes.get(tenant, 0) > quota

    def _evict_over_budget(self, keep: Any = None,
                           inserting: Any = None) -> None:
        # every caller already holds the (reentrant) plane lock; taking
        # it again makes the helper safe on its own rather than by
        # call-site convention
        with self._lock:
            while self._bytes > self.byte_budget and len(self._entries) > 1:
                # tenant isolation: prefer victims that are unowned,
                # the inserter's own, or belong to an over-quota
                # tenant; a tenant within its quota is only evicted by
                # global pressure when no such victim exists (e.g. the
                # quotas were configured to exceed the plane budget)
                key = None
                for k, (_, _, t, _lb) in self._entries.items():
                    if k == keep:
                        continue
                    if t is None or t == inserting or self._over_quota(t):
                        key = k
                        break
                if key is None:
                    key = next(iter(self._entries))
                if key == keep:
                    # never evict the entry being returned; rotate it to
                    # the MRU end and take the next-oldest instead
                    self._entries.move_to_end(key)
                    key = next(iter(self._entries))
                    if key == keep:
                        break
                self._pop_entry(key)
        # a single oversized entry may exceed the budget on its own; it
        # stays (dropping it would force a re-upload every search) and
        # becomes the next LRU victim

    # -- residency -------------------------------------------------------
    def _get(self, key):
        with self._lock:
            hit = self._entries.get(key)
            if hit is not None:
                self._entries.move_to_end(key)
                self.hits += 1
                return hit[0]
            return None

    def _insert(self, key, value, nbytes: int, tenant: Any = None,
                label: str = ""):
        with self._lock:
            if key in self._entries:
                return
            # per-tenant quota: a tenant exceeding its own quota evicts
            # its OWN least-recently-used residents first — other
            # tenants' entries are untouchable here by construction
            quota = self._tenant_quotas.get(tenant)
            if tenant is not None and quota:
                while self._tenant_bytes.get(tenant, 0) + int(nbytes) \
                        > quota:
                    victim = next(
                        (k for k, (_, _, t, _lb) in self._entries.items()
                         if t == tenant), None)
                    if victim is None:
                        break
                    self._pop_entry(victim)
            self._entries[key] = (value, int(nbytes), tenant, label)
            self._bytes += int(nbytes)
            if tenant is not None:
                self._tenant_bytes[tenant] = \
                    self._tenant_bytes.get(tenant, 0) + int(nbytes)
            self._evict_over_budget(keep=key, inserting=tenant)

    # -- multi-tenant quotas ---------------------------------------------
    def set_tenant_quota(self, tenant, nbytes: int) -> None:
        """Register (or update) a tenant's resident byte quota.  New
        inserts charged to the tenant evict its own LRU entries beyond
        it; 0/None removes the quota (usage accounting remains)."""
        with self._lock:
            if nbytes:
                self._tenant_quotas[tenant] = int(nbytes)
            else:
                self._tenant_quotas.pop(tenant, None)

    def tenant_usage(self, tenant) -> int:
        """Bytes currently resident and charged to ``tenant``."""
        with self._lock:
            return self._tenant_bytes.get(tenant, 0)

    def tenant_usage_all(self) -> Dict[Any, int]:
        """Resident bytes charged per tenant (the fleet endpoint's
        per-tenant residency gauge)."""
        with self._lock:
            return dict(self._tenant_bytes)

    def release_tenant(self, tenant) -> int:
        """Release a tenant's plane charge (a cancelled or finished
        tenant's last search): its entries become unowned — first in
        line for LRU eviction, but still servable as hits while they
        survive — its usage resets to zero and its quota is dropped.
        Returns the byte count released."""
        with self._lock:
            released = 0
            for k in list(self._entries):
                value, nbytes, t, label = self._entries[k]
                if t == tenant:
                    self._entries[k] = (value, nbytes, None, label)
                    self._entries.move_to_end(k, last=False)
                    released += nbytes
            self._tenant_bytes.pop(tenant, None)
            self._tenant_quotas.pop(tenant, None)
            return released

    def demote(self, label_prefix: str, tenant) -> int:
        """Un-charge a tenant's entries whose label starts with
        ``label_prefix``: they become unowned, stop counting against
        the tenant's quota, and rotate to the LRU front — still
        servable as hits while they survive, but first in line for
        eviction.  The successive-halving rung barrier
        (search/halving.py) calls this with the previous rung's
        namespace (``"mask.r0."``) so a tenant's data-plane charge
        shrinks as rungs retire candidates: that rung's subsampled
        fold masks and wide tiled masks are exactly the buffers the
        surviving (narrower) rungs no longer need — and the scoped
        prefix can never touch a sibling search's live masks under
        the same tenant.  Returns the byte count demoted."""
        with self._lock:
            released = 0
            for k in list(self._entries):
                value, nbytes, t, label = self._entries[k]
                if t == tenant and label.startswith(label_prefix):
                    self._entries[k] = (value, nbytes, None, label)
                    self._entries.move_to_end(k, last=False)
                    self._uncharge(tenant, nbytes)
                    released += nbytes
            return released

    def put(self, arr: np.ndarray, sharding, label: str = "array",
            tenant: Any = None):
        """The cached ``device_put``: returns the resident device array
        for this (content, sharding), uploading at most once while the
        entry survives the budget.

        The whole miss path runs under the plane lock: two threads
        racing on the same key (stage thread vs a supervisor recovery
        relaunch) must not both upload — transfers serialize on the
        host->device stream anyway, and a double upload would inflate
        the ``bytes_uploaded`` counter the warm-search acceptance
        asserts to be zero."""
        key = ("host", fingerprint(arr), _sharding_key(sharding))
        with self._lock:
            cached = self._get(key)
            if cached is not None:
                return cached
            self.misses += 1
            self.bytes_uploaded += int(arr.nbytes)
            dev = upload(arr, sharding, label=label)
            self._insert(key, dev, arr.nbytes, tenant=tenant,
                         label=label)
            return dev

    def zeros(self, n: int, dtype, sharding, tenant: Any = None):
        """Cached all-zero launch operand (the all-static group's
        ``_pad`` axis definition) — uploaded once per (n, dtype,
        sharding), never per launch."""
        host = np.zeros(int(n), dtype=dtype)
        return self.put(host, sharding, label="zeros", tenant=tenant)

    def tiled(self, base: np.ndarray, base_dev, reps: int, out_sharding,
              label: str = "mask.tiled", fp: Optional[str] = None,
              tenant: Any = None):
        """Device-tiled ``(reps * rows, cols)`` view of ``base`` — the
        on-device replacement for host ``np.tile`` + upload.

        ``base_dev`` is the already-resident base (e.g. the fold masks'
        replicated upload); the tile itself is a tiny compiled
        broadcast whose RESULT is cached per (content, reps, sharding),
        so a width revisited by any later group, OOM relaunch or search
        costs one cache lookup and zero transfer.  Pass ``fp`` (a
        :func:`fingerprint` of ``base``) to skip re-hashing an array
        the caller already fingerprinted — hot-path callers memoize it
        once per search."""
        fp = fp or fingerprint(base)
        key = ("tile", fp, int(reps), _sharding_key(out_sharding))
        with self._lock:
            cached = self._get(key)
            if cached is not None:
                return cached
            self.misses += 1
            prog_key = (base.shape, str(base.dtype), int(reps),
                        _sharding_key(out_sharding))
            tile_fn = self._tile_programs.get(prog_key)
            if tile_fn is None:
                tile_fn = jax.jit(
                    lambda m, _r=int(reps): jnp.tile(m, (_r, 1)),
                    out_shardings=out_sharding)
                self._tile_programs[prog_key] = tile_fn
            nbytes = int(base.nbytes) * int(reps)
            with get_tracer().span("dataplane.tile", bytes=nbytes,
                                   reps=int(reps), label=label):
                dev = tile_fn(base_dev)
            self.bytes_tiled += nbytes
            self._insert(key, dev, nbytes, tenant=tenant, label=label)
            return dev

    def derived(self, key_parts: Tuple, maker, nbytes: int,
                label: str = "derived", tenant: Any = None):
        """Cached DEVICE-COMPUTED buffer — the resident home of arrays
        that never cross host->device (e.g. the shared-prefix
        scheduler's per-fold transformed design matrices).  Returns
        ``(device_array, hit)``; ``maker()`` runs at most once while
        the entry survives the budget and its result is charged
        ``nbytes`` against the tenant's quota like any upload.

        ``key_parts`` IS the provenance: callers key on the content
        digests of every input the computation consumed (prefix-config
        digest, source-X fingerprint, fold-mask fingerprint, sharding)
        so a mutated source yields a fresh key — invalidation by
        construction, same contract as :meth:`put` (entries are never
        revalidated on hit).  The whole miss path runs under the plane
        lock so two searches racing on one digest compute it once."""
        key = ("derived",) + tuple(key_parts)
        # equal keys must mean equal bytes: one key observed with two
        # different nbytes is content drift the digests failed to
        # capture — surfaced as a key collision under SST_KEYCHECK=1
        _keycheck.note("dataplane", key,
                       fields={"nbytes": int(nbytes)}, detail=label)
        with self._lock:
            cached = self._get(key)
            if cached is not None:
                return cached, True
            self.misses += 1
            nbytes = int(nbytes)
            with get_tracer().span("dataplane.derive", bytes=nbytes,
                                   label=label):
                dev = maker()
            self.bytes_derived += nbytes
            self._insert(key, dev, nbytes, tenant=tenant, label=label)
            return dev, False

    # -- introspection ---------------------------------------------------
    @property
    def n_entries(self) -> int:
        with self._lock:
            return len(self._entries)

    @property
    def bytes_in_cache(self) -> int:
        with self._lock:
            return self._bytes

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "hits": self.hits, "misses": self.misses,
                "evictions": self.evictions,
                "bytes_uploaded": self.bytes_uploaded,
                "bytes_tiled": self.bytes_tiled,
                "n_entries": len(self._entries),
                "bytes_in_cache": self._bytes,
                "budget_bytes": self.byte_budget,
            }

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._bytes = 0
            self._tile_programs.clear()
            self._tenant_bytes.clear()
            self._tenant_quotas.clear()


_PLANE: Optional[DataPlane] = None
_PLANE_LOCK = named_lock("dataplane._PLANE_LOCK")


def get_dataplane() -> DataPlane:
    """The process-global plane (created on first use)."""
    global _PLANE
    with _PLANE_LOCK:
        if _PLANE is None:
            _PLANE = DataPlane()
        return _PLANE


def plane_for(config) -> Optional[DataPlane]:
    """The plane a search should use under ``config``, budget applied —
    or ``None`` when ``TpuConfig(dataplane_bytes=0)`` disabled it (the
    legacy per-search ``device_put`` escape hatch)."""
    budget = getattr(config, "dataplane_bytes", DEFAULT_BYTE_BUDGET)
    if not budget or budget <= 0:
        return None
    return get_dataplane().configure(int(budget))


def snapshot_counters(plane: Optional[DataPlane]) -> Dict[str, int]:
    """Counter snapshot for per-search deltas (``search_report
    ["dataplane"]``)."""
    snap = {"total_bytes": bytes_uploaded()}
    if plane is not None:
        s = plane.stats()
        snap.update({k: s[k] for k in (
            "hits", "misses", "evictions", "bytes_uploaded",
            "bytes_tiled")})
    return snap


def report_block(plane: Optional[DataPlane], before: Dict[str, int],
                 mask_tiling: str = "n/a") -> Dict[str, Any]:
    """The rendered ``search_report["dataplane"]`` block (schema pinned
    in ``obs.metrics.DATAPLANE_BLOCK_SCHEMA``): this search's cache
    traffic plus the plane's end-of-search state."""
    total_delta = bytes_uploaded() - before.get("total_bytes", 0)
    if plane is None:
        return {"enabled": False, "hits": 0, "misses": 0, "evictions": 0,
                "bytes_uploaded": 0, "bytes_tiled": 0,
                "bytes_staged": total_delta, "n_entries": 0,
                "bytes_in_cache": 0, "budget_bytes": 0,
                "mask_tiling": mask_tiling}
    s = plane.stats()
    cacheable = s["bytes_uploaded"] - before.get("bytes_uploaded", 0)
    return {
        "enabled": True,
        "hits": s["hits"] - before.get("hits", 0),
        "misses": s["misses"] - before.get("misses", 0),
        "evictions": s["evictions"] - before.get("evictions", 0),
        "bytes_uploaded": cacheable,
        "bytes_tiled": s["bytes_tiled"] - before.get("bytes_tiled", 0),
        "bytes_staged": max(0, total_delta - cacheable),
        "n_entries": s["n_entries"],
        "bytes_in_cache": s["bytes_in_cache"],
        "budget_bytes": s["budget_bytes"],
        "mask_tiling": mask_tiling,
    }


#: does jax.device_put COPY the host buffer (True) or may it alias it
#: (False)?  On device backends (TPU/GPU — the perf target) host and
#: device are distinct memory spaces, so the h2d transfer is the last
#: read of the host buffer and reuse-after-transfer is safe.  XLA:CPU
#: zero-copies aligned host arrays (observed: mutating the source after
#: a SHARDED device_put changes the device value), so the pending
#: launch reads the host memory at execute time — no host-side wait can
#: bound that, and the ring must not reuse buffers there.
_DEVICE_PUT_COPIES: Optional[bool] = None


def _device_put_copies() -> bool:
    global _DEVICE_PUT_COPIES
    if _DEVICE_PUT_COPIES is None:
        _DEVICE_PUT_COPIES = jax.default_backend() != "cpu"
    return _DEVICE_PUT_COPIES


class StagingRing:
    """Reusable host buffers for per-chunk dynamic-param staging — the
    double-buffer behind ``TpuConfig(donate_chunk_buffers=True)``.

    ``pad_chunk`` writes each chunk into a ring slot instead of a fresh
    allocation, so the stage thread stops allocating at steady state.
    A slot remembers the device array its last contents fed and blocks
    on its transfer before handing the buffer out again — sufficient on
    copying backends (the transfer is the last read of the host
    buffer), and the block also makes supervisor retries that consume
    extra slots harmless.  On backends where ``device_put`` may ALIAS
    host memory (XLA:CPU) the pending launch reads the buffer at
    execute time, so reuse is never provably safe: the ring detects
    that once (:func:`_device_put_copies`) and degrades to fresh
    allocations — identical results, no double-buffer win.
    """

    class _Slot:
        __slots__ = ("array", "consumer")

        def __init__(self, array: np.ndarray):
            self.array = array
            self.consumer = None

        def commit(self, dev) -> None:
            """Remember the device array this slot's contents fed."""
            self.consumer = dev

    def __init__(self, slots: int = 3):
        self._n = max(2, int(slots))
        self._lock = named_lock("dataplane.StagingRing._lock")
        self._rings: Dict[Any, Dict[str, Any]] = {}

    def slot(self, key, shape: Tuple[int, ...], dtype) -> "_Slot":
        """The next reusable buffer for ``key`` (shape/dtype bound into
        the ring identity, so an OOM-bisected width gets its own
        ring)."""
        if not _device_put_copies():
            # aliasing backend: a fresh buffer per chunk (see class
            # docstring) — correctness over the allocation win
            return StagingRing._Slot(np.empty(shape, dtype))
        rkey = (key, tuple(shape), str(np.dtype(dtype)))
        with self._lock:
            ring = self._rings.get(rkey)
            if ring is None:
                ring = {"i": 0, "slots": []}
                self._rings[rkey] = ring
            if len(ring["slots"]) < self._n:
                slot = StagingRing._Slot(np.empty(shape, dtype))
                ring["slots"].append(slot)
            else:
                slot = ring["slots"][ring["i"] % self._n]
            ring["i"] += 1
        if slot.consumer is not None:
            try:
                jax.block_until_ready(slot.consumer)
            # a donated-and-deleted consumer raises on the readiness
            # probe, which PROVES the buffer was consumed — exactly the
            # condition the wait establishes, so the error is the
            # success case here, not a hidden failure
            # sstlint: disable=swallowed-exception
            except Exception:   # donated-and-deleted: consumed for sure
                pass
            slot.consumer = None
        return slot
