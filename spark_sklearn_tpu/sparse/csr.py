"""Sparse row-matrix container — the UDT replacement.

The reference's CSRVectorUDT (reference: python/spark_sklearn/udt.py) teaches
Spark DataFrames to carry scipy `csr_matrix` rows so sparse features reach
sklearn without densifying.  There is no Spark SQL engine here; the
equivalent capability is a typed container that moves CSR data between
scipy, numpy (pandas cells), and JAX:

  - `CSRMatrix.from_scipy` / `.to_scipy` — lossless scipy round trip
  - `.to_dense()` — jnp dense array (the TPU compute format; XLA has no
    first-class CSR, and for MXU-sized problems dense is the fast path)
  - `.to_bcoo()` — `jax.experimental.sparse.BCOO` for genuinely sparse
    compute (canonical: duplicate-free, row-major sorted indices)
  - `.serialize()` / `CSRMatrix.deserialize` — the UDT contract (sqlType/
    serialize/deserialize) as a plain tuple-of-arrays schema

`SparseOperand` is the host-side staging form of a BCOO operand: the
search engine uploads its `values`/`indices` components separately (each
nnz-proportional) and reassembles the device BCOO, so upload accounting,
dataplane fingerprints and the ledger all price nnz — never n x d.
"""

from __future__ import annotations

from typing import Tuple

import numpy as np

#: first index value that no longer fits an int32 — matrices at or past
#: this size (any dimension, or nnz) carry int64 indices end to end
_INT32_MAX = np.iinfo(np.int32).max


def index_dtype(*extents) -> np.dtype:
    """int32 when every extent (dims, nnz) fits, int64 past 2**31-1 —
    silent int32 truncation on a huge-axis matrix would alias rows."""
    if any(int(e) > _INT32_MAX for e in extents):
        return np.dtype(np.int64)
    return np.dtype(np.int32)


class CSRMatrix:
    """Compressed sparse row matrix: (data, indices, indptr, shape)."""

    def __init__(self, data, indices, indptr, shape: Tuple[int, int]):
        self.data = np.asarray(data)
        shape = (int(shape[0]), int(shape[1]))
        # indices index columns (< shape[1]); indptr indexes into data
        # (<= nnz) — size each independently so a tiny-nnz matrix over a
        # huge axis keeps exactly the dtypes it needs
        self.indices = np.asarray(
            indices, dtype=index_dtype(shape[1], 0))
        self.indptr = np.asarray(
            indptr, dtype=index_dtype(len(self.data)))
        self.shape = shape

    # -- scipy bridge ----------------------------------------------------
    @classmethod
    def from_scipy(cls, m) -> "CSRMatrix":
        m = m.tocsr()
        return cls(m.data, m.indices, m.indptr, m.shape)

    def to_scipy(self):
        from scipy.sparse import csr_matrix
        return csr_matrix((self.data, self.indices, self.indptr),
                          shape=self.shape)

    # -- device bridges --------------------------------------------------
    def to_dense(self, dtype=np.float32):
        import jax.numpy as jnp
        if dtype == np.float32 and \
                self.indices.dtype == np.int32 and \
                self.indptr.dtype == np.int32:
            from spark_sklearn_tpu.utils.native import csr_to_dense
            return jnp.asarray(csr_to_dense(
                self.data, self.indices, self.indptr, self.shape))
        return jnp.asarray(self.to_scipy().toarray().astype(dtype))

    def to_bcoo(self, dtype=np.float32):
        from jax.experimental import sparse as jsparse
        op = SparseOperand.from_csr(self, dtype=dtype)
        return jsparse.BCOO(
            (op.values, op.indices), shape=op.shape,
            indices_sorted=True, unique_indices=True)

    # -- UDT-style serialization (reference: udt.py sqlType/serialize) ---
    def serialize(self):
        return (self.data, self.indices, self.indptr,
                np.asarray(self.shape, dtype=np.int64))

    @classmethod
    def deserialize(cls, datum) -> "CSRMatrix":
        data, indices, indptr, shape = datum
        return cls(data, indices, indptr, tuple(int(s) for s in shape))

    # -- conveniences ----------------------------------------------------
    @property
    def nnz(self) -> int:
        return int(len(self.data))

    @property
    def nbytes(self) -> int:
        """Component bytes (data + indices + indptr) — what footprint
        pricing and upload accounting should see, never n x d."""
        return int(self.data.nbytes + self.indices.nbytes
                   + self.indptr.nbytes)

    def __repr__(self):
        return (f"CSRMatrix(shape={self.shape}, nnz={self.nnz}, "
                f"dtype={self.data.dtype})")

    def __eq__(self, other):
        if not isinstance(other, CSRMatrix):
            return NotImplemented
        return (self.shape == other.shape
                and np.array_equal(self.data, other.data)
                and np.array_equal(self.indices, other.indices)
                and np.array_equal(self.indptr, other.indptr))


class SparseOperand:
    """Host-side staged form of one BCOO device operand.

    Carries the canonical COO components (`values` (nnz,), `indices`
    (nnz, 2)) the engine uploads separately — each transfer is
    nnz-proportional and individually fingerprinted by the data plane —
    plus the facts (`shape`, `nnz`) that enter program-store keys and
    checkpoint fingerprints as the sparse signature."""

    __slots__ = ("values", "indices", "shape")

    def __init__(self, values, indices, shape):
        self.values = np.ascontiguousarray(values)
        self.indices = np.ascontiguousarray(indices)
        self.shape = (int(shape[0]), int(shape[1]))

    @classmethod
    def from_csr(cls, m, dtype=np.float32) -> "SparseOperand":
        """Canonical (duplicate-free, row-major sorted) COO components
        from any CSR-like matrix (scipy sparse or CSRMatrix)."""
        if isinstance(m, CSRMatrix):
            m = m.to_scipy()
        m = m.tocsr().copy()
        # scipy canonical form: sums duplicates AND sorts each row's
        # column indices, so the row-major COO walk below emits sorted,
        # unique coordinates — the flags to_bcoo() then asserts
        m.sum_duplicates()
        coo = m.tocoo()
        idt = index_dtype(m.shape[0], m.shape[1], m.nnz)
        idx = np.stack([coo.row.astype(idt), coo.col.astype(idt)],
                       axis=1)
        return cls(coo.data.astype(dtype, copy=False), idx, m.shape)

    @property
    def nnz(self) -> int:
        return int(self.values.shape[0])

    @property
    def nbytes(self) -> int:
        return int(self.values.nbytes + self.indices.nbytes)

    def signature(self) -> tuple:
        """The sparse program signature: enough to distinguish two
        compiled programs whose dense shapes agree but whose sparse
        layouts differ (joins ProgramStore keys and checkpoint
        fingerprints)."""
        return ("bcoo", self.shape, self.nnz,
                str(self.values.dtype), str(self.indices.dtype))

    def to_bcoo(self, values=None, indices=None):
        """Assemble the device BCOO from already-uploaded components
        (or the host ones, for tests)."""
        from jax.experimental import sparse as jsparse
        return jsparse.BCOO(
            (self.values if values is None else values,
             self.indices if indices is None else indices),
            shape=self.shape, indices_sorted=True, unique_indices=True)


_BCOO_EXPORT_REGISTERED = False


def register_bcoo_export() -> bool:
    """Teach ``jax.export`` to serialize BCOO-carrying pytrees so the
    ProgramStore can persist sparse Tier-A programs (AOT prewarm).
    Idempotent; returns False when the running jax cannot register
    (old jax, or another module already claimed the name) — callers
    then simply skip the store for sparse programs."""
    global _BCOO_EXPORT_REGISTERED
    if _BCOO_EXPORT_REGISTERED:
        return True
    try:
        import json

        from jax import export as jexport
        from jax.experimental import sparse as jsparse

        def _ser(aux):
            d = dict(aux)
            d["shape"] = [int(s) for s in d["shape"]]
            return json.dumps(d, sort_keys=True).encode()

        def _de(b):
            d = json.loads(b.decode())
            d["shape"] = tuple(d["shape"])
            return d

        jexport.register_pytree_node_serialization(
            jsparse.BCOO,
            serialized_name="jax.experimental.sparse.BCOO",
            serialize_auxdata=_ser,
            deserialize_auxdata=_de)
    except ValueError:
        # already registered (e.g. a second engine in-process): that is
        # success for our purposes
        _BCOO_EXPORT_REGISTERED = True
        return True
    except (ImportError, AttributeError, TypeError):
        return False
    _BCOO_EXPORT_REGISTERED = True
    return True
