"""Sparse row-matrix container — the UDT replacement.

The reference's CSRVectorUDT (reference: python/spark_sklearn/udt.py) teaches
Spark DataFrames to carry scipy `csr_matrix` rows so sparse features reach
sklearn without densifying.  There is no Spark SQL engine here; the
equivalent capability is a typed container that moves CSR data between
scipy, numpy (pandas cells), and JAX:

  - `CSRMatrix.from_scipy` / `.to_scipy` — lossless scipy round trip
  - `.to_dense()` — jnp dense array (the TPU compute format; XLA has no
    first-class CSR, and for MXU-sized problems dense is the fast path)
  - `.to_bcoo()` — `jax.experimental.sparse.BCOO` for genuinely sparse
    compute
  - `.serialize()` / `CSRMatrix.deserialize` — the UDT contract (sqlType/
    serialize/deserialize) as a plain tuple-of-arrays schema
"""

from __future__ import annotations

from typing import Tuple

import numpy as np


class CSRMatrix:
    """Compressed sparse row matrix: (data, indices, indptr, shape)."""

    def __init__(self, data, indices, indptr, shape: Tuple[int, int]):
        self.data = np.asarray(data)
        self.indices = np.asarray(indices, dtype=np.int32)
        self.indptr = np.asarray(indptr, dtype=np.int32)
        self.shape = (int(shape[0]), int(shape[1]))

    # -- scipy bridge ----------------------------------------------------
    @classmethod
    def from_scipy(cls, m) -> "CSRMatrix":
        m = m.tocsr()
        return cls(m.data, m.indices, m.indptr, m.shape)

    def to_scipy(self):
        from scipy.sparse import csr_matrix
        return csr_matrix((self.data, self.indices, self.indptr),
                          shape=self.shape)

    # -- device bridges --------------------------------------------------
    def to_dense(self, dtype=np.float32):
        import jax.numpy as jnp
        if dtype == np.float32:
            from spark_sklearn_tpu.utils.native import csr_to_dense
            return jnp.asarray(csr_to_dense(
                self.data, self.indices, self.indptr, self.shape))
        return jnp.asarray(self.to_scipy().toarray().astype(dtype))

    def to_bcoo(self, dtype=np.float32):
        from jax.experimental import sparse as jsparse
        coo = self.to_scipy().tocoo()
        idx = np.stack([coo.row, coo.col], axis=1).astype(np.int32)
        return jsparse.BCOO(
            (coo.data.astype(dtype), idx), shape=self.shape)

    # -- UDT-style serialization (reference: udt.py sqlType/serialize) ---
    def serialize(self):
        return (self.data, self.indices, self.indptr,
                np.asarray(self.shape, dtype=np.int64))

    @classmethod
    def deserialize(cls, datum) -> "CSRMatrix":
        data, indices, indptr, shape = datum
        return cls(data, indices, indptr, tuple(int(s) for s in shape))

    # -- conveniences ----------------------------------------------------
    @property
    def nnz(self) -> int:
        return int(len(self.data))

    def __repr__(self):
        return (f"CSRMatrix(shape={self.shape}, nnz={self.nnz}, "
                f"dtype={self.data.dtype})")

    def __eq__(self, other):
        if not isinstance(other, CSRMatrix):
            return NotImplemented
        return (self.shape == other.shape
                and np.array_equal(self.data, other.data)
                and np.array_equal(self.indices, other.indices)
                and np.array_equal(self.indptr, other.indptr))
