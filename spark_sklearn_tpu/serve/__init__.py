"""Multi-tenant search serving — ``TpuSession.submit`` and the async
fair-share executor.

Public surface::

    session = createLocalTpuSession()
    fut_a = session.submit(search_a, X, y)        # tenant "default"
    fut_b = session.submit(search_b, X, y)        # interleaves with a
    search_a = fut_a.result()                     # fitted estimator
    fut_b.cancel()                                # drains, resumable

See :mod:`spark_sklearn_tpu.serve.executor` for the architecture
(deficit-round-robin fair share, admission control, tenant byte
quotas, cancellation) and the ``search_report["scheduler"]`` block.
"""

from spark_sklearn_tpu.serve.executor import (
    DEFAULT_TENANT,
    AdmissionError,
    SearchCancelledError,
    SearchExecutor,
    SearchFuture,
    SearchHandle,
    current_binding,
    report_block,
    resolve_fusion,
    resolve_fusion_max_width,
    resolve_fusion_window_ms,
    resolve_tenant,
    resolve_weight,
)
from spark_sklearn_tpu.serve.journal import (
    RecoveryDataMismatchError,
    RecoveryEntry,
    RecoveryReport,
    ServiceJournal,
    ServiceLeaseError,
    activate_service_journal,
    data_fingerprint,
    resolve_service_journal_dir,
)

__all__ = [
    "RecoveryDataMismatchError",
    "RecoveryEntry",
    "RecoveryReport",
    "ServiceJournal",
    "ServiceLeaseError",
    "activate_service_journal",
    "data_fingerprint",
    "resolve_service_journal_dir",
    "DEFAULT_TENANT",
    "AdmissionError",
    "SearchCancelledError",
    "SearchExecutor",
    "SearchFuture",
    "SearchHandle",
    "current_binding",
    "report_block",
    "resolve_fusion",
    "resolve_fusion_max_width",
    "resolve_fusion_window_ms",
    "resolve_tenant",
    "resolve_weight",
]
