"""Durable service journal + lease-fenced recovery — the crash-safe
service layer.

The per-search checkpoint journal (``utils/checkpoint.py``) already
makes one *search* resumable; this module makes the *service* itself
resumable.  :class:`ServiceJournal` is a write-ahead log in
``TpuConfig(service_journal_dir)`` / ``SST_SERVICE_JOURNAL_DIR``: the
executor appends one checksummed record per submission (tenant,
weight, family, compile-structure digest, X/y content fingerprints,
checkpoint-journal directory) and per state transition (admitted →
running → finished/cancelled/failed/shed), each line flushed + fsynced
before the submit/transition proceeds, so a SIGKILLed process leaves a
byte-exact account of every search the fleet owed an answer for.

On restart, :meth:`TpuSession.recover` scans the journal for
non-terminal entries and returns a :class:`RecoveryReport`; the caller
re-binds data and resubmits through the normal admission path, with
the journaled blake2b :func:`data_fingerprint` verified first — a
mismatch is a clean :class:`RecoveryDataMismatchError`, never a
silently-wrong resume.  Each recovered search then replays its own
per-search checkpoint journal, so recovered ``cv_results_`` are
bit-exact vs the uncrashed run.

**Lease fencing**: a heartbeat-stamped ``service-lease.json`` in the
journal directory names the live owner.  A second live process gets a
structured :class:`ServiceLeaseError` at session init; a stale lease
(owner dead, or its stamp older than ``service_lease_timeout_s`` /
``SST_SERVICE_LEASE_TIMEOUT_S``) is fenced and taken over, and the
unclean shutdown it implies dumps a crash-marker flight bundle
(``parallel/faults.crash_marker_context``) for the postmortem.

No journal directory configured is the exact no-op: zero writes, zero
reads, byte-identical reports and ``cv_results_``.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from spark_sklearn_tpu.obs.log import get_logger
from spark_sklearn_tpu.obs.trace import get_tracer
from spark_sklearn_tpu.utils.atomic import atomic_write, fsync_dir
from spark_sklearn_tpu.utils.journalspec import (SERVICE_JOURNAL_FORMAT,
                                                 SERVICE_RECORD_KINDS)
from spark_sklearn_tpu.utils.locks import named_lock

logger = get_logger(__name__)

__all__ = [
    "DEFAULT_LEASE_TIMEOUT_S",
    "SERVICE_JOURNAL_FORMAT",
    "TERMINAL_STATES",
    "RecoveryDataMismatchError",
    "RecoveryEntry",
    "RecoveryReport",
    "ServiceJournal",
    "ServiceLeaseError",
    "activate_service_journal",
    "data_fingerprint",
    "resolve_lease_timeout_s",
    "resolve_service_journal_dir",
    "submission_digest",
]

#: on-disk format version: declared (with the record-kind vocabulary)
#: in utils/journalspec.py, the one versioned registry of every
#: durable journal record kind; re-exported here for callers.  Bumping
#: it turns old journals into clean empty scans, never parse errors.
assert SERVICE_JOURNAL_FORMAT == 1, "bump requires a migration plan"

#: how stale the lease stamp may grow before a successor may fence a
#: still-registered (but silent) owner.
DEFAULT_LEASE_TIMEOUT_S = 30.0

#: journal states that owe the caller nothing on restart
#: ("recovered" marks an entry whose successor submission — linked by
#: ``recovered_from`` — carries the work from here on).
TERMINAL_STATES = frozenset({"finished", "cancelled", "failed", "shed",
                             "recovered"})

#: executor handle states -> journal transition vocabulary.
JOURNAL_STATE_BY_HANDLE_STATE = {"done": "finished"}

JOURNAL_NAME = "service-journal.jsonl"
LEASE_NAME = "service-lease.json"

#: the TpuConfig knobs worth replaying to a recovered submission —
#: scalars only, so the journaled summary is always JSON-able.
_CONFIG_SUMMARY_FIELDS = (
    "tenant", "tenant_weight", "checkpoint_dir", "search_deadline_s",
    "partial_results", "admission_mode", "data_mode", "chunk_loop",
    "max_tasks_per_batch",
)


class ServiceLeaseError(RuntimeError):
    """The journal directory is owned by another LIVE process.

    Machine-readable: ``owner_pid`` / ``owner`` / ``age_s`` /
    ``timeout_s`` name the conflicting lease, so an operator (or a
    supervisor loop) can decide between waiting the timeout out and
    killing the owner."""

    def __init__(self, message: str, *, path: str = "",
                 owner: str = "", owner_pid: int = 0,
                 age_s: float = 0.0, timeout_s: float = 0.0):
        super().__init__(message)
        self.path = path
        self.owner = owner
        self.owner_pid = int(owner_pid)
        self.age_s = float(age_s)
        self.timeout_s = float(timeout_s)


class RecoveryDataMismatchError(ValueError):
    """Re-bound data does not match the journaled fingerprint.

    Raised by :meth:`TpuSession.resubmit` BEFORE any admission or
    device work: resuming a checkpoint journal against different data
    would silently blend two datasets' partial results."""

    def __init__(self, message: str, *, handle: str = "",
                 expected: str = "", got: str = ""):
        super().__init__(message)
        self.handle = handle
        self.expected = expected
        self.got = got


def data_fingerprint(X, y=None) -> str:
    """blake2b content fingerprint of a submission's data binding.

    Bounded (first MiB of each buffer) + shape + dtype, like the
    checkpoint key's sha256 fingerprint but keyed for the SERVICE
    journal: recovery compares this against the journaled value before
    any resume.  Sparse (CSR-like) X hashes its component arrays, so
    the fingerprint never densifies."""
    h = hashlib.blake2b(digest_size=16)
    for part in (X, y):
        if part is None:
            h.update(b"<none>")
            continue
        if hasattr(part, "indptr") and hasattr(part, "indices"):
            for comp in (part.data, part.indices, part.indptr):
                arr = np.ascontiguousarray(comp)
                h.update(arr.tobytes()[:1 << 20])
            h.update(str(part.shape).encode())
            h.update(str(getattr(part, "dtype", "")).encode())
            continue
        arr = np.ascontiguousarray(np.asarray(part))
        h.update(arr.tobytes()[:1 << 20])
        h.update(str(arr.shape).encode())
        h.update(str(arr.dtype).encode())
    return h.hexdigest()


def submission_digest(search, X, y=None) -> str:
    """Stable structural digest of a submission (family, grid, cv,
    data shape/dtype) — display identity for journal/doctor tooling,
    sharing the RunLog's blake2b spelling."""
    from spark_sklearn_tpu.obs.runlog import structure_digest
    est = getattr(search, "estimator", None)
    family = type(est).__name__ if est is not None \
        else type(search).__name__
    grid = getattr(search, "param_grid", None)
    if not isinstance(grid, dict):
        grid = getattr(search, "param_distributions", None)
    grid_repr = repr(sorted(grid.items())) if isinstance(grid, dict) \
        else ""
    return structure_digest(
        family, grid_repr, repr(getattr(search, "cv", None)),
        tuple(getattr(X, "shape", ()) or ()),
        str(getattr(X, "dtype", "")),
        tuple(getattr(y, "shape", ()) or ()))


def resolve_service_journal_dir(config) -> Optional[str]:
    """``TpuConfig.service_journal_dir``, else
    ``SST_SERVICE_JOURNAL_DIR``, else None (journal off)."""
    d = getattr(config, "service_journal_dir", None) \
        if config is not None else None
    if not d:
        d = os.environ.get("SST_SERVICE_JOURNAL_DIR", "").strip() or None
    return d


def resolve_lease_timeout_s(config) -> float:
    """``TpuConfig.service_lease_timeout_s``, else
    ``SST_SERVICE_LEASE_TIMEOUT_S``, else the 30s default."""
    t = getattr(config, "service_lease_timeout_s", None) \
        if config is not None else None
    if t is None:
        env = os.environ.get("SST_SERVICE_LEASE_TIMEOUT_S", "").strip()
        if env:
            # a typo'd timeout fails loudly at activation, not at the
            # first fencing decision
            t = float(env)
    return DEFAULT_LEASE_TIMEOUT_S if t is None else float(t)


def _config_summary(config) -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    for name in _CONFIG_SUMMARY_FIELDS:
        val = getattr(config, name, None) if config is not None else None
        if val is not None:
            out[name] = val if isinstance(
                val, (str, int, float, bool)) else str(val)
    return out


@dataclasses.dataclass(frozen=True)
class RecoveryEntry:
    """One non-terminal journaled search a restarted session owes."""

    handle: str
    tenant: str
    weight: float
    family: str
    structure_digest: str
    data_fingerprint: str
    checkpoint_dir: str
    state: str
    config: Dict[str, Any]

    def as_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


@dataclasses.dataclass(frozen=True)
class RecoveryReport:
    """What :meth:`TpuSession.recover` found in the journal."""

    entries: Tuple[RecoveryEntry, ...] = ()
    taken_over: bool = False
    unclean: bool = False
    journal_dir: str = ""

    @property
    def n_nonterminal(self) -> int:
        return len(self.entries)

    def as_dict(self) -> Dict[str, Any]:
        return {
            "n_nonterminal": self.n_nonterminal,
            "taken_over": self.taken_over,
            "unclean": self.unclean,
            "journal_dir": self.journal_dir,
            "entries": [e.as_dict() for e in self.entries],
        }


class ServiceJournal:
    """Append-only checksummed WAL of the service's submissions.

    One JSON line per event, each wrapped in a RunLog-style checksummed
    document (format key + payload sha256) and flushed + fsynced before
    the caller proceeds — a torn tail line from a crash is skipped at
    scan time, never a parse error.  Thread-safe: the executor's
    dispatch, worker and shutdown paths all append."""

    def __init__(self, directory: str,
                 lease_timeout_s: float = DEFAULT_LEASE_TIMEOUT_S,
                 owner: str = ""):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self.path = os.path.join(self.directory, JOURNAL_NAME)
        self.lease_path = os.path.join(self.directory, LEASE_NAME)
        self.lease_timeout_s = float(lease_timeout_s)
        self.owner = owner or f"pid-{os.getpid()}"
        self.lease_info: Dict[str, Any] = {}
        self._lock = named_lock("journal.ServiceJournal._lock")
        self._seq = 0
        self._counts = {"appends": 0, "corrupt": 0,
                        "lease_takeovers": 0, "lease_conflicts": 0,
                        "unclean_shutdowns": 0}
        self._held = False
        self._hb_stop = threading.Event()
        self._hb_thread: Optional[threading.Thread] = None

    # -- record IO ---------------------------------------------------------
    def append(self, kind: str, record: Dict[str, Any]) -> bool:
        """Durably append one checksummed record.  Returns False on an
        I/O failure — journaling hardens the service, it must never
        fail a submit.  ``kind`` must be declared in the journalspec
        registry: an undeclared kind is a programming error (format
        drift a future reader has no decoder for), not an I/O hazard,
        so it raises."""
        if str(kind) not in SERVICE_RECORD_KINDS:
            raise ValueError(
                f"undeclared service-journal record kind {kind!r}: "
                "declare it (with a decoder) in "
                "spark_sklearn_tpu/utils/journalspec.py")
        payload = json.dumps(record, sort_keys=True, default=str)
        doc = {
            "service_journal_format": SERVICE_JOURNAL_FORMAT,
            "kind": str(kind),
            "payload_sha256": hashlib.sha256(
                payload.encode()).hexdigest(),
            "record": json.loads(payload),
        }
        line = json.dumps(doc) + "\n"
        with get_tracer().span("journal.append", kind=str(kind)):
            with self._lock:
                self._seq += 1
                self._counts["appends"] += 1
                try:
                    with open(self.path, "a") as f:
                        f.write(line)
                        f.flush()
                        os.fsync(f.fileno())
                except OSError as exc:
                    logger.warning(
                        "service journal: append failed (%r)", exc)
                    return False
        return True

    def qualify(self, handle: str) -> str:
        """Journal-unique spelling of an executor handle id.

        Executor handles (``tenant/sN``) restart from s1 in every
        process, so a recovered journal would alias old and new
        submissions; the pid prefix keeps each process's entries
        distinct across restarts."""
        return f"p{os.getpid()}/{handle}"

    def record_submission(self, handle: str, *, tenant: str,
                          weight: float, family: str,
                          structure_digest: str,
                          data_fingerprint: str,
                          checkpoint_dir: str = "",
                          config=None,
                          recovered_from: str = "") -> bool:
        rec = {
            "handle": self.qualify(str(handle)),
            "tenant": str(tenant),
            "weight": float(weight),
            "family": str(family),
            "structure_digest": str(structure_digest),
            "data_fingerprint": str(data_fingerprint),
            "checkpoint_dir": str(checkpoint_dir or ""),
            "config": _config_summary(config),
            "state": "admitted",
            "ts_unix_s": time.time(),
        }
        if recovered_from:
            rec["recovered_from"] = str(recovered_from)
        return self.append("submitted", rec)

    def record_transition(self, handle: str, state: str,
                          qualify: bool = True, **extra: Any) -> bool:
        """One state-transition record.  ``qualify=False`` addresses a
        handle exactly as journaled (e.g. a PREVIOUS process's entry
        being marked ``recovered`` by its successor)."""
        hid = self.qualify(str(handle)) if qualify else str(handle)
        rec = {"handle": hid, "state": str(state),
               "ts_unix_s": time.time(), **extra}
        return self.append("state", rec)

    def entries(self) -> List[Dict[str, Any]]:
        """Every verified record document, in append order.  Corrupt
        lines (torn tail, bit rot, undecodable bytes) are counted and
        skipped."""
        out: List[Dict[str, Any]] = []
        try:
            if not (os.path.exists(self.path)
                    and os.path.getsize(self.path) > 0):
                return out
        except OSError:
            return out
        corrupt = 0
        # errors="replace": a crash can leave undecodable bytes in the
        # tail line; the mangled line then fails the checksum and is
        # skipped like any other torn record
        with open(self.path, errors="replace") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    doc = json.loads(line)
                except json.JSONDecodeError:
                    corrupt += 1
                    continue
                if doc.get("service_journal_format") != \
                        SERVICE_JOURNAL_FORMAT:
                    corrupt += 1
                    continue
                payload = json.dumps(doc.get("record", {}),
                                     sort_keys=True, default=str)
                if hashlib.sha256(payload.encode()).hexdigest() != \
                        doc.get("payload_sha256"):
                    corrupt += 1
                    continue
                out.append(doc)
        if corrupt:
            with self._lock:
                self._counts["corrupt"] += corrupt
        return out

    def nonterminal(self) -> Dict[str, Dict[str, Any]]:
        """handle -> merged submission record (latest state folded in)
        for every journaled search whose last transition is not
        terminal — exactly what a warm restart owes the caller."""
        subs: Dict[str, Dict[str, Any]] = {}
        states: Dict[str, str] = {}
        for doc in self.entries():
            rec = doc.get("record") or {}
            handle = str(rec.get("handle", "") or "")
            if not handle:
                continue
            if doc.get("kind") == "submitted":
                subs[handle] = rec
                # the WAL append and a fast worker's first transition
                # race on file order: a transition always outranks the
                # submission's initial state, whichever landed first
                states.setdefault(handle,
                                  str(rec.get("state", "admitted")))
            elif doc.get("kind") == "state":
                states[handle] = str(rec.get("state", ""))
        return {h: {**sub, "state": states.get(h, "")}
                for h, sub in subs.items()
                if states.get(h) not in TERMINAL_STATES}

    # -- lease fencing -----------------------------------------------------
    def _read_lease(self) -> Optional[Dict[str, Any]]:
        try:
            with open(self.lease_path) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    @staticmethod
    def _pid_alive(pid: int) -> bool:
        if pid <= 0:
            return False
        try:
            os.kill(pid, 0)
        except ProcessLookupError:
            return False
        except PermissionError:
            return True      # alive, owned by someone else
        except OSError:
            return False
        return True

    def _stamp_lease(self) -> None:
        doc = {"pid": os.getpid(), "owner": self.owner,
               "ts_unix_s": time.time(),
               "timeout_s": self.lease_timeout_s}
        atomic_write(self.lease_path, json.dumps(doc).encode())

    def acquire_lease(self) -> Dict[str, Any]:
        """Take (or fence) the journal directory's lease.

        A LIVE owner with a fresh stamp raises
        :class:`ServiceLeaseError`; a dead owner, or one whose stamp
        aged past ``lease_timeout_s``, is fenced and taken over.  A
        leftover lease is the unclean-shutdown marker: the previous
        owner died without :meth:`release_lease`.  Starts the
        heartbeat re-stamp thread on success."""
        prev = self._read_lease()
        now = time.time()
        taken_over = False
        if prev is not None and int(prev.get("pid", 0)) != os.getpid():
            pid = int(prev.get("pid", 0))
            age = max(0.0, now - float(prev.get("ts_unix_s", 0.0)
                                       or 0.0))
            if self._pid_alive(pid) and age < self.lease_timeout_s:
                with self._lock:
                    self._counts["lease_conflicts"] += 1
                raise ServiceLeaseError(
                    f"service journal {self.directory!r} is leased by "
                    f"live process {pid} ({prev.get('owner', '?')}, "
                    f"stamped {age:.1f}s ago, timeout "
                    f"{self.lease_timeout_s:g}s)",
                    path=self.lease_path,
                    owner=str(prev.get("owner", "")), owner_pid=pid,
                    age_s=age, timeout_s=self.lease_timeout_s)
            taken_over = True
            with self._lock:
                self._counts["lease_takeovers"] += 1
                self._counts["unclean_shutdowns"] += 1
            logger.warning(
                "service journal: fencing stale lease of pid %d "
                "(%s, stamped %.1fs ago)", pid,
                prev.get("owner", "?"), age)
        self._stamp_lease()
        self._held = True
        self._start_heartbeat()
        if taken_over:
            self.append("lease", {
                "event": "fenced", "owner": self.owner,
                "previous_pid": int(prev.get("pid", 0)),
                "previous_owner": str(prev.get("owner", "")),
                "stale_age_s": round(age, 3),
                "ts_unix_s": now})
        self.lease_info = {"taken_over": taken_over,
                           "unclean": taken_over, "previous": prev}
        return self.lease_info

    def _start_heartbeat(self) -> None:
        period = max(0.05, self.lease_timeout_s / 3.0)
        self._hb_stop.clear()
        t = threading.Thread(target=self._hb_loop, args=(period,),
                             name="sst-journal-lease", daemon=True)
        self._hb_thread = t
        t.start()

    def _hb_loop(self, period: float) -> None:
        while not self._hb_stop.wait(period):
            try:
                self._stamp_lease()
            except OSError as exc:
                # the next stamp retries; losing one heartbeat must
                # not kill the service the lease protects
                logger.debug("service lease re-stamp failed: %r", exc)

    def release_lease(self, clean: bool = True) -> None:
        """Stop the heartbeat and drop the lease.  ``clean=True``
        journals a shutdown record first, so the next startup knows
        this owner exited deliberately."""
        self._hb_stop.set()
        t = self._hb_thread
        if t is not None:
            t.join(timeout=5.0)
            self._hb_thread = None
        if not self._held:
            return
        if clean:
            self.append("shutdown", {"owner": self.owner,
                                     "clean": True,
                                     "ts_unix_s": time.time()})
        try:
            os.remove(self.lease_path)
            fsync_dir(self.directory)
        except OSError:
            pass
        self._held = False

    # -- stats -------------------------------------------------------------
    def counts(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._counts)

    def disk_stats(self) -> Dict[str, int]:
        try:
            size = os.path.getsize(self.path) \
                if os.path.exists(self.path) else 0
        except OSError:
            size = 0
        return {"journal_bytes": int(size)}


def activate_service_journal(config=None,
                             owner: str = "") -> Optional[ServiceJournal]:
    """The service journal a session should use under ``config`` — or
    None when no directory is configured (the exact no-op).  Acquires
    the lease (raising :class:`ServiceLeaseError` on a live owner) and
    leaves the takeover verdict in ``journal.lease_info``."""
    directory = resolve_service_journal_dir(config)
    if not directory:
        return None
    journal = ServiceJournal(
        directory, lease_timeout_s=resolve_lease_timeout_s(config),
        owner=owner)
    journal.acquire_lease()
    return journal
