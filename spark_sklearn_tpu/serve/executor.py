"""Multi-tenant search service — the async fair-share executor.

The reference's whole reason to exist was a *shared* Spark cluster:
many users submitting grid searches against one pool of executors
(reference: grid_search.py over a long-lived SparkContext).  Before
this module the TPU rebuild was a single-search owner of the device —
``GridSearchCV.fit`` blocked, and a second search in the same process
queued behind the first at Python level with no fairness, no admission
control and no shared accounting.  Online shared-cluster tuning
(arXiv:2309.01901) and gang-scheduled accelerator stages (JAMPI,
arXiv:2005.12048) are the reference designs this executor brings to
the session:

  - :class:`SearchExecutor` (owned by
    :class:`~spark_sklearn_tpu.utils.session.TpuSession`) runs the ONE
    device-dispatch loop (the ``sst-dispatch`` thread).  Submitted
    searches run their fits on worker threads and their chunk
    ``LaunchItem`` dispatches route through a shared queue, tagged
    with a tenant id and search handle, while each search's own
    stage/compile/gather threads keep overlapping host work with
    device compute exactly as before;
  - **fair share** — deficit round-robin over tenants, weighted by
    ``TpuConfig(tenant_weight)``: per scheduling round each tenant
    earns ``scheduler_quantum x weight`` dispatch credit in task
    units, so a weight-3 tenant's chunks interleave onto the device at
    3x a weight-1 tenant's rate while both have chunks queued;
  - **admission control** — ``max_concurrent_searches`` running slots,
    a bounded ``max_queued_searches`` waiting line, per-tenant
    in-flight chunk caps (``tenant_max_inflight``), all rejecting with
    a clean :class:`AdmissionError` instead of unbounded queueing;
  - **tenant byte quotas** — each search's broadcast uploads are
    charged to its tenant in the device data plane
    (``TpuConfig(dataplane_tenant_bytes)``), so one tenant cannot
    evict another's resident X/y (parallel/dataplane.py);
  - **single-search short circuit** — with one active search and empty
    queues a dispatch runs inline on the search's own thread (no queue
    hop, no cross-thread handoff): the solo path keeps today's
    dispatch order and wall time;
  - **cancellation** — :meth:`SearchFuture.cancel` drains the search's
    queued chunks, fails its next dispatch with
    :class:`SearchCancelledError` (never retried, never host-fallback
    re-run), releases the tenant's data-plane charge when its last
    search ends, and leaves the checkpoint journal resumable.

Everything downstream of the dispatch queue is per-search and rides
along unchanged at LaunchItem granularity: the fault supervisor's
retry/bisection, the geometry planner, the checkpoint journal and the
program store all keep their contracts, so every submitted search's
``cv_results_`` is bit-exact with its solo run.

Observability: the per-search ``search_report["scheduler"]`` block
(schema pinned in ``obs.metrics.SCHEDULER_BLOCK_SCHEMA``) records
queue waits, the interleave fraction and the measured per-tenant
shares; ``serve.submit`` / ``sched.queue.wait`` / ``sched.dispatch``
spans land on the trace timeline.

NOTE on per-search counters under concurrency: the data-plane byte
totals, persistent-cache hit counts and ``n_compiles`` are process-
global deltas, so concurrent searches' traffic may bleed into each
other's numbers — scores never do.
"""

from __future__ import annotations

import dataclasses
import math
import os
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

from spark_sklearn_tpu.obs import heartbeat as _heartbeat
from spark_sklearn_tpu.obs import telemetry as _telemetry
from spark_sklearn_tpu.obs.log import get_logger
from spark_sklearn_tpu.obs.trace import get_tracer, set_correlation
from spark_sklearn_tpu.parallel.pipeline import FusedLaunch, LaunchItem
from spark_sklearn_tpu.serve.journal import JOURNAL_STATE_BY_HANDLE_STATE
from spark_sklearn_tpu.utils.locks import named_rlock

logger = get_logger(__name__)

__all__ = [
    "AdmissionError",
    "SearchCancelledError",
    "SearchExecutor",
    "SearchFuture",
    "SearchHandle",
    "current_binding",
    "report_block",
    "resolve_fusion",
    "resolve_fusion_max_width",
    "resolve_fusion_window_ms",
]

DEFAULT_TENANT = "default"

#: handle.queue_waits is bounded so a million-chunk search cannot grow
#: an unbounded list; the mean/max aggregates keep counting past it
_MAX_WAIT_SAMPLES = 4096

#: bounded global dispatch journal (handle id, tenant, cost) — the
#: fair-share tests read share ratios from its prefix
_MAX_DISPATCH_LOG = 4096


class AdmissionError(RuntimeError):
    """A submission was rejected by admission control: the executor's
    running slots (``max_concurrent_searches``) AND its bounded waiting
    line (``max_queued_searches``) are full, the executor is shutting
    down, or predictive admission (``TpuConfig.admission_mode=
    "predictive"``) priced the search out before any device work.
    Resubmit later, or raise the limits.

    Machine-readable fields: ``reason`` ("queue-full" | "shutdown" |
    "footprint" | "deadline-unmeetable"), ``retry_after_s`` (a hint,
    None when resubmitting will not help by itself), ``tenant``, and
    the queue/slot state at rejection (``n_active`` / ``n_pending`` /
    ``max_concurrent`` / ``max_queued``)."""

    def __init__(self, message: str, *, reason: str = "",
                 retry_after_s: Optional[float] = None,
                 tenant: Optional[str] = None, n_active: int = 0,
                 n_pending: int = 0, max_concurrent: int = 0,
                 max_queued: int = 0):
        super().__init__(message)
        self.reason = reason
        self.retry_after_s = retry_after_s
        self.tenant = tenant
        self.n_active = int(n_active)
        self.n_pending = int(n_pending)
        self.max_concurrent = int(max_concurrent)
        self.max_queued = int(max_queued)


class SearchCancelledError(RuntimeError):
    """The search was cancelled via :meth:`SearchFuture.cancel`.
    Raised from :meth:`SearchFuture.result` and from the cancelled
    search's next dispatch.  Completed chunks stay durable in the
    checkpoint journal, so an identically-configured search resumes
    them."""

    #: consumed by grid._dispatch: a cancelled compiled search must
    #: never be silently re-run on the host tier
    _sst_no_fallback = True
    #: consumed by faults.LaunchSupervisor: cancellation is an
    #: instruction, not a fault — no retry, no recovery, no journal
    _sst_cancelled = True


# ---------------------------------------------------------------------------
# Thread-local binding: which (executor, handle) the current thread's
# search runs under.  Set by the executor's worker threads; consulted
# by grid._run_groups to route LaunchItems and tag data-plane uploads.
# ---------------------------------------------------------------------------

_TLS = threading.local()


@dataclasses.dataclass(frozen=True)
class _Binding:
    executor: "SearchExecutor"
    handle: "SearchHandle"

    @property
    def tenant(self) -> str:
        return self.handle.tenant


def current_binding() -> Optional[_Binding]:
    """The executor binding of the calling thread's search, or None
    when the search runs standalone (a plain ``fit()`` call)."""
    return getattr(_TLS, "binding", None)


def resolve_tenant(config) -> str:
    """Tenant id under ``config``: ``TpuConfig.tenant``, else the
    ``SST_TENANT`` env var, else ``"default"``."""
    t = getattr(config, "tenant", None)
    if t:
        return str(t)
    return os.environ.get("SST_TENANT") or DEFAULT_TENANT


def resolve_weight(config) -> float:
    """Fair-share weight under ``config``: ``TpuConfig.tenant_weight``,
    else the ``SST_TENANT_WEIGHT`` env var, else 1.0."""
    w = getattr(config, "tenant_weight", None)
    if w is None:
        env = os.environ.get("SST_TENANT_WEIGHT")
        if env:
            try:
                w = float(env)
            except ValueError:
                w = None
    return max(float(w), 1e-6) if w is not None else 1.0


def resolve_fusion(config) -> bool:
    """Cross-search launch fusion under ``config``:
    ``TpuConfig.fusion``, else the ``SST_FUSION`` env var, else True.
    False is the exact escape hatch — every chunk dispatches solo."""
    f = getattr(config, "fusion", None)
    if f is not None:
        return bool(f)
    env = os.environ.get("SST_FUSION", "").strip().lower()
    if env in ("0", "false", "off", "no"):
        return False
    return True


def resolve_fusion_window_ms(config) -> float:
    """Fusion peer-wait window (milliseconds):
    ``TpuConfig.fusion_window_ms``, else ``SST_FUSION_WINDOW_MS``,
    else 5.0.  0 disables the hold (fusion still coalesces peers that
    are ALREADY queued when a fusable head dispatches)."""
    v = getattr(config, "fusion_window_ms", None)
    if v is None:
        env = os.environ.get("SST_FUSION_WINDOW_MS")
        if env:
            try:
                v = float(env)
            except ValueError:
                v = None
    return max(0.0, float(v)) if v is not None else 5.0


def resolve_fusion_max_width(config) -> int:
    """Fused-launch real-lane cap: ``TpuConfig.fusion_max_width``,
    else ``SST_FUSION_MAX_WIDTH``, else 0 = bounded only by the member
    plans' own width caps."""
    v = getattr(config, "fusion_max_width", None)
    if v is None:
        env = os.environ.get("SST_FUSION_MAX_WIDTH")
        if env:
            try:
                v = int(env)
            except ValueError:
                v = None
    return max(0, int(v)) if v is not None else 0


class SearchHandle:
    """Executor-side state of one submitted search.  Mutable counters
    are owned by the executor's lock; readers snapshot through
    :meth:`SearchExecutor.search_block` / :meth:`SearchFuture.progress`.
    """

    def __init__(self, hid: str, tenant: str, weight: float,
                 exclusive: bool = False):
        self.id = hid
        self.tenant = tenant
        self.weight = weight
        #: wants_float64 searches flip the process-wide jax x64 flag,
        #: so they are scheduled exclusively (no concurrent searches)
        self.exclusive = exclusive
        self.cancelled = False
        self.state = "queued"      # queued|running|done|failed|cancelled
        self.n_dispatched = 0      # chunks dispatched (routed + fastpath)
        self.n_fastpath = 0        # single-search inline dispatches
        self.n_interleaved = 0     # dispatches preceded by another search
        self.cost_dispatched = 0   # task units dispatched
        self.inflight = 0          # chunks dispatched, not yet finalized
        self.planned = 0           # live chunk estimate (progress())
        #: successive-halving view (SearchExecutor.note_rung): current
        #: rung index and the surviving-candidate fraction — the
        #: tenant's EFFECTIVE in-flight cap scales by the fraction, so
        #: a halving search's device claim shrinks as rungs retire
        #: candidates instead of holding rung-0's reservation
        self.rung = -1             # -1 = not a halving search
        self.rung_frac = 1.0
        #: bounded {tenant, wait_s} records — tenant-stamped so samples
        #: merged across concurrent searches still attribute per tenant
        self.queue_waits: List[Dict[str, Any]] = []
        self.queue_wait_s = 0.0
        self.queue_wait_max_s = 0.0
        self.t_start: Optional[float] = None
        self.t_end: Optional[float] = None
        #: perf_counter instant the search's deadline expires, stamped
        #: at SUBMIT when TpuConfig.search_deadline_s is set — queue
        #: wait counts against the budget, and grid's protection
        #: context reads this instead of starting its own clock
        self.t_deadline: Optional[float] = None
        #: per-tenant dispatched-cost snapshot at search start — the
        #: window the report's tenant shares are measured over
        self.cost_window_before: Dict[str, int] = {}
        self.tenant_shares: Dict[str, float] = {}
        self.share_frac = 0.0
        #: cross-search launch fusion counters (owned by the executor
        #: lock like every counter above; reported only when fusion is
        #: resolved ON, so fusion=False blocks stay byte-identical)
        self.n_fused = 0             # dispatches served by a fused launch
        self.lanes_donated = 0       # real peer lanes this search's fused
        #                              heads carried for other searches
        self.lanes_borrowed = 0      # own real lanes run in peers' launches
        self.fusion_saved_launches = 0  # solo launches fusing avoided


class _Tenant:
    """One tenant's scheduler state: its FIFO request queue, DRR
    deficit, and in-flight chunk count across all of its searches."""

    __slots__ = ("name", "weight", "deficit", "queue", "inflight",
                 "cost_total")

    def __init__(self, name: str, weight: float):
        self.name = name
        self.weight = weight
        self.deficit = 0.0
        self.queue: deque = deque()
        self.inflight = 0
        self.cost_total = 0


@dataclasses.dataclass
class _Request:
    """One chunk dispatch waiting in the fair-share queue."""

    handle: SearchHandle
    item: LaunchItem
    launch: Callable[[Any], Any]
    payload: Any
    cost: int
    state: Dict[str, Any]          # per-item wrapper state
    t_enqueued: float
    t_dequeued: float = 0.0
    reply: Any = None              # threading.Event-backed _Reply


class _Reply:
    """Minimal one-shot future for a dispatch reply (stdlib Future
    would work, but this keeps the executor's locking story explicit
    and exception-type-transparent)."""

    __slots__ = ("_evt", "_out", "_exc")

    def __init__(self):
        self._evt = threading.Event()
        self._out = None
        self._exc: Optional[BaseException] = None

    def set_result(self, out) -> None:
        self._out = out
        self._evt.set()

    def set_exception(self, exc: BaseException) -> None:
        self._exc = exc
        self._evt.set()

    def result(self):
        self._evt.wait()
        if self._exc is not None:
            raise self._exc
        return self._out


class SearchFuture:
    """Handle to a submitted search: ``result()`` blocks for the
    fitted estimator, ``cancel()`` aborts, ``progress()`` reports the
    live chunk-dispatch state."""

    def __init__(self, executor: "SearchExecutor", handle: SearchHandle,
                 search):
        self._executor = executor
        self._handle = handle
        self._search = search
        self._done = threading.Event()
        self._exc: Optional[BaseException] = None

    # -- executor side ---------------------------------------------------
    def _finish(self, exc: Optional[BaseException]) -> None:
        self._exc = exc
        self._done.set()

    # -- consumer side ---------------------------------------------------
    @property
    def handle_id(self) -> str:
        """The executor's handle id (``tenant/sN``) — what the service
        journal links a recovered entry's successor to."""
        return self._handle.id

    def done(self) -> bool:
        return self._done.is_set()

    def cancelled(self) -> bool:
        return self._handle.state == "cancelled"

    def result(self, timeout: Optional[float] = None):
        """The fitted search estimator.  Raises whatever ``fit``
        raised; a cancelled search raises
        :class:`SearchCancelledError`."""
        if not self._done.wait(timeout):
            raise TimeoutError(
                f"search {self._handle.id!r} not done after {timeout}s")
        if self._exc is not None:
            raise self._exc
        return self._search

    def exception(self, timeout: Optional[float] = None):
        if not self._done.wait(timeout):
            raise TimeoutError(
                f"search {self._handle.id!r} not done after {timeout}s")
        return self._exc

    def cancel(self) -> bool:
        """Cancel the search: queued chunks drain immediately, the next
        dispatch raises, queued-but-unstarted searches never start.
        Returns False when the search already finished."""
        return self._executor.cancel(self._handle)

    def progress(self) -> Dict[str, Any]:
        """Live progress: state, chunks dispatched, the planned live-
        chunk estimate (known once geometry is planned) and their
        ratio.  With the in-flight heartbeat on
        (``TpuConfig.heartbeat`` / ``SST_HEARTBEAT``) a ``heartbeat``
        sub-dict adds intra-segment ``steps_done/steps_total`` and a
        blended ETA, so a scanned rung no longer freezes progress for
        its whole multi-minute launch."""
        return self._executor.progress(self._handle)


# ---------------------------------------------------------------------------
# Executor
# ---------------------------------------------------------------------------


class SearchExecutor:
    """The session-owned async search service.  See the module
    docstring for the architecture; the public surface is
    :meth:`submit` (-> :class:`SearchFuture`), :meth:`wrap_items`
    (consumed by ``grid._run_groups``), :meth:`search_block` /
    :func:`report_block` (the ``search_report["scheduler"]`` block)
    and :meth:`shutdown`."""

    def __init__(self, config=None, name: str = "sst-serve"):
        self.config = config
        self.name = name
        # reentrant: helpers called under the lock (start/accounting)
        # take it again themselves, so each is safe standalone
        self._lock = named_rlock("serve.SearchExecutor._lock")
        self._work = threading.Event()      # a queue may be non-empty
        self._gate = threading.Event()      # cleared = paused (tests/drain)
        self._gate.set()
        self._stop = False
        #: set at shutdown START: rejects new submissions immediately
        #: while the dispatch loop keeps serving active searches'
        #: queued chunks until they finish (_stop ends the loop)
        self._closing = False
        self._thread: Optional[threading.Thread] = None
        self._tenants: Dict[str, _Tenant] = {}
        self._rr = 0                        # DRR rotation cursor
        self._seq = 0
        self._active: List[SearchHandle] = []
        self._pending: deque = deque()      # (handle, future, thunk)
        self._workers: List[threading.Thread] = []
        self._last_handle: Optional[SearchHandle] = None
        self._cost_by_tenant: Dict[str, int] = {}
        self._dispatch_log: deque = deque(maxlen=_MAX_DISPATCH_LOG)
        #: recent completed-search walls (seconds) — predictive
        #: admission's queue-wait forecast divides the waiting line by
        #: running-slot count and multiplies by the p50 of these
        self._recent_walls: deque = deque(maxlen=64)
        self._quantum = max(1, int(getattr(config, "scheduler_quantum",
                                           64) or 64))
        self._max_concurrent = max(1, int(getattr(
            config, "max_concurrent_searches", 8) or 8))
        self._max_queued = max(0, int(getattr(
            config, "max_queued_searches", 16) or 0))
        self._tenant_cap = max(0, int(getattr(
            config, "tenant_max_inflight", 0) or 0))
        #: cross-search launch fusion (ISSUE 14): same-program chunks
        #: from different searches coalesce into one wide launch
        self._fusion = resolve_fusion(config)
        self._fusion_window_s = resolve_fusion_window_ms(config) / 1000.0
        self._fusion_max_width = resolve_fusion_max_width(config)
        #: hint from _pop_next to _loop: a fusable head is being held
        #: inside its fusion window — sleep a sliver, don't hot-spin
        self._fuse_defer = False
        #: durable service journal (serve/journal.py), bound by the
        #: session via attach_journal.  None (the default) is the
        #: exact no-op: every _journal_note_* early-outs, zero writes.
        self._journal = None

    # -- durable journal hooks -------------------------------------------
    def attach_journal(self, journal) -> None:
        """Bind the session's :class:`~spark_sklearn_tpu.serve.journal.
        ServiceJournal`.  All journal notes are called OUTSIDE
        ``self._lock`` (the journal has its own lock and fsyncs on
        append — never under the scheduler's lock)."""
        self._journal = journal

    def _journal_note_submitted(self, handle, search, X, y, cfg,
                                recovered_from: str = "") -> None:
        # caller does NOT hold self._lock
        if self._journal is None:
            return
        from spark_sklearn_tpu.serve import journal as _svc_journal
        try:
            est = getattr(search, "estimator", None)
            family = type(est).__name__ if est is not None \
                else type(search).__name__
            digest = _svc_journal.submission_digest(search, X, y)
            fp = _svc_journal.data_fingerprint(X, y)
        except (TypeError, ValueError) as exc:
            # non-array data the fingerprint cannot hash: journal the
            # submission anyway (state tracking still recovers it),
            # just without a verifiable binding
            logger.warning("service journal: fingerprint failed for "
                           "%s (%r)", handle.id, exc, handle=handle.id)
            family, digest, fp = type(search).__name__, "", ""
        self._journal.record_submission(
            handle.id, tenant=handle.tenant, weight=handle.weight,
            family=family, structure_digest=digest,
            data_fingerprint=fp,
            checkpoint_dir=getattr(cfg, "checkpoint_dir", None) or "",
            config=cfg, recovered_from=recovered_from)

    def _journal_note_state(self, handle, state: str, **extra) -> None:
        # caller does NOT hold self._lock
        if self._journal is None:
            return
        self._journal.record_transition(handle.id, state, **extra)

    # -- submission ------------------------------------------------------
    def submit(self, search, X, y=None, fit_params: Optional[dict] = None,
               tenant: Optional[str] = None,
               weight: Optional[float] = None,
               recovered_from: str = "") -> SearchFuture:
        """Run ``search.fit(X, y, **fit_params)`` on a worker thread
        under this executor's fair-share scheduling and return a
        :class:`SearchFuture`.  Tenant identity and weight resolve from
        the search's own config (or the executor's) unless passed
        explicitly.  Raises :class:`AdmissionError` when both the
        running slots and the bounded waiting line are full."""
        cfg = getattr(search, "config", None) or self.config
        tenant = tenant or resolve_tenant(cfg)
        weight = weight if weight is not None else resolve_weight(cfg)
        exclusive = self._needs_exclusive(search)
        predictive = str(getattr(cfg, "admission_mode", "static")
                         or "static") == "predictive"
        deadline_s = getattr(cfg, "search_deadline_s", None)
        # the footprint check prices the search against the HBM budget
        # with the memory ledger's model — computed OUTSIDE self._lock
        # (the ledger has its own lock) and before any state mutation
        footprint_exc = self._admission_footprint_check(
            search, X, y, cfg, tenant) if predictive else None
        try:
            with get_tracer().span("serve.submit", tenant=tenant):
                with self._lock:
                    if self._stop or self._closing:
                        raise AdmissionError(
                            "executor is shut down; no new searches",
                            reason="shutdown", tenant=tenant,
                            n_active=len(self._active),
                            n_pending=len(self._pending),
                            max_concurrent=self._max_concurrent,
                            max_queued=self._max_queued)
                    if footprint_exc is not None:
                        raise footprint_exc
                    queue_now = bool(self._pending) or \
                        not self._can_start_new(exclusive)
                    if queue_now and predictive and deadline_s:
                        # SLO forecast: a search that would provably
                        # blow its whole deadline waiting in line is
                        # refused NOW, not after queueing device-less
                        # for deadline_s and shedding everything
                        forecast = self._queue_wait_forecast_locked()
                        if forecast is not None and \
                                forecast > float(deadline_s):
                            raise AdmissionError(
                                f"admission deferred for tenant "
                                f"{tenant!r}: forecast queue wait "
                                f"{forecast:.1f}s exceeds "
                                f"search_deadline_s={deadline_s:g}s",
                                reason="deadline-unmeetable",
                                retry_after_s=round(forecast, 3),
                                tenant=tenant,
                                n_active=len(self._active),
                                n_pending=len(self._pending),
                                max_concurrent=self._max_concurrent,
                                max_queued=self._max_queued)
                    if queue_now and \
                            len(self._pending) >= self._max_queued:
                        # reject BEFORE any state mutation: a refused
                        # submission must not bump the sequence or
                        # rewrite its tenant's live fair-share weight
                        raise AdmissionError(
                            f"admission rejected for tenant {tenant!r}: "
                            f"{len(self._active)} running (max "
                            f"{self._max_concurrent}) and "
                            f"{len(self._pending)} queued (max "
                            f"{self._max_queued})",
                            reason="queue-full",
                            retry_after_s=self._wall_p50_locked(),
                            tenant=tenant,
                            n_active=len(self._active),
                            n_pending=len(self._pending),
                            max_concurrent=self._max_concurrent,
                            max_queued=self._max_queued)
                    self._seq += 1
                    hid = f"{tenant}/s{self._seq}"
                    handle = SearchHandle(hid, tenant, weight,
                                          exclusive=exclusive)
                    if deadline_s:
                        # the deadline clock starts at SUBMIT: queue
                        # wait spends the same budget device time does
                        handle.t_deadline = time.perf_counter() \
                            + float(deadline_s)
                    future = SearchFuture(self, handle, search)
                    handle.future = future
                    t = self._tenants.get(tenant)
                    if t is None:
                        t = self._tenants[tenant] = _Tenant(tenant,
                                                            weight)
                    else:
                        t.weight = weight  # latest ADMITTED search wins
                    thunk = self._make_worker(handle, future, search,
                                              X, y,
                                              dict(fit_params or {}))
                    # FIFO honesty: while anything is already waiting,
                    # new arrivals wait behind it — otherwise a pending
                    # exclusive (x64) search could be starved forever
                    # by a stream of immediately-startable submissions
                    if queue_now:
                        self._pending.append((handle, future, thunk))
                        logger.info(
                            "search %s queued (tenant=%s, %d running)",
                            hid, tenant, len(self._active),
                            handle=hid, tenant=tenant)
                    else:
                        self._start_locked(handle, thunk)
        except AdmissionError as exc:
            # telemetry outside the lock (hook discipline); the
            # rejection carries its machine-readable reason
            _telemetry.note_admission("rejected", tenant,
                                      getattr(exc, "reason", "") or "")
            # the shed submission never got a handle: journal the
            # refusal itself so the workload record is complete
            if self._journal is not None:
                self._journal.record_transition(
                    f"{tenant}/rejected", "shed", tenant=tenant,
                    reason=getattr(exc, "reason", "") or "")
            raise
        # durable WAL entry BEFORE the future is handed back: a crash
        # after this point leaves a non-terminal record recover() owes
        self._journal_note_submitted(handle, search, X, y, cfg,
                                     recovered_from=recovered_from)
        _telemetry.note_admission("queued" if queue_now else "admitted",
                                  tenant)
        return future

    def _admission_footprint_check(self, search, X, y, cfg,
                                   tenant: str) -> Optional[AdmissionError]:
        """Predictive admission's HBM pricing: model the search's
        MINIMUM feasible footprint (broadcast residents + one single-
        candidate chunk, scaled by the ledger's learned safety margin)
        and refuse when even that cannot fit ``hbm_budget_bytes`` — no
        geometry could launch it, so rejecting costs zero device work.
        Returns the error to raise, or None to admit."""
        from spark_sklearn_tpu.obs import memory as _obs_memory
        from spark_sklearn_tpu.parallel import memledger as _memledger
        ledger = _memledger.ledger_for(cfg)
        if ledger is None:
            return None
        budget = _obs_memory.resolve_hbm_budget(cfg)
        if not budget:
            return None
        grid = getattr(search, "param_grid", None)
        if not isinstance(grid, dict):
            grid = getattr(search, "param_distributions", None)
        if not isinstance(grid, dict) or X is None:
            return None
        import numpy as np
        dyn: Dict[str, Any] = {}
        for name, vals in grid.items():
            try:
                arr = np.asarray(list(vals)
                                 if not hasattr(vals, "dtype") else vals)
            # non-materializable values (e.g. scipy distributions)
            # just mean this param stages nothing predictable — the
            # admission probe models what it can, never fails a
            # submit; nothing has launched yet, so the fault taxonomy
            # does not apply
            # sstlint: disable=swallowed-exception,launch-except-taxonomy
            except Exception:
                continue
            if arr.dtype.kind in "fiub":
                dyn[name] = arr[:1]
        cv = getattr(search, "cv", None)
        n_folds = cv if isinstance(cv, int) else \
            int(getattr(cv, "n_splits", 0) or 0) or 5
        n = int(getattr(X, "shape", (len(X),))[0])
        fp = _memledger.model_group_footprint(
            dyn, 1, n_folds, task_batched=True, n_samples=n,
            return_train=bool(getattr(search, "return_train_score",
                                      False)))
        # true dataset bytes: dense nbytes, or the CSR component sum
        # for sparse X (scipy sparse has no .nbytes — the old getattr
        # spelling priced it at zero and dense-equivalent pricing would
        # over-reject by orders of magnitude)
        x_bytes = _memledger.dataset_nbytes(X)
        y_bytes = _memledger.dataset_nbytes(y)
        from spark_sklearn_tpu.search import stream as _stream
        if _stream.resolve_data_mode(cfg) == "stream":
            # streamed submission: X is never wholly resident — price
            # the double-buffered shard slab the stream planner will
            # actually keep on device
            x_bytes = min(x_bytes,
                          2 * _stream.resolve_shard_bytes(cfg))
        # broadcast residents: X/y replicas + the base fold masks
        # (train + test, int32) the data plane keeps device-resident
        resident = x_bytes + y_bytes + 2 * n_folds * n * 4
        margin = max(1.0, float(getattr(ledger, "safety_margin", 1.0)))
        modeled = int((resident + fp["chunk_bytes"]) * margin)
        if modeled <= int(budget):
            return None
        with self._lock:
            state = (len(self._active), len(self._pending))
        return AdmissionError(
            f"admission rejected for tenant {tenant!r}: modeled "
            f"footprint {modeled} byte(s) (residents {resident} + "
            f"minimum chunk {fp['chunk_bytes']}, margin "
            f"{margin:.2f}) exceeds hbm_budget_bytes={int(budget)}",
            reason="footprint", retry_after_s=None, tenant=tenant,
            n_active=state[0], n_pending=state[1],
            max_concurrent=self._max_concurrent,
            max_queued=self._max_queued)

    def _wall_p50_locked(self) -> Optional[float]:
        # caller holds the lock
        if not self._recent_walls:
            return None
        vals = sorted(self._recent_walls)
        return round(float(vals[len(vals) // 2]), 3)

    def _queue_wait_forecast_locked(self) -> Optional[float]:
        """p50-of-recent-walls x the waiting line's depth in running-
        slot waves — None until at least one search completed (no
        data beats a wrong forecast)."""
        p50 = self._wall_p50_locked()
        if p50 is None:
            return None
        waves = -(-(len(self._pending) + 1) // max(
            1, self._max_concurrent))
        return p50 * waves

    def _needs_exclusive(self, search) -> bool:
        """wants_float64 families flip the process-global jax x64 flag
        for their whole fit — concurrent searches would trace under the
        wrong dtype, so they schedule exclusively."""
        if getattr(search, "backend", None) == "host":
            return False
        est = getattr(search, "estimator", None)
        if est is None:
            return False
        try:
            from spark_sklearn_tpu.models.base import resolve_family
            fam = resolve_family(est)
        # resolution failing here just means the search decides its own
        # tier later; non-exclusive is the safe default because only
        # RESOLVED wants_float64 families touch the x64 flag — this is
        # an admission-time probe, not a launch failure to classify
        # sstlint: disable=swallowed-exception,launch-except-taxonomy
        except Exception:
            return False
        return bool(getattr(fam, "wants_float64", False))

    def _apply_tenant_quota(self, cfg, tenant: str) -> None:
        quota = int(getattr(cfg, "dataplane_tenant_bytes", 0) or 0)
        if quota <= 0:
            return
        from spark_sklearn_tpu.parallel import dataplane as _dataplane
        plane = _dataplane.plane_for(cfg)
        if plane is not None:
            plane.set_tenant_quota(tenant, quota)

    def _can_start(self, handle: SearchHandle) -> bool:
        return self._can_start_new(handle.exclusive)

    def _can_start_new(self, exclusive: bool) -> bool:
        # caller holds the lock
        if any(h.exclusive for h in self._active):
            return False
        if exclusive:
            return not self._active
        return len(self._active) < self._max_concurrent

    def _start_locked(self, handle: SearchHandle, thunk) -> None:
        with self._lock:
            self._active.append(handle)
            handle.state = "running"
            handle.t_start = time.perf_counter()
            handle.cost_window_before = dict(self._cost_by_tenant)
            worker = threading.Thread(
                target=thunk, name=f"{self.name}-{handle.id}",
                daemon=True)
            self._workers.append(worker)
        worker.start()

    def _make_worker(self, handle, future, search, X, y, fit_params):
        cfg = getattr(search, "config", None) or self.config

        def run():
            # durable "running" transition first thing on the worker
            # thread — outside the executor lock, before any fit work
            self._journal_note_state(handle, "running")
            _TLS.binding = _Binding(self, handle)
            # tenant/handle correlation: stamped onto every span and
            # structured log record this thread (and the pipeline
            # workers it spawns) emits, so a multi-tenant trace or
            # flight bundle attributes each event to its search
            set_correlation({"tenant": handle.tenant,
                             "handle": handle.id})
            exc: Optional[BaseException] = None
            try:
                if handle.cancelled:
                    raise SearchCancelledError(
                        f"search {handle.id!r} cancelled before start")
                # tenant byte quota in the device data plane — applied
                # at worker START so searches admitted via the waiting
                # line get it too (the plane has its own lock)
                self._apply_tenant_quota(cfg, handle.tenant)
                search.fit(X, y, **fit_params)
            # the worker is a thread boundary: EVERY failure (cancel
            # included) must marshal to the future's consumer via
            # future._finish below instead of dying on a daemon thread
            # — the fault taxonomy already ran inside fit's supervisor
            # sstlint: disable=broad-except-swallow,launch-except-taxonomy
            except BaseException as e:
                exc = e
            finally:
                _TLS.binding = None
                set_correlation(None)
                if exc is None:
                    # surface the search doctor's one-line diagnosis on
                    # the serving channel, so a fleet operator sees the
                    # critical path without opening the report
                    attr = (getattr(search, "search_report", None)
                            or {}).get("attribution") or {}
                    if attr.get("verdict"):
                        logger.info(
                            "search %s doctor: %s", handle.id,
                            attr["verdict"], handle=handle.id,
                            tenant=handle.tenant,
                            dominant=attr.get("dominant", ""),
                            regression=(attr.get("regression") or {})
                            .get("status", ""))
                self._finish_search(handle, exc)
                future._finish(exc)
        return run

    def _finish_search(self, handle: SearchHandle,
                       exc: Optional[BaseException]) -> None:
        release_tenant = None
        with self._lock:
            if handle in self._active:
                self._active.remove(handle)
            handle.t_end = time.perf_counter()
            if exc is None and handle.t_start is not None:
                # completed walls feed the admission SLO forecast
                self._recent_walls.append(handle.t_end - handle.t_start)
            if exc is None:
                # includes a cancel that lost the race to a completed
                # fit: the results are valid, so the future resolves
                handle.state = "done"
            elif isinstance(exc, SearchCancelledError):
                handle.state = "cancelled"
            elif handle.state != "cancelled":
                handle.state = "failed"
            t = self._tenants.get(handle.tenant)
            if t is not None and handle.inflight:
                t.inflight = max(0, t.inflight - handle.inflight)
                handle.inflight = 0
            # prune finished worker threads: a long-lived serving
            # session must not accumulate a Thread object per
            # historical search
            self._workers = [w for w in self._workers if w.is_alive()]
            self._update_shares(handle)
            # a cancelled tenant with no other live searches releases
            # its data-plane charge (outside the lock, below)
            if handle.state == "cancelled" and not any(
                    h.tenant == handle.tenant
                    for h in self._active) and not any(
                    p[0].tenant == handle.tenant for p in self._pending):
                release_tenant = handle.tenant
            while self._pending and self._can_start(self._pending[0][0]):
                nxt_handle, _, nxt_thunk = self._pending.popleft()
                if nxt_handle.cancelled:
                    continue
                self._start_locked(nxt_handle, nxt_thunk)
            self._work.set()    # re-evaluate runnability (caps freed)
        if release_tenant is not None:
            from spark_sklearn_tpu.parallel import dataplane as _dataplane
            plane = _dataplane.get_dataplane()
            freed = plane.release_tenant(release_tenant)
            logger.info("tenant %s: released %d data-plane byte(s) on "
                        "cancellation", release_tenant, freed,
                        tenant=release_tenant)
        # terminal transition in the WAL (outside the lock): after this
        # line a restart owes this search nothing
        self._journal_note_state(
            handle, JOURNAL_STATE_BY_HANDLE_STATE.get(handle.state,
                                                      handle.state))
        logger.info("search %s %s (%d chunk(s) dispatched, %d fastpath)",
                    handle.id, handle.state, handle.n_dispatched,
                    handle.n_fastpath, handle=handle.id,
                    state=handle.state)

    def _update_shares(self, handle: SearchHandle) -> None:
        # caller holds the lock; window = [search start, now]
        before = handle.cost_window_before or {}
        deltas = {t: c - before.get(t, 0)
                  for t, c in self._cost_by_tenant.items()}
        deltas = {t: c for t, c in deltas.items() if c > 0}
        total = sum(deltas.values())
        if total > 0:
            handle.tenant_shares = {
                t: round(c / total, 4) for t, c in sorted(deltas.items())}
            handle.share_frac = round(
                handle.cost_dispatched / total, 4)

    # -- cancellation ----------------------------------------------------
    def cancel(self, handle: SearchHandle) -> bool:
        drained: List[_Request] = []
        with self._lock:
            if handle.state in ("done", "failed", "cancelled"):
                return False
            handle.cancelled = True
            was_queued = handle.state == "queued"
            handle.state = "cancelled"
            t = self._tenants.get(handle.tenant)
            if t is not None:
                keep = deque()
                for req in t.queue:
                    # queued requests are not yet in flight (the cap
                    # counts dispatched-unfinalized chunks), so drain
                    # needs no in-flight adjustment
                    (drained if req.handle is handle else keep).append(req)
                t.queue = keep
            if was_queued:
                self._pending = deque(
                    p for p in self._pending if p[0] is not handle)
            self._work.set()
        exc = SearchCancelledError(
            f"search {handle.id!r} was cancelled "
            f"({len(drained)} queued chunk(s) drained)")
        for req in drained:
            req.reply.set_exception(exc)
        if was_queued:
            # never started: no worker will ever _finish it
            self._finish_search(handle, exc)
            handle.future._finish(exc)
        logger.info("search %s cancelled (%d queued chunk(s) drained)",
                    handle.id, len(drained), handle=handle.id)
        # black box: a cancellation is an operator-visible incident —
        # bundle the scheduler state + recent events for the postmortem
        # (dir checked FIRST: without one, no state is even copied)
        if _telemetry.resolve_flight_dir(self.config) is not None:
            rec = _telemetry.flight_recorder()
            sched = {**self.stats(),
                     "dispatch_log": self.dispatch_log()[-256:]}
            ctx = {"handle": handle.id, "tenant": handle.tenant,
                   "drained": len(drained)}
            if handle.t_deadline is not None and \
                    time.perf_counter() >= handle.t_deadline:
                # a cancel AFTER deadline expiry is a protection
                # verdict, not an operator whim: tag the bundle so the
                # postmortem tooling groups it with shed/quarantine
                rec.protection_dump("deadline-expired",
                                    reason="cancelled",
                                    config=self.config, scheduler=sched,
                                    context=ctx)
            else:
                rec.dump("cancelled", config=self.config,
                         scheduler=sched, context=ctx)
        return True

    def progress(self, handle: SearchHandle) -> Dict[str, Any]:
        # the heartbeat hub owns its own named lock — query it BEFORE
        # taking ours (no cross-module lock nesting).  None (heartbeat
        # off / no scanned segments yet) leaves the dict unchanged, so
        # the pre-heartbeat progress shape is byte-identical.
        hb = _heartbeat.get_hub().progress_for_handle(handle.id)
        with self._lock:
            frac = (min(1.0, handle.n_dispatched / handle.planned)
                    if handle.planned else None)
            out = {
                "state": handle.state,
                "tenant": handle.tenant,
                "dispatched": handle.n_dispatched,
                "planned": handle.planned,
                "frac": frac,
            }
            if handle.rung >= 0:
                out["rung"] = handle.rung
                out["rung_frac"] = round(handle.rung_frac, 4)
            if hb is not None:
                # intra-segment steps_done/steps_total + blended ETA:
                # the scanned rung no longer freezes progress for a
                # whole multi-minute launch
                out["heartbeat"] = hb
            return out

    def note_planned(self, handle: SearchHandle, n: int) -> None:
        """Live-chunk estimate from the search's geometry plan, for
        :meth:`SearchFuture.progress`."""
        with self._lock:
            handle.planned = int(n)

    def note_rung(self, handle: SearchHandle, itr: int,
                  n_candidates: int, frac: float) -> None:
        """A halving search's rung transition (search/halving.py):
        records the rung index and surviving-candidate fraction.  The
        fraction scales the tenant's effective in-flight chunk cap in
        :meth:`_pop_next` — as rungs retire candidates the search's
        claim on the shared device shrinks with them, freeing dispatch
        slots for other tenants mid-search instead of at search end."""
        with self._lock:
            handle.rung = int(itr)
            handle.rung_frac = min(1.0, max(float(frac), 0.0)) or 1.0
        logger.info(
            "search %s entered halving rung %d (%d candidate(s), "
            "share %.3f)", handle.id, itr, n_candidates,
            handle.rung_frac, handle=handle.id, rung=int(itr))

    def _effective_cap(self, tenant_name: str) -> int:
        """The tenant's in-flight chunk cap, scaled by its active
        halving searches' surviving fraction (caller holds the lock).
        0 = unbounded.  Any active NON-halving search of the tenant
        pins the fraction to 1.0 — the tenant-wide cap must never
        starve an exhaustive search because a sibling halving search
        reached a late rung."""
        cap = self._tenant_cap
        if not cap:
            return 0
        frac = 0.0
        seen = False
        for h in self._active:
            if h.tenant != tenant_name:
                continue
            seen = True
            frac = max(frac, 1.0 if h.rung < 0 else h.rung_frac)
            if frac >= 1.0:
                return cap
        if not seen:
            return cap
        return max(1, int(math.ceil(cap * frac)))

    # -- item wrapping (the grid._run_groups seam) -----------------------
    def wrap_items(self, handle: SearchHandle, items):
        """Wrap a search's LaunchItem stream so every dispatch routes
        through the shared fair-share queue (lazily — the pipeline's
        stage-ahead behavior is preserved).  Applied UNDER the fault
        supervisor's wrapper, so retries re-enter the queue and one
        tenant's recovery runs on its own search's threads, never on
        the shared dispatch loop."""
        for item in items:
            yield self._wrap_one(handle, item)

    def _wrap_one(self, handle: SearchHandle,
                  item: LaunchItem) -> LaunchItem:
        inner_launch = item.launch
        inner_finalize = item.finalize
        # DRR billing is in task units: a scanned segment (kind="scan",
        # chunk_loop="scan") carries the SUM of its member chunks' real
        # lanes in n_tasks, so its one coarse launch debits the tenant
        # exactly what the per-chunk launches it replaced would have
        cost = max(1, int(item.n_tasks or 0))
        #: first_wait = the dispatch-phase call's queue wait (the
        #: pipeline calls launch exactly once; later calls are
        #: supervisor retries whose walls land in the wait phase) —
        #: only it may be subtracted from dispatch_s.  queue_wait_s
        #: totals every attempt for the reported timings.
        state: Dict[str, Any] = {"counted": False, "queue_wait_s": 0.0,
                                 "first_wait": None}

        def routed_launch(payload, item=item):
            if handle.cancelled:
                raise SearchCancelledError(
                    f"search {handle.id!r} was cancelled")
            if self._try_fastpath(handle, cost, state):
                # single active search, empty queues: dispatch inline —
                # today's order, zero queue hops (and zero wait: a
                # later ROUTED retry must not claim the first-wait
                # slot, its wall is not in dispatch_s)
                if state["first_wait"] is None:
                    state["first_wait"] = 0.0
                self._note_dispatch_out(handle, cost, None,
                                        fastpath=True, key=item.key)
                return inner_launch(payload)
            req = _Request(handle=handle, item=item, launch=inner_launch,
                           payload=payload, cost=cost, state=state,
                           t_enqueued=time.perf_counter(), reply=_Reply())
            self._enqueue(req)
            with get_tracer().span("sched.queue.wait", key=item.key,
                                   tenant=handle.tenant):
                out = req.reply.result()
            wait = max(0.0, req.t_dequeued - req.t_enqueued)
            state["queue_wait_s"] += wait
            if state["first_wait"] is None:
                state["first_wait"] = wait
            return out

        def routed_finalize(host, tm):
            qw = state["queue_wait_s"]
            first = state["first_wait"] or 0.0
            state["queue_wait_s"] = 0.0
            state["first_wait"] = None
            if qw:
                # keep fair-share waiting out of dispatch_s — the
                # geometry cost model prices launch overhead from it,
                # and contention is not overhead of THIS launch.  Only
                # the dispatch-phase (first) wait is in dispatch_s;
                # retry waits landed in the wait phase's wall
                tm.queue_wait_s += qw
                tm.dispatch_s = max(0.0, tm.dispatch_s - first)
            self._note_done(handle, state)
            if inner_finalize is not None:
                inner_finalize(host, tm)

        return LaunchItem(
            key=item.key, launch=routed_launch, stage=item.stage,
            gather=item.gather, finalize=routed_finalize,
            group=item.group, kind=item.kind, n_tasks=item.n_tasks,
            n_chunks=item.n_chunks, wait=item.wait, bisect=item.bisect,
            host_fallback=item.host_fallback, fuse=item.fuse)

    def _try_fastpath(self, handle: SearchHandle, cost: int,
                      state: Dict[str, Any]) -> bool:
        if not self._gate.is_set():
            return False
        with self._lock:
            if self._stop or len(self._active) != 1 \
                    or self._active[0] is not handle:
                return False
            if any(t.queue for t in self._tenants.values()):
                return False
            handle.n_fastpath += 1
            self._account_dispatch(handle, cost)
            self._count_inflight(handle, state)
            return True

    def _count_inflight(self, handle: SearchHandle,
                        state: Dict[str, Any]) -> None:
        # caller holds the lock; in flight = dispatched, not finalized.
        # counted at most once per item (a supervisor retry re-routes
        # the SAME item, which is still in flight)
        if not state.get("counted"):
            state["counted"] = True
            handle.inflight += 1
            t = self._tenants.get(handle.tenant)
            if t is not None:
                t.inflight += 1

    def _account_dispatch(self, handle: SearchHandle, cost: int) -> None:
        with self._lock:
            handle.n_dispatched += 1
            handle.cost_dispatched += cost
            if self._last_handle is not None and \
                    self._last_handle is not handle:
                handle.n_interleaved += 1
            self._last_handle = handle
            t = self._tenants.get(handle.tenant)
            if t is not None:
                t.cost_total += cost
            self._cost_by_tenant[handle.tenant] = \
                self._cost_by_tenant.get(handle.tenant, 0) + cost
            self._dispatch_log.append((handle.id, handle.tenant, cost))

    def _enqueue(self, req: _Request) -> None:
        self._ensure_loop()
        with self._lock:
            if self._stop:
                # the dispatch loop is gone: failing loudly beats a
                # request that would sit unserved forever (the search's
                # supervisor surfaces this as a fatal launch error)
                req.reply.set_exception(AdmissionError(
                    "executor is shut down; chunk dispatch refused"))
                return
            t = self._tenants.get(req.handle.tenant)
            if t is None:
                t = self._tenants[req.handle.tenant] = _Tenant(
                    req.handle.tenant, req.handle.weight)
            t.queue.append(req)
            self._work.set()

    def _note_done(self, handle: SearchHandle,
                   state: Dict[str, Any]) -> None:
        with self._lock:
            if state.get("counted"):
                state["counted"] = False
                handle.inflight = max(0, handle.inflight - 1)
                t = self._tenants.get(handle.tenant)
                if t is not None:
                    t.inflight = max(0, t.inflight - 1)
                    self._work.set()   # a capped tenant may be runnable

    # -- the shared dispatch loop ----------------------------------------
    def _ensure_loop(self) -> None:
        with self._lock:
            if self._stop:
                return
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._loop, name="sst-dispatch", daemon=True)
                self._thread.start()

    def _loop(self) -> None:
        while not self._stop:
            if not self._gate.wait(0.1):
                continue
            if not self._work.wait(0.1):
                continue
            try:
                req = self._pop_next()
                if req is not None:
                    self._run_request(req)
                else:
                    with self._lock:
                        defer = self._fuse_defer
                        self._fuse_defer = False
                    if defer:
                        # a fusable head is holding for a same-program
                        # peer inside its fusion window: sleep a sliver
                        # instead of hot-spinning on the still-set work
                        # event
                        time.sleep(0.0005)
            # defensive: a scheduler bug must degrade to a logged error
            # + the next poll, never a silently-dead dispatch loop with
            # every search hung on its reply (launch failures never
            # reach here — _run_request marshals them to the reply)
            # sstlint: disable=broad-except-swallow,launch-except-taxonomy
            except Exception as exc:
                logger.warning("dispatch loop error (%r); continuing",
                               exc)
                time.sleep(0.05)

    def _pop_next(self) -> Optional[_Request]:
        """Deficit round-robin: rotate over tenants; a visited tenant
        earns ``quantum x weight`` credit when its head does not fit,
        and dispatches while its head's cost fits the deficit."""
        with self._lock:
            names = sorted(self._tenants)
            n = len(names)
            runnable = 0
            now = time.perf_counter()
            for off in range(n):
                idx = (self._rr + off) % n
                t = self._tenants[names[idx]]
                if not t.queue:
                    continue
                cap = self._effective_cap(t.name)
                if cap and t.inflight >= cap:
                    # in-flight chunks count the head itself once it
                    # dispatches, so >= holds the cap exactly (the cap
                    # shrinks with a halving tenant's surviving rung
                    # fraction — see note_rung)
                    continue
                runnable += 1
                head = t.queue[0]
                if self._fusion and self._fusion_window_s > 0.0 \
                        and head.item.fuse is not None \
                        and not head.handle.cancelled \
                        and now - head.t_enqueued < self._fusion_window_s \
                        and not self._has_fuse_peer_locked(head):
                    # fusion window: hold a fusable head briefly — a
                    # same-program peer from another search may arrive
                    # and fill its padded lanes.  The head stays at its
                    # queue front (FIFO intact) and dispatches solo
                    # once the window expires peer-less.  Scanned
                    # segments (kind="scan") never enter: their
                    # stacked step axis admits no peer lanes, so
                    # grid.py yields them with fuse=None (and turns
                    # cross-search fusion off for the whole search
                    # when chunk_loop="scan").
                    self._fuse_defer = True
                    continue
                if t.deficit < head.cost:
                    t.deficit += self._quantum * t.weight
                if t.deficit < head.cost:
                    continue          # earns more credit next round
                t.queue.popleft()
                t.deficit -= head.cost
                if not t.queue:
                    t.deficit = 0.0   # classic DRR: idle queues reset
                    self._rr = (idx + 1) % n
                elif t.deficit >= t.queue[0].cost:
                    # remaining credit covers the next head: stay on
                    # this tenant (one request returns per call, so the
                    # cursor must hold the burst a weight-w quantum
                    # grants — advancing every pop would flatten DRR
                    # into unweighted round-robin)
                    self._rr = idx
                else:
                    self._rr = (idx + 1) % n
                head.t_dequeued = time.perf_counter()
                self._account_dispatch(head.handle, head.cost)
                self._count_inflight(head.handle, head.state)
                wait = head.t_dequeued - head.t_enqueued
                h = head.handle
                h.queue_wait_s += wait
                h.queue_wait_max_s = max(h.queue_wait_max_s, wait)
                if len(h.queue_waits) < _MAX_WAIT_SAMPLES:
                    # tenant-stamped sample (ISSUE 8 satellite): merged
                    # samples from concurrent searches still attribute,
                    # so bench/fleet derive PER-TENANT p50/p95 from it
                    h.queue_waits.append(
                        {"tenant": h.tenant, "wait_s": round(wait, 6)})
                return head
            if runnable == 0:
                self._work.clear()
            return None

    def _note_dispatch_out(self, handle: SearchHandle, cost: int,
                           wait_s: Optional[float], fastpath: bool,
                           key: str = "") -> None:
        """Fleet-telemetry + flight-recorder dispatch notes — always
        called OUTSIDE the executor lock, so telemetry introduces no
        cross-module lock nesting.  ``wait_s`` is None for fastpath
        dispatches (they never queued; the SLO wait percentiles cover
        routed dispatches only, like the scheduler block's sample)."""
        _telemetry.note_dispatch(handle.tenant, cost, wait_s=wait_s)
        _telemetry.flight_recorder().note(
            "dispatch", handle=handle.id, tenant=handle.tenant,
            cost=cost, key=key,
            wait_s=round(wait_s, 6) if wait_s is not None else 0.0,
            fastpath=fastpath)

    def _run_request(self, req: _Request) -> None:
        if self._fusion and req.item.fuse is not None \
                and not req.handle.cancelled:
            peers = self._claim_fusion_peers(req)
            if peers:
                self._run_fused([req] + peers)
                return
        self._note_dispatch_out(
            req.handle, req.cost,
            max(0.0, req.t_dequeued - req.t_enqueued),
            fastpath=False, key=req.item.key)
        if req.handle.cancelled:
            self._note_done(req.handle, req.state)
            req.reply.set_exception(SearchCancelledError(
                f"search {req.handle.id!r} was cancelled"))
            return
        self._dispatch_solo(req)

    def _dispatch_solo(self, req: _Request) -> None:
        tr = get_tracer()
        t_busy0 = time.perf_counter()
        try:
            with tr.span("sched.dispatch", key=req.item.key,
                         tenant=req.handle.tenant, handle=req.handle.id,
                         cost=req.cost):
                out = req.launch(req.payload)
        # the dispatch loop is a thread boundary: every launch failure
        # (including injected faults) marshals back to the owning
        # search's thread, where the fault supervisor classifies it —
        # nothing is swallowed and other tenants keep dispatching
        # sstlint: disable=broad-except-swallow,launch-except-taxonomy
        except BaseException as exc:
            _telemetry.note_sched_busy(time.perf_counter() - t_busy0)
            req.reply.set_exception(exc)
            return
        _telemetry.note_sched_busy(time.perf_counter() - t_busy0)
        req.reply.set_result(out)

    # -- cross-search launch fusion --------------------------------------
    def _has_fuse_peer_locked(self, head: _Request) -> bool:
        """Is a same-program (equal FuseSpec key) request from another
        live search queued anywhere?  Caller holds the lock."""
        key = head.item.fuse.key
        for t in self._tenants.values():
            for r in t.queue:
                if r is head or r.handle.cancelled:
                    continue
                f = r.item.fuse
                if f is not None and f.key == key:
                    return True
        return False

    def _claim_fusion_peers(self, head: _Request) -> List[_Request]:
        """Pop every queued same-program peer that fits the fused
        width, within DRR credit — each claimed peer gets the exact
        head-equivalent dequeue accounting (dispatch/cost/in-flight
        counters, deficit charge, wait sample), so fair-share ratios
        and the scheduler block stay truthful under fusion."""
        spec = head.item.fuse
        claimed: List[_Request] = []
        now = time.perf_counter()
        with self._lock:
            if self._stop:
                return []
            shard = max(1, int(spec.shard))
            total = int(spec.n)
            bound = int(spec.max_width)   # HBM width ceiling; 0 = none
            for name in sorted(self._tenants):
                t = self._tenants[name]
                if not t.queue:
                    continue
                cap = self._effective_cap(name)
                # the head's tenant already earned its quantum in
                # _pop_next this round — a second top-up here would
                # double its round credit and skew fair share
                topped = name == head.handle.tenant
                for r in list(t.queue):
                    if r is head or r.handle.cancelled:
                        continue
                    f = r.item.fuse
                    if f is None or f.key != spec.key:
                        continue
                    if cap and t.inflight >= cap:
                        break
                    new_total = total + int(f.n)
                    padded = -(-new_total // shard) * shard
                    f_bound = int(f.max_width)
                    limit = min((b for b in (bound, f_bound) if b > 0),
                                default=0)
                    if limit and padded > limit:
                        continue
                    if self._fusion_max_width and \
                            new_total > self._fusion_max_width:
                        continue
                    if t.deficit < r.cost:
                        # same credit law as _pop_next: at most one
                        # quantum top-up per tenant per claim pass
                        if topped:
                            continue
                        topped = True
                        t.deficit += self._quantum * t.weight
                        if t.deficit < r.cost:
                            continue
                    t.queue.remove(r)
                    t.deficit -= r.cost
                    if not t.queue:
                        t.deficit = 0.0   # classic DRR: idle queues reset
                    r.t_dequeued = now
                    self._account_dispatch(r.handle, r.cost)
                    self._count_inflight(r.handle, r.state)
                    wait = r.t_dequeued - r.t_enqueued
                    h = r.handle
                    h.queue_wait_s += wait
                    h.queue_wait_max_s = max(h.queue_wait_max_s, wait)
                    if len(h.queue_waits) < _MAX_WAIT_SAMPLES:
                        h.queue_waits.append(
                            {"tenant": h.tenant,
                             "wait_s": round(wait, 6)})
                    claimed.append(r)
                    total = new_total
                    if f_bound:
                        bound = min(bound, f_bound) if bound else f_bound
        return claimed

    def _run_fused(self, members: List[_Request]) -> None:
        """ONE device launch serving every member's chunk, results
        scattered back per member reply.  A launch failure is delivered
        to every live member: each search's own fault supervisor then
        recovers over only ITS [lo, hi) range (member-boundary-first
        bisection), so one tenant's poison candidate never retries
        another tenant's rows."""
        live: List[_Request] = []
        for r in members:
            self._note_dispatch_out(
                r.handle, r.cost,
                max(0.0, r.t_dequeued - r.t_enqueued),
                fastpath=False, key=r.item.key)
            if r.handle.cancelled:
                # a member cancelled between claim and launch drops out
                # without touching its peers' launch
                self._note_done(r.handle, r.state)
                r.reply.set_exception(SearchCancelledError(
                    f"search {r.handle.id!r} was cancelled"))
            else:
                live.append(r)
        if not live:
            return
        if len(live) == 1:
            # every peer dropped out: the survivor dispatches solo on
            # its own already-staged payload — no fusion accounting
            self._dispatch_solo(live[0])
            return
        fl = FusedLaunch([r.item.fuse for r in live])
        tr = get_tracer()
        t_busy0 = time.perf_counter()
        try:
            with tr.span("sched.fuse", key=live[0].item.key,
                         tenant=live[0].handle.tenant,
                         n_members=len(live), lanes=fl.padded_width(),
                         cost=sum(r.cost for r in live)):
                fl.run()
        # same thread boundary as _dispatch_solo: the failure marshals
        # to EVERY member search's supervisor, each of which recovers
        # over its own candidate range only
        # sstlint: disable=broad-except-swallow,launch-except-taxonomy
        except BaseException as exc:
            _telemetry.note_sched_busy(time.perf_counter() - t_busy0)
            for r in live:
                r.reply.set_exception(exc)
            return
        _telemetry.note_sched_busy(time.perf_counter() - t_busy0)
        head = live[0]
        n_head = int(head.item.fuse.n)
        donated = fl.n_total - n_head
        borrowed: Dict[str, int] = {}
        with self._lock:
            for i, r in enumerate(live):
                r.handle.n_fused += 1
                if i == 0:
                    r.handle.lanes_donated += donated
                    r.handle.fusion_saved_launches += len(live) - 1
                else:
                    n_r = int(r.item.fuse.n)
                    r.handle.lanes_borrowed += n_r
                    borrowed[r.handle.tenant] = \
                        borrowed.get(r.handle.tenant, 0) + n_r
        # telemetry + flight notes outside the lock (hook discipline)
        _telemetry.note_fusion(
            head.handle.tenant, n_members=len(live),
            lanes_total=fl.padded_width(), lanes_real=fl.n_total,
            saved_launches=len(live) - 1, borrowed=borrowed)
        _telemetry.flight_recorder().note(
            "fuse", key=head.item.key, n_members=len(live),
            lanes=fl.padded_width(),
            tenants=[r.handle.tenant for r in live])
        for i, r in enumerate(live):
            r.reply.set_result(fl.member_result(i))

    # -- drain/test aids -------------------------------------------------
    def pause(self) -> None:
        """Hold the dispatch loop (requests keep queueing) — the
        drain/test aid behind deterministic interleave assertions."""
        self._gate.clear()

    def resume(self) -> None:
        self._gate.set()

    def queued_count(self, tenant: Optional[str] = None) -> int:
        with self._lock:
            return sum(len(t.queue) for name, t in self._tenants.items()
                       if tenant is None or name == tenant)

    def dispatch_log(self) -> List[Any]:
        """Bounded (handle id, tenant, cost) journal in dispatch
        order — what the fair-share tests assert ratios from."""
        with self._lock:
            return list(self._dispatch_log)

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "n_active": len(self._active),
                "n_pending": len(self._pending),
                "tenants": {
                    name: {"weight": t.weight, "queued": len(t.queue),
                           "inflight": t.inflight,
                           "cost_total": t.cost_total}
                    for name, t in sorted(self._tenants.items())},
            }

    def telemetry_gauges(self) -> Dict[str, Any]:
        """Sampler provider (obs/telemetry.py): the scheduler gauges
        the fleet endpoint polls — total queue depth plus the
        active/pending search counts."""
        with self._lock:
            return {
                "queue_depth": sum(
                    len(t.queue) for t in self._tenants.values()),
                "n_active": len(self._active),
                "n_pending": len(self._pending),
            }

    # -- reporting -------------------------------------------------------
    def search_block(self, handle: SearchHandle) -> Dict[str, Any]:
        """The search's rendered ``search_report["scheduler"]`` block
        (schema pinned in ``obs.metrics.SCHEDULER_BLOCK_SCHEMA``)."""
        with self._lock:
            self._update_shares(handle)
            n = handle.n_dispatched
            routed = max(0, n - handle.n_fastpath)
            block = {
                "enabled": True,
                "tenant": handle.tenant,
                "handle": handle.id,
                "weight": handle.weight,
                "n_dispatches": n,
                "n_fastpath": handle.n_fastpath,
                "n_interleaved": handle.n_interleaved,
                "interleave_frac": round(
                    handle.n_interleaved / n, 4) if n else 0.0,
                "queue_wait_s": round(handle.queue_wait_s, 4),
                "queue_wait_mean_s": round(
                    handle.queue_wait_s / routed, 6) if routed else 0.0,
                "queue_wait_max_s": round(handle.queue_wait_max_s, 6),
                "share_frac": handle.share_frac,
                "tenant_shares": dict(handle.tenant_shares),
                "waits": [dict(w) for w in handle.queue_waits],
            }
            if self._fusion:
                # fusion keys ride only when fusion is resolved ON —
                # fusion=False (and standalone report_block) blocks
                # stay byte-identical to the pre-fusion engine
                block.update({
                    "n_fused": handle.n_fused,
                    "lanes_donated": handle.lanes_donated,
                    "lanes_borrowed": handle.lanes_borrowed,
                    "fusion_saved_launches":
                        handle.fusion_saved_launches,
                })
            return block

    # -- lifecycle -------------------------------------------------------
    def shutdown(self, wait: bool = True,
                 timeout: Optional[float] = 30.0) -> None:
        """Stop accepting searches, cancel the waiting line, let active
        searches finish (their queued chunks still dispatch), then stop
        the dispatch loop."""
        with self._lock:
            if self._stop or self._closing:
                return
            # reject new submissions NOW; the dispatch loop keeps
            # serving the active searches' queued chunks until their
            # workers finish below
            self._closing = True
            pending = list(self._pending)
            self._pending.clear()
            workers = list(self._workers)
        exc = AdmissionError("executor shut down before the search "
                            "started")
        for handle, future, _ in pending:
            handle.cancelled = True
            handle.state = "cancelled"
            # a queued search cancelled by shutdown is SHED work: the
            # journal marks it terminal so a restart does not re-admit
            # something the operator deliberately drained
            self._journal_note_state(handle, "shed", reason="shutdown")
            future._finish(exc)
        if wait:
            for w in workers:
                w.join(timeout)
        with self._lock:
            self._stop = True
            thread = self._thread
            # drain every still-queued request (a worker that outlived
            # the join timeout, or wait=False): failing its reply beats
            # a dispatch blocked forever on a dead loop
            stranded = []
            for t in self._tenants.values():
                stranded.extend(t.queue)
                t.queue.clear()
        for req in stranded:
            req.reply.set_exception(AdmissionError(
                "executor shut down with the chunk still queued"))
        self._gate.set()
        self._work.set()
        if wait and thread is not None and thread.is_alive():
            thread.join(timeout)

    def __repr__(self) -> str:
        s = self.stats()
        return (f"SearchExecutor({self.name!r}, active={s['n_active']}, "
                f"pending={s['n_pending']}, "
                f"tenants={sorted(s['tenants'])})")


def report_block(binding: Optional[_Binding]) -> Dict[str, Any]:
    """The ``search_report["scheduler"]`` block for a search running
    under ``binding`` — the zeroed ``enabled: False`` shape for a
    standalone fit, so the report schema never changes shape."""
    if binding is None:
        return {
            "enabled": False, "tenant": "", "handle": "", "weight": 0.0,
            "n_dispatches": 0, "n_fastpath": 0, "n_interleaved": 0,
            "interleave_frac": 0.0, "queue_wait_s": 0.0,
            "queue_wait_mean_s": 0.0, "queue_wait_max_s": 0.0,
            "share_frac": 0.0, "tenant_shares": {}, "waits": [],
        }
    return binding.executor.search_block(binding.handle)
