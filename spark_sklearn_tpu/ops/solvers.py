"""Batched, jit/vmap-safe solvers.

The reference never solves anything itself — each Spark task calls
`estimator.fit`, which reaches scipy's L-BFGS / liblinear / libsvm on a CPU
executor (reference: grid_search.py -> sklearn _fit_and_score -> est.fit).
On TPU the solver must BE the program: fixed-shape, static control flow, no
Python in the loop, batchable with `vmap` over hyperparameter candidates so
the MXU sees one big batched problem instead of thousands of small ones.

`lbfgs` is a limited-memory BFGS with rolling history buffers and an Armijo
backtracking line search, written entirely with `lax.while_loop`/`fori_loop`
so that XLA compiles one program per (shape, max_iter) and `vmap` lifts it
over candidates (a batched while_loop runs until every lane converges).
"""

from __future__ import annotations

from functools import partial
from typing import Callable, NamedTuple

import jax
import jax.numpy as jnp
from jax import lax


class LBFGSResult(NamedTuple):
    x: jnp.ndarray
    fun: jnp.ndarray
    grad_norm: jnp.ndarray
    n_iter: jnp.ndarray
    converged: jnp.ndarray


def _two_loop(g, s_mem, y_mem, rho, gamma, total, n_valid, m):
    """Two-loop recursion over a rolling history buffer.

    `total` is the number of pairs ever inserted (ring head = total % m);
    `n_valid = min(total, m)`.  Slot `(total - 1 - i) % m` holds the i-th most
    recent pair; slots with i >= n_valid are masked out so the same program
    serves warmup and steady state.
    """

    def bwd(i, carry):
        q, alpha = carry
        idx = jnp.mod(total - 1 - i, m)
        valid = i < n_valid
        a = rho[idx] * jnp.dot(s_mem[idx], q)
        a = jnp.where(valid, a, 0.0)
        q = q - a * y_mem[idx]
        alpha = alpha.at[idx].set(a)
        return q, alpha

    q, alpha = lax.fori_loop(0, m, bwd, (g, jnp.zeros((m,), g.dtype)))
    r = gamma * q

    def fwd(i, r):
        idx = jnp.mod(total - n_valid + i, m)
        valid = i < n_valid
        b = rho[idx] * jnp.dot(y_mem[idx], r)
        corr = (alpha[idx] - b) * s_mem[idx]
        return r + jnp.where(valid, corr, 0.0)

    r = lax.fori_loop(0, m, fwd, r)
    return -r


@partial(jax.jit, static_argnums=(0, 2, 4, 6))
def lbfgs(
    fun: Callable,
    x0: jnp.ndarray,
    max_iter: int = 100,
    tol: float = 1e-4,
    history: int = 10,
    c1: float = 1e-4,
    ls_max: int = 30,
) -> LBFGSResult:
    """Minimise `fun(x) -> scalar` from flat `x0`.

    Matches the role scipy's lbfgs plays for sklearn's LogisticRegression
    (sum-loss objective, gradient-infinity-norm stopping at `tol`).
    """
    m = history
    d = x0.shape[0]
    dtype = x0.dtype
    vg = jax.value_and_grad(fun)
    f0, g0 = vg(x0)

    state = dict(
        x=x0, f=f0, g=g0,
        s_mem=jnp.zeros((m, d), dtype),
        y_mem=jnp.zeros((m, d), dtype),
        rho=jnp.zeros((m,), dtype),
        gamma=jnp.asarray(1.0, dtype),
        n_valid=jnp.asarray(0, jnp.int32),
        it=jnp.asarray(0, jnp.int32),
    )

    def gnorm(g):
        return jnp.max(jnp.abs(g))

    def cond(st):
        return jnp.logical_and(st["it"] < max_iter, gnorm(st["g"]) > tol)

    def body(st):
        x, f, g = st["x"], st["f"], st["g"]
        p = _two_loop(g, st["s_mem"], st["y_mem"], st["rho"], st["gamma"],
                      st["n_valid"], jnp.minimum(st["n_valid"], m), m)
        dginit = jnp.dot(g, p)
        # fall back to steepest descent if the direction lost descent-ness
        bad = dginit >= 0
        p = jnp.where(bad, -g, p)
        dginit = jnp.where(bad, -jnp.dot(g, g), dginit)

        # first step: scale so the initial trial is modest
        a0 = jnp.where(
            st["it"] == 0,
            jnp.minimum(jnp.asarray(1.0, dtype),
                        1.0 / (gnorm(g) + jnp.finfo(dtype).eps)),
            jnp.asarray(1.0, dtype),
        )

        def ls_cond(carry):
            alpha, k, fnew = carry
            armijo = fnew <= f + c1 * alpha * dginit
            return jnp.logical_and(k < ls_max, jnp.logical_not(armijo))

        def ls_body(carry):
            alpha, k, _ = carry
            alpha = alpha * 0.5
            return alpha, k + 1, fun(x + alpha * p)

        alpha, _, _ = lax.while_loop(
            ls_cond, ls_body, (a0, jnp.asarray(0, jnp.int32), fun(x + a0 * p)))

        x_new = x + alpha * p
        f_new, g_new = vg(x_new)
        # reject non-finite steps outright (error_score semantics handle the
        # rest at the search layer)
        ok = jnp.isfinite(f_new)
        x_new = jnp.where(ok, x_new, x)
        f_new = jnp.where(ok, f_new, f)
        g_new = jnp.where(ok, g_new, g)

        s = x_new - x
        yv = g_new - g
        sy = jnp.dot(s, yv)
        update = sy > 1e-10
        head = jnp.mod(st["n_valid"], m)
        s_mem = jnp.where(update, st["s_mem"].at[head].set(s), st["s_mem"])
        y_mem = jnp.where(update, st["y_mem"].at[head].set(yv), st["y_mem"])
        rho = jnp.where(update, st["rho"].at[head].set(1.0 / sy), st["rho"])
        gamma = jnp.where(update, sy / (jnp.dot(yv, yv) + jnp.finfo(dtype).eps),
                          st["gamma"])
        n_valid = jnp.where(update, st["n_valid"] + 1, st["n_valid"])

        return dict(x=x_new, f=f_new, g=g_new, s_mem=s_mem, y_mem=y_mem,
                    rho=rho, gamma=gamma, n_valid=n_valid, it=st["it"] + 1)

    st = lax.while_loop(cond, body, state)
    return LBFGSResult(
        x=st["x"], fun=st["f"], grad_norm=gnorm(st["g"]), n_iter=st["it"],
        converged=gnorm(st["g"]) <= tol)
