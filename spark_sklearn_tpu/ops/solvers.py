"""Batched, jit/vmap-safe solvers.

The reference never solves anything itself — each Spark task calls
`estimator.fit`, which reaches scipy's L-BFGS / liblinear / libsvm on a CPU
executor (reference: grid_search.py -> sklearn _fit_and_score -> est.fit).
On TPU the solver must BE the program: fixed-shape, static control flow, no
Python in the loop, batchable with `vmap` over hyperparameter candidates so
the MXU sees one big batched problem instead of thousands of small ones.

`lbfgs` is a limited-memory BFGS with rolling history buffers and an Armijo
backtracking line search, written entirely with `lax.while_loop`/`fori_loop`
so that XLA compiles one program per (shape, max_iter) and `vmap` lifts it
over candidates (a batched while_loop runs until every lane converges).
"""

from __future__ import annotations

from functools import partial
from typing import Callable, NamedTuple

import jax
import jax.numpy as jnp
from jax import lax


class LBFGSResult(NamedTuple):
    x: jnp.ndarray
    fun: jnp.ndarray
    grad_norm: jnp.ndarray
    n_iter: jnp.ndarray
    converged: jnp.ndarray


def _two_loop(g, s_mem, y_mem, rho, gamma, total, n_valid, m):
    """Two-loop recursion over a rolling history buffer.

    `total` is the number of pairs ever inserted (ring head = total % m);
    `n_valid = min(total, m)`.  Slot `(total - 1 - i) % m` holds the i-th most
    recent pair; slots with i >= n_valid are masked out so the same program
    serves warmup and steady state.
    """

    def bwd(i, carry):
        q, alpha = carry
        idx = jnp.mod(total - 1 - i, m)
        valid = i < n_valid
        a = rho[idx] * jnp.dot(s_mem[idx], q)
        a = jnp.where(valid, a, 0.0)
        q = q - a * y_mem[idx]
        alpha = alpha.at[idx].set(a)
        return q, alpha

    q, alpha = lax.fori_loop(0, m, bwd, (g, jnp.zeros((m,), g.dtype)))
    r = gamma * q

    def fwd(i, r):
        idx = jnp.mod(total - n_valid + i, m)
        valid = i < n_valid
        b = rho[idx] * jnp.dot(y_mem[idx], r)
        corr = (alpha[idx] - b) * s_mem[idx]
        return r + jnp.where(valid, corr, 0.0)

    r = lax.fori_loop(0, m, fwd, r)
    return -r


@partial(jax.jit, static_argnums=(0, 2, 4, 6))
def lbfgs(
    fun: Callable,
    x0: jnp.ndarray,
    max_iter: int = 100,
    tol: float = 1e-4,
    history: int = 10,
    c1: float = 1e-4,
    ls_max: int = 30,
) -> LBFGSResult:
    """Minimise `fun(x) -> scalar` from flat `x0`.

    Matches the role scipy's lbfgs plays for sklearn's LogisticRegression
    (sum-loss objective, gradient-infinity-norm stopping at `tol`).
    """
    m = history
    d = x0.shape[0]
    dtype = x0.dtype
    vg = jax.value_and_grad(fun)
    f0, g0 = vg(x0)

    state = dict(
        x=x0, f=f0, g=g0,
        s_mem=jnp.zeros((m, d), dtype),
        y_mem=jnp.zeros((m, d), dtype),
        rho=jnp.zeros((m,), dtype),
        gamma=jnp.asarray(1.0, dtype),
        n_valid=jnp.asarray(0, jnp.int32),
        it=jnp.asarray(0, jnp.int32),
    )

    def gnorm(g):
        return jnp.max(jnp.abs(g))

    def cond(st):
        return jnp.logical_and(st["it"] < max_iter, gnorm(st["g"]) > tol)

    def body(st):
        x, f, g = st["x"], st["f"], st["g"]
        p = _two_loop(g, st["s_mem"], st["y_mem"], st["rho"], st["gamma"],
                      st["n_valid"], jnp.minimum(st["n_valid"], m), m)
        dginit = jnp.dot(g, p)
        # fall back to steepest descent if the direction lost descent-ness
        bad = dginit >= 0
        p = jnp.where(bad, -g, p)
        dginit = jnp.where(bad, -jnp.dot(g, g), dginit)

        # first step: scale so the initial trial is modest
        a0 = jnp.where(
            st["it"] == 0,
            jnp.minimum(jnp.asarray(1.0, dtype),
                        1.0 / (gnorm(g) + jnp.finfo(dtype).eps)),
            jnp.asarray(1.0, dtype),
        )

        def ls_cond(carry):
            alpha, k, fnew = carry
            armijo = fnew <= f + c1 * alpha * dginit
            return jnp.logical_and(k < ls_max, jnp.logical_not(armijo))

        def ls_body(carry):
            alpha, k, _ = carry
            alpha = alpha * 0.5
            return alpha, k + 1, fun(x + alpha * p)

        alpha, _, _ = lax.while_loop(
            ls_cond, ls_body, (a0, jnp.asarray(0, jnp.int32), fun(x + a0 * p)))

        x_new = x + alpha * p
        f_new, g_new = vg(x_new)
        # reject non-finite steps outright (error_score semantics handle the
        # rest at the search layer)
        ok = jnp.isfinite(f_new)
        x_new = jnp.where(ok, x_new, x)
        f_new = jnp.where(ok, f_new, f)
        g_new = jnp.where(ok, g_new, g)

        s = x_new - x
        yv = g_new - g
        sy = jnp.dot(s, yv)
        update = sy > 1e-10
        head = jnp.mod(st["n_valid"], m)
        s_mem = jnp.where(update, st["s_mem"].at[head].set(s), st["s_mem"])
        y_mem = jnp.where(update, st["y_mem"].at[head].set(yv), st["y_mem"])
        rho = jnp.where(update, st["rho"].at[head].set(1.0 / sy), st["rho"])
        gamma = jnp.where(update, sy / (jnp.dot(yv, yv) + jnp.finfo(dtype).eps),
                          st["gamma"])
        n_valid = jnp.where(update, st["n_valid"] + 1, st["n_valid"])

        return dict(x=x_new, f=f_new, g=g_new, s_mem=s_mem, y_mem=y_mem,
                    rho=rho, gamma=gamma, n_valid=n_valid, it=st["it"] + 1)

    st = lax.while_loop(cond, body, state)
    return LBFGSResult(
        x=st["x"], fun=st["f"], grad_norm=gnorm(st["g"]), n_iter=st["it"],
        converged=gnorm(st["g"]) <= tol)


def glm_lbfgs_batched(
    Ax: Callable,          # x (B,D) -> Z (n, B) or (n, B, k)  ONE matmul
                           # (lane axis MUST be position 1 — see _bcast)
    data_loss: Callable,   # Z                  -> (B,)   elementwise+reduce
    data_grad: Callable,   # Z                  -> dL/dZ  elementwise
    AT: Callable,          # dL/dZ              -> (B,D)  ONE matmul
    reg_loss: Callable,    # x (B,D)            -> (B,)
    reg_grad: Callable,    # x (B,D)            -> (B,D)
    x0: jnp.ndarray,
    max_iter: int = 100,
    tol=1e-4,
    history: int = 10,
    c1: float = 1e-4,
    ls_trials: int = 16,
) -> LBFGSResult:
    """L-BFGS for batched GLMs: objective f(x) = data_loss(A(x)) + reg(x)
    with A *linear* in x.

    The TPU-shaped trick: logits are linear in the parameters, so along a
    search direction p the logits move as Z(x + a*p) = Zx + a*Zp.  Carrying
    Zx in the solver state means one iteration costs exactly TWO wide
    matmuls — Ax(p) forward and AT(dL/dZ) backward — and the whole
    backtracking line search is ONE fused elementwise pass: all
    `ls_trials` candidate steps evaluate together (vmap over the trial
    axis reads Z/Zp once), and each lane keeps its largest
    Armijo-passing step.  Measured on the 1000-candidate digits grid
    this layout is ~12x over a generic batched L-BFGS (whose line search
    re-evaluates full losses sequentially) and far over vmapping the
    scalar solver.
    """
    m = history
    B, D = x0.shape
    dtype = x0.dtype
    eps = jnp.finfo(dtype).eps
    tol = jnp.broadcast_to(jnp.asarray(tol, dtype), (B,))

    def full_grad(x, Z):
        return AT(data_grad(Z)) + reg_grad(x)

    def full_f(x, Z):
        return data_loss(Z) + reg_loss(x)

    Z0 = Ax(x0)
    f0 = full_f(x0, Z0)
    g0 = full_grad(x0, Z0)

    state = dict(
        x=x0, Z=Z0, f=f0, g=g0,
        s_mem=jnp.zeros((m, B, D), dtype),
        y_mem=jnp.zeros((m, B, D), dtype),
        rho=jnp.zeros((m, B), dtype),
        gamma=jnp.ones((B,), dtype),
        it=jnp.asarray(0, jnp.int32),
        done=jnp.zeros((B,), bool),
        stall=jnp.zeros((B,), jnp.int32),
    )

    def gnorm(g):
        return jnp.max(jnp.abs(g), axis=1)

    def cond(st):
        return jnp.logical_and(st["it"] < max_iter,
                               jnp.logical_not(jnp.all(st["done"])))

    def body(st):
        x, Z, f, g, it = st["x"], st["Z"], st["f"], st["g"], st["it"]
        n_hist = jnp.minimum(it, m)

        def bwd(i, carry):
            q, alpha = carry
            idx = jnp.mod(it - 1 - i, m)
            s_i = lax.dynamic_index_in_dim(st["s_mem"], idx, 0, False)
            y_i = lax.dynamic_index_in_dim(st["y_mem"], idx, 0, False)
            rho_i = lax.dynamic_index_in_dim(st["rho"], idx, 0, False)
            a = jnp.where(i < n_hist,
                          rho_i * jnp.sum(s_i * q, axis=1), 0.0)
            q = q - a[:, None] * y_i
            return q, alpha.at[i].set(a)

        q, alpha_rec = lax.fori_loop(
            0, m, bwd, (g, jnp.zeros((m, B), dtype)))
        r = st["gamma"][:, None] * q

        def fwd(i, r):
            j = m - 1 - i
            idx = jnp.mod(it - 1 - j, m)
            s_i = lax.dynamic_index_in_dim(st["s_mem"], idx, 0, False)
            y_i = lax.dynamic_index_in_dim(st["y_mem"], idx, 0, False)
            rho_i = lax.dynamic_index_in_dim(st["rho"], idx, 0, False)
            b = rho_i * jnp.sum(y_i * r, axis=1)
            corr = (alpha_rec[j] - b)[:, None] * s_i
            return r + jnp.where(j < n_hist, 1.0, 0.0) * corr

        r = lax.fori_loop(0, m, fwd, r)
        p = -r

        dginit = jnp.sum(g * p, axis=1)
        bad = dginit >= 0
        p = jnp.where(bad[:, None], -g, p)
        dginit = jnp.where(bad, -jnp.sum(g * g, axis=1), dginit)
        # a lane whose direction went non-finite (overflowed gradient or
        # history) is frozen this iteration: p=0 keeps x/Z exact under
        # x + alpha*p, where alpha*non-finite would be NaN and poison the
        # state (the pre-step-masking code preserved the last finite
        # iterate with where()-guards; this keeps that guarantee)
        lane_bad = jnp.logical_not(jnp.logical_and(
            jnp.all(jnp.isfinite(p), axis=1), jnp.isfinite(dginit)))
        p = jnp.where(lane_bad[:, None], 0.0, p)
        dginit = jnp.where(lane_bad, 0.0, dginit)

        a0 = jnp.where(
            it == 0,
            jnp.minimum(jnp.ones((B,), dtype), 1.0 / (gnorm(g) + eps)),
            jnp.ones((B,), dtype))

        # --- matmul-free, single-pass backtracking line search ------------
        # Z moves linearly along p, so a trial is elementwise on
        # Zx + a*Zp.  A sequential halving loop with an all-lanes early
        # exit is a trap at large B: ONE stubborn lane forces EVERY lane
        # through all trials, each a full Z-sized memory pass (profiled at
        # ~14 passes/iteration on the 5000-lane digits grid — line search
        # was most of the solver).  Instead evaluate ALL ls_trials
        # candidate steps in one fused pass: vmap over the trial axis
        # turns the halvings into register-level compute over a single
        # read of (Z, Zp), then each lane picks its largest passing step.
        Zp = Ax(p)                                   # the ONE forward matmul

        def eval_trial(a):
            Zt = Z + _bcast(a, Z) * Zp
            return data_loss(Zt) + reg_loss(x + a[:, None] * p)

        halvings = 0.5 ** jnp.arange(ls_trials, dtype=dtype)
        alphas = a0[None, :] * halvings[:, None]            # (T, B)
        losses = jax.vmap(eval_trial)(alphas)               # (T, B)
        armijo = losses <= f[None, :] + c1 * alphas * dginit[None, :]
        # first (largest-step) passing trial per lane; no trial passed ->
        # take the last (smallest) step rather than stall
        first_ok = jnp.argmax(armijo, axis=0)               # (B,)
        found = jnp.any(armijo, axis=0)
        pick = jnp.where(found, first_ok, ls_trials - 1)
        alpha = jnp.take_along_axis(alphas, pick[None, :], axis=0)[0]
        f_pick = jnp.take_along_axis(losses, pick[None, :], axis=0)[0]

        # mask the STEP, not the state: dead lanes (done, or a non-finite
        # trial loss) take alpha=0, so x_new == x and Z_new == Z exactly
        # and g_new recomputes to the same value — no Z-sized select
        # passes (profiled at ~4ms/iteration of pure bandwidth)
        live = jnp.logical_and(jnp.isfinite(f_pick),
                               jnp.logical_not(st["done"]))
        alpha = jnp.where(live, alpha, 0.0)
        x_new = x + alpha[:, None] * p
        Z_new = Z + _bcast(alpha, Z) * Zp
        # the picked trial's loss IS full_f(x_new, Z_new): reuse, no pass
        f_new = jnp.where(live, f_pick, f)
        g_new = full_grad(x_new, Z_new)              # the ONE backward matmul

        s = x_new - x
        yv = g_new - g
        sy = jnp.sum(s * yv, axis=1)
        update = jnp.logical_and(sy > 1e-10, live)
        slot = jnp.mod(it, m)
        s_mem = lax.dynamic_update_index_in_dim(
            st["s_mem"], jnp.where(update[:, None], s, 0.0), slot, 0)
        y_mem = lax.dynamic_update_index_in_dim(
            st["y_mem"], jnp.where(update[:, None], yv, 0.0), slot, 0)
        rho = lax.dynamic_update_index_in_dim(
            st["rho"],
            jnp.where(update, 1.0 / jnp.where(sy > 1e-10, sy, 1.0), 0.0),
            slot, 0)
        gamma = jnp.where(update,
                          sy / (jnp.sum(yv * yv, axis=1) + eps),
                          st["gamma"])
        # float32 stall detector: the sum-loss gradient has a rounding
        # floor that often sits ABOVE tol (n terms x eps32), so the tol
        # exit alone can be unreachable and every lane burns max_iter.
        # A lane whose relative objective improvement stays below ~eps32
        # for 3 consecutive iterations has hit that floor — its iterate
        # is pinned by rounding, and the remaining lockstep iterations
        # are pure waste.  (Safe for the strongly-convex GLM objectives
        # this solver serves: genuine progress never hides behind
        # consecutive sub-eps steps.)
        rel_impr = (f - f_new) / jnp.maximum(jnp.abs(f), eps)
        stall = jnp.where(jnp.logical_and(live, rel_impr <= eps),
                          st["stall"] + 1, 0)
        done = jnp.logical_or(
            st["done"],
            jnp.logical_or(gnorm(g_new) <= tol, stall >= 3))
        return dict(x=x_new, Z=Z_new, f=f_new, g=g_new, s_mem=s_mem,
                    y_mem=y_mem, rho=rho, gamma=gamma, it=it + 1,
                    done=done, stall=stall)

    st = lax.while_loop(cond, body, state)
    gn = jnp.max(jnp.abs(st["g"]), axis=1)
    return LBFGSResult(
        x=st["x"], fun=st["f"], grad_norm=gn,
        n_iter=jnp.broadcast_to(st["it"], (B,)), converged=gn <= tol)


def glm_fista_batched(
    Ax: Callable,          # x (B,D) -> Z (n, B) or (n, B, k)  ONE matmul
    data_loss: Callable,   # Z -> (B,)
    data_grad: Callable,   # Z -> dL/dZ
    AT: Callable,          # dL/dZ -> (B,D)  ONE matmul
    l1: jnp.ndarray,       # (B, D) per-coefficient l1 weights (0 = none)
    l2: jnp.ndarray,       # (B, D) per-coefficient l2 weights
    x0: jnp.ndarray,
    max_iter: int = 1000,
    tol=1e-4,
    curvature: float = 0.25,
) -> LBFGSResult:
    """Proximal FISTA for batched GLMs with elastic-net penalties.

    Covers the l1/elasticnet logistic regressions L-BFGS cannot (soft
    thresholding handles the non-smooth term).  Same TPU shape as
    `glm_lbfgs_batched`: logits move linearly along the momentum
    extrapolation (Z_v = Z_x + beta*(Z_x - Z_prev) — no matmul), so one
    iteration costs exactly TWO wide matmuls: the gradient pullback
    AT(dL/dZ(Z_v)) and the fresh Ax(x_new) after the prox step.

    Step size 1/L with L = curvature*lambda_max(A^T A) + max(l2):
    `curvature` bounds the data-loss hessian's per-sample scale (0.25 for
    binary logistic; 0.5 for softmax, whose diag(p)-pp^T has eigenvalues
    <= 1/2).  Fold weights w <= 1 only shrink the true constant, so the
    unweighted Gram bound stays safe.  Estimated per lane by power
    iteration through Ax/AT.
    """
    B, D = x0.shape
    dtype = x0.dtype
    tol = jnp.broadcast_to(jnp.asarray(tol, dtype), (B,))

    # per-lane Lipschitz bound via power iteration on x -> AT(0.25*Ax(x)):
    # 0.25*A^T A dominates the logistic hessian A^T W'' A (w'' <= 0.25)
    def power(i, v):
        u = AT(0.25 * Ax(v))
        nrm = jnp.sqrt(jnp.sum(u * u, axis=1, keepdims=True)) + 1e-30
        return u / nrm

    v0 = jnp.ones((B, D), dtype) / jnp.sqrt(D)
    v = lax.fori_loop(0, 20, power, v0)
    u = AT(0.25 * Ax(v))
    L = jnp.sqrt(jnp.sum(u * u, axis=1)) + jnp.max(l2, axis=1) + 1e-6
    step = (1.0 / L)[:, None]                               # (B, 1)

    def soft(u_, t_):
        return jnp.sign(u_) * jnp.maximum(jnp.abs(u_) - t_, 0.0)

    def body(carry):
        x, x_prev, Zx, Zx_prev, t, it, done = carry
        t_next = 0.5 * (1.0 + jnp.sqrt(1.0 + 4.0 * t * t))
        beta = (t - 1.0) / t_next
        v_pt = x + beta * (x - x_prev)
        Zv = Zx + beta * (Zx - Zx_prev)   # logits are linear in params
        g = AT(data_grad(Zv)) + l2 * v_pt
        x_new = soft(v_pt - step * g, step * l1)
        Zx_new = Ax(x_new)                                  # ONE matmul
        shift = jnp.max(jnp.abs(x_new - x), axis=1)
        done_new = jnp.logical_or(done, shift <= tol)
        x_new = jnp.where(done[:, None], x, x_new)
        Zx_new = jnp.where(_bcast(done, Zx), Zx, Zx_new)
        return (x_new, x, Zx_new, Zx, t_next, it + 1, done_new)

    def cond(carry):
        *_, it, done = carry
        return jnp.logical_and(it < max_iter,
                               jnp.logical_not(jnp.all(done)))

    Z0 = Ax(x0)
    x, _, Zx, _, _, n_iter, done = lax.while_loop(
        cond, body,
        (x0, x0, Z0, Z0, jnp.asarray(1.0, dtype),
         jnp.asarray(0, jnp.int32), jnp.zeros((B,), bool)))
    f = data_loss(Zx) + jnp.sum(l1 * jnp.abs(x) + 0.5 * l2 * x * x, axis=1)
    return LBFGSResult(
        x=x, fun=f, grad_norm=jnp.zeros((B,), dtype),
        n_iter=jnp.broadcast_to(n_iter, (B,)), converged=done)


def _bcast(v, like):
    """(B,) -> broadcastable against Z.

    CONTRACT: Ax must put the lane axis at position 1 — Z is (n, B) or
    (n, B, k).  Shape-based guessing is forbidden (n can equal B)."""
    if like.ndim == 3:        # (n, B, k)
        return v[None, :, None]
    return v[None, :]         # (n, B)
