from spark_sklearn_tpu.ops.solvers import lbfgs, LBFGSResult
