"""Binned, level-wise decision-tree growth as fixed-shape XLA programs.

The reference runs sklearn's exact-split CART (Cython, per-node sorted
scans) inside Spark tasks.  Exact splitting is a data-dependent, pointer-
chasing algorithm with no MXU mapping, so the TPU redesign uses the
histogram method every modern GBDT uses (LightGBM/XGBoost-style), which is
all segment-sums and cumulative sums over fixed shapes:

  - features are pre-binned host-side to uint8 codes (native quantile_bin,
    see native/tpusk_native.cpp);
  - a tree grows level-by-level (static python loop over max_depth): one
    `segment_sum` builds the (node, feature, bin) gradient/hessian
    histograms for the whole level at once, a cumsum turns them into
    left/right split statistics, and the best (feature, bin) per node is an
    argmax — no per-node control flow;
  - nodes live in a heap-indexed array (children of i at 2i+1/2i+2) so the
    tree is a pytree of fixed arrays: feat, thresh_bin, leaf flag, value.

Leaf values are Newton steps -G/(H+lambda) (squared loss: mean residual),
which reproduces sklearn's mean-of-leaf behavior for regression and the
one-hot-target trick approximates gini for classification forests.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np


class Tree(NamedTuple):
    feat: jnp.ndarray        # (max_nodes,) int32, -1 = leaf/unused
    thresh: jnp.ndarray      # (max_nodes,) int32 bin threshold (go left if
                             # code <= thresh)
    value: jnp.ndarray       # (max_nodes, n_out) leaf values
    is_leaf: jnp.ndarray     # (max_nodes,) bool


def grow_tree(codes, g, h, w, max_depth, n_bins, min_child_weight=1e-3,
              reg_lambda=1.0, feat_mask_key=None, max_features=None,
              n_out=1):
    """Grow one tree on binned features.

    codes: (n, d) int32 bin codes.  g/h: (n, n_out)/(n,) gradient & hessian
    per sample (hessian shared across outputs).  w: (n,) sample weights
    (0 excludes — CV fold masks and bootstrap weights both enter here).
    Returns a Tree whose value column holds the Newton leaf step per output.
    """
    n, d = codes.shape
    max_nodes = 2 ** (max_depth + 1) - 1
    n_level_max = 2 ** max_depth

    feat = jnp.full((max_nodes,), -1, jnp.int32)
    thresh = jnp.zeros((max_nodes,), jnp.int32)
    is_leaf = jnp.zeros((max_nodes,), bool)

    gw = g * w[:, None]                       # (n, n_out)
    hw = h * w                                # (n,)
    node = jnp.zeros((n,), jnp.int32)         # current node per sample
    frozen = jnp.zeros((n,), bool)            # sample sits in a leaf

    for level in range(max_depth):
        n_nodes = 2 ** level
        offset = n_nodes - 1
        local = node - offset                 # (n,) 0..n_nodes-1

        # (node, feature, bin) histograms in one segment-sum per stat
        ids = (local[:, None] * d + jnp.arange(d, dtype=jnp.int32)[None, :]
               ) * n_bins + codes             # (n, d)
        ids = jnp.where(frozen[:, None], 0, ids)
        num_seg = n_nodes * d * n_bins
        live = jnp.logical_not(frozen)

        def hist(v):                          # v: (n,)
            vals = jnp.where(live, v, 0.0)
            flat = jnp.broadcast_to(vals[:, None], (n, d)).reshape(-1)
            return jax.ops.segment_sum(
                flat, ids.reshape(-1), num_segments=num_seg
            ).reshape(n_nodes, d, n_bins)

        Hh = hist(hw)                                       # hessians
        cum_h = jnp.cumsum(Hh, axis=2)
        tot_h = cum_h[..., -1:]
        left_h = cum_h
        right_h = tot_h - left_h

        # gain summed over outputs (multi-output = one-hot targets: the sum
        # is the full variance-reduction criterion, not just class 0's)
        gain = jnp.zeros_like(cum_h)
        for o in range(n_out):
            cum_g = jnp.cumsum(hist(gw[:, o]), axis=2)
            tot_g = cum_g[..., -1:]
            left_g = cum_g
            right_g = tot_g - left_g
            gain = gain + (left_g ** 2 / (left_h + reg_lambda)
                           + right_g ** 2 / (right_h + reg_lambda)
                           - tot_g ** 2 / (tot_h + reg_lambda))
        ok = (left_h >= min_child_weight) & (right_h >= min_child_weight)
        gain = jnp.where(ok, gain, -jnp.inf)
        # never split on the last bin (empty right side by construction)
        gain = gain.at[..., -1].set(-jnp.inf)

        if feat_mask_key is not None and max_features is not None and \
                max_features < d:
            # per-(node) random feature subset, fresh every level — the
            # forest analog of sklearn's per-split max_features
            k_lvl = jax.random.fold_in(feat_mask_key, level)
            scores = jax.random.uniform(k_lvl, (n_nodes, d))
            kth = jnp.sort(scores, axis=1)[:, max_features - 1][:, None]
            fmask = scores <= kth
            gain = jnp.where(fmask[:, :, None], gain, -jnp.inf)

        flat_gain = gain.reshape(n_nodes, d * n_bins)
        best = jnp.argmax(flat_gain, axis=1)                # (n_nodes,)
        best_gain = jnp.take_along_axis(
            flat_gain, best[:, None], axis=1)[:, 0]
        bf = (best // n_bins).astype(jnp.int32)
        bb = (best % n_bins).astype(jnp.int32)
        do_split = best_gain > 1e-7

        node_ids = offset + jnp.arange(n_nodes)
        feat = feat.at[node_ids].set(jnp.where(do_split, bf, -1))
        thresh = thresh.at[node_ids].set(bb)
        is_leaf = is_leaf.at[node_ids].set(jnp.logical_not(do_split))

        # route samples
        nf = bf[local]                         # (n,) feature per sample
        code_at = jnp.take_along_axis(codes, nf[:, None], axis=1)[:, 0]
        go_right = code_at > bb[local]
        splitting = do_split[local] & jnp.logical_not(frozen)
        node = jnp.where(splitting,
                         2 * node + 1 + go_right.astype(jnp.int32), node)
        frozen = frozen | jnp.logical_not(do_split[local])

    # everything still unfrozen at the last level is a leaf
    is_leaf = is_leaf.at[node].set(True)

    # leaf values: Newton step per output, aggregated at the final node ids
    sum_h = jax.ops.segment_sum(hw, node, num_segments=max_nodes)
    value = []
    for o in range(n_out):
        sum_g = jax.ops.segment_sum(gw[:, o], node, num_segments=max_nodes)
        value.append(-sum_g / (sum_h + reg_lambda))
    value = jnp.stack(value, axis=1)           # (max_nodes, n_out)
    return Tree(feat=feat, thresh=thresh, value=value, is_leaf=is_leaf)


def predict_tree(tree: Tree, codes, max_depth):
    """(n, d) codes -> (n, n_out) leaf values (vectorised level walk)."""
    n = codes.shape[0]
    node = jnp.zeros((n,), jnp.int32)
    for _ in range(max_depth):
        f = tree.feat[node]
        stop = tree.is_leaf[node] | (f < 0)
        code_at = jnp.take_along_axis(
            codes, jnp.maximum(f, 0)[:, None], axis=1)[:, 0]
        go_right = code_at > tree.thresh[node]
        nxt = 2 * node + 1 + go_right.astype(jnp.int32)
        node = jnp.where(stop, node, nxt)
    return tree.value[node]                    # (n, n_out)
