"""Crash-safe file publication — the one hardened write idiom.

``tmp + flush + fsync + os.replace``: concurrent writers of one path
each replace with a complete file, last writer wins, and no reader
ever sees a torn file.  Extracted from the program store's artifact
writer (PR 6) so the flight recorder's black-box bundles — written
mid-incident, exactly when a crash is most likely — share the same
guarantees instead of a drifting hand-rolled copy.

stdlib-only on purpose: both ``parallel/programstore.py`` and
``obs/telemetry.py`` import it, so it must sit below both.
"""

from __future__ import annotations

import os

__all__ = ["atomic_write", "fsync_dir"]


def fsync_dir(directory: str) -> None:
    """fsync a directory so a just-renamed entry survives power loss.

    ``os.replace`` makes the rename atomic against concurrent readers,
    but the *directory entry* itself is only durable once the parent
    directory's metadata reaches disk — without this a machine that
    loses power right after the rename can come back with the old name
    (or no file at all).  Best-effort: some filesystems/platforms
    refuse ``open(dir)``/``fsync(dirfd)``, and durability hardening
    must never turn a successful publish into an error.
    """
    try:
        fd = os.open(directory or ".", os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def atomic_write(path: str, payload: bytes) -> None:
    """Atomically publish ``payload`` at ``path`` (see module doc)."""
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "wb") as f:
            f.write(payload)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        fsync_dir(os.path.dirname(os.path.abspath(path)))
    finally:
        if os.path.exists(tmp):
            os.remove(tmp)
