"""Crash-safe file publication — the one hardened write idiom.

``tmp + flush + fsync + os.replace``: concurrent writers of one path
each replace with a complete file, last writer wins, and no reader
ever sees a torn file.  Extracted from the program store's artifact
writer (PR 6) so the flight recorder's black-box bundles — written
mid-incident, exactly when a crash is most likely — share the same
guarantees instead of a drifting hand-rolled copy.

stdlib-only on purpose: both ``parallel/programstore.py`` and
``obs/telemetry.py`` import it, so it must sit below both.
"""

from __future__ import annotations

import os

__all__ = ["atomic_write"]


def atomic_write(path: str, payload: bytes) -> None:
    """Atomically publish ``payload`` at ``path`` (see module doc)."""
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "wb") as f:
            f.write(payload)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            os.remove(tmp)
