"""Cache-key surfaces + the opt-in runtime key-flow recorder.

The engine's headline contract — bit-exact ``cv_results_`` across the
program cache, the persistent program store, cross-search launch
fusion, scan segments, prefix reuse and kill-resume — rests on one
invariant: *everything that influences a traced program must join the
key that caches it*.  :data:`KEY_SURFACES` is the single declared map
of those key surfaces; two consumers build on it:

  - ``tools/sstlint`` (the ``keyflow`` checker) loads this module
    import-light (no jax) and statically proves, per registered
    surface, that every ``TpuConfig`` read reaching a traced closure
    flows into the matching key (``key-part-missing``) and that no key
    part is dead weight nobody reads (``key-part-dead``);
  - under ``SST_KEYCHECK=1`` (mirroring ``SST_LOCKCHECK``) the
    surfaces call :func:`note` at each key construction, recording the
    ACTUAL key tuples per compiled artifact.  Two distinct traced
    artifacts colliding on one key — the aliasing bug class PRs 15/17/
    19 each fixed by hand — fails the suite via the conftest hook, and
    the per-surface key log lets tests prove that toggling a declared
    key-feeding knob really changes the recorded key.

Off (the default) :func:`note` is a single env check: zero overhead,
zero behavior change.  This module must stay stdlib-only so the
linter can execute it without paying the jax import.
"""

from __future__ import annotations

import hashlib
import os
import threading
from typing import Any, Dict, Mapping, Optional, Tuple

__all__ = [
    "KEY_SURFACES",
    "KeyFlowRecorder",
    "get_recorder",
    "keycheck_enabled",
    "note",
    "registry_markdown",
]

#: Every cache-key surface in the engine, keyed by surface name.  Per
#: entry:
#:
#:   - ``relpath``: package-relative module that constructs the key;
#:   - ``anchor``: the function that builds/consumes key tuples there
#:     (the static pass resolves call sites / definitions by this
#:     name, and ``keycheck-note-missing`` requires the module to call
#:     ``note("<surface>", ...)``);
#:   - ``config_fields``: the ``TpuConfig`` fields DECLARED
#:     key-feeding at this surface.  The static pass holds the key
#:     expressions to this list in both directions: a declared field
#:     absent from the key is ``key-part-missing``, an undeclared
#:     ``config.*`` key part that no traced path reads is
#:     ``key-part-dead``;
#:   - ``key_tokens``: per declared field, the LOCAL NAME that carries
#:     its value into key expressions when the raw ``config.<field>``
#:     attribute does not appear there (``donate``/``hb`` in grid);
#:   - ``aliases``: store-key identifier -> the in-memory-key
#:     identifier carrying the same information (``mesh_desc`` ->
#:     ``mesh``), for the store-parts-vs-key consistency check;
#:   - ``dataflow``: True when the surface's call sites pair a key
#:     tuple with a resolvable traced callable, letting the static
#:     pass additionally prove read-implies-keyed over the closure.
KEY_SURFACES: Dict[str, Dict[str, Any]] = {
    "program_cache": {
        "relpath": "search/grid.py",
        "anchor": "_cached_program",
        "description": (
            "the cross-search in-memory cache of jitted programs "
            "(fit/score/fused/scan/prefix), keyed by everything the "
            "per-search closures capture"),
        "config_fields": ("bf16_matmul", "donate_chunk_buffers",
                          "heartbeat"),
        "key_tokens": {"donate_chunk_buffers": "donate",
                       "heartbeat": "hb"},
        "aliases": {"mesh_desc": "mesh",
                    "store_score_names": "score_key",
                    "store_sw_key": "sw_blind"},
        "dataflow": True,
    },
    "program_store": {
        "relpath": "parallel/programstore.py",
        "anchor": "maybe_wrap",
        "description": (
            "the persistent AOT program store's deterministic "
            "(kind, family, *structure) key parts, digested "
            "cross-process; the parts tuples are CONSTRUCTED at the "
            "program_cache call sites, whose store-parts-vs-key "
            "consistency check covers their contents"),
        "config_fields": (),
        "dataflow": False,
    },
    "fuse_spec": {
        "relpath": "search/grid.py",
        "anchor": "make_fuse_spec",
        "description": (
            "cross-search launch fusion: equal keys guarantee members "
            "share one compiled fused program and resident buffers"),
        "config_fields": ("bf16_matmul",),
        "dataflow": False,
    },
    "checkpoint": {
        "relpath": "search/grid.py",
        "anchor": "fingerprint",
        "description": (
            "the checkpoint journal fingerprint: a resumed search may "
            "only reuse chunks computed under a result-identical "
            "config"),
        "config_fields": ("bf16_matmul", "dtype"),
        "dataflow": False,
    },
    "plan_key": {
        "relpath": "parallel/taskgrid.py",
        "anchor": "plan_geometry",
        "description": (
            "the geometry plan cache: PlanKey's named fields are the "
            "declared planner inputs, decoded back-compat from "
            "plans.json"),
        "config_fields": ("chunk_loop",),
        "dataflow": False,
    },
    "dataplane": {
        "relpath": "parallel/dataplane.py",
        "anchor": "derived",
        "description": (
            "derived device buffers (e.g. prefix-transformed "
            "matrices) cached by content key parts; equal keys must "
            "mean equal bytes"),
        "config_fields": (),
        "dataflow": False,
    },
}


def keycheck_enabled() -> bool:
    """Is the runtime key-flow recorder active (``SST_KEYCHECK=1``)?
    Read at each :func:`note` call so tests may flip it mid-process."""
    return os.environ.get("SST_KEYCHECK", "").strip().lower() in (
        "1", "true", "on", "yes")


def _digest(obj: Any) -> str:
    """Stable-within-process 16-hex digest of an arbitrary key part
    (repr-based: key tuples may hold meshes, families and other
    rich objects whose reprs are stable for the process lifetime)."""
    return hashlib.sha256(repr(obj).encode(
        "utf-8", "backslashreplace")).hexdigest()[:16]


class KeyFlowRecorder:
    """Accumulates (surface, key) -> artifact-signature observations.

    A *collision* is one (surface, key) observed with two different
    signatures: two distinct traced artifacts would alias one cache
    slot — exactly the bug class the declared key surfaces exist to
    prevent.  Signatures are the site's *effective trace inputs*
    (``fields``); surfaces that cannot name one record key-only lines
    (no collision check, but the key log still feeds the
    toggle-a-knob-changes-the-key tests)."""

    def __init__(self):
        # the recorder is lint/lockcheck META-infrastructure, like the
        # lock shim's own mutex: a named lock here would make the
        # SST_LOCKCHECK recorder observe the SST_KEYCHECK recorder
        self._mu = threading.Lock()  # sstlint: disable=unnamed-lock
        #: (surface, key_digest) -> first observation
        self.by_key: Dict[Tuple[str, str], Dict[str, Any]] = {}
        self.collisions: list = []
        #: surface -> set of observed key digests
        self.keys_by_surface: Dict[str, set] = {}
        self.n_notes = 0

    def note(self, surface: str, key: Any,
             fields: Optional[Mapping[str, Any]] = None,
             detail: str = "") -> None:
        kd = _digest(key)
        sig = _digest(tuple(sorted(
            (str(k), repr(v)) for k, v in fields.items()))) \
            if fields is not None else None
        with self._mu:
            self.n_notes += 1
            self.keys_by_surface.setdefault(surface, set()).add(kd)
            prev = self.by_key.get((surface, kd))
            if prev is None:
                self.by_key[(surface, kd)] = {
                    "sig": sig,
                    "sigs": {sig},
                    "fields": dict(fields) if fields is not None
                    else None,
                    "detail": detail,
                }
            elif sig is not None and prev["sig"] is not None \
                    and sig not in prev["sigs"]:
                # one report per distinct aliasing signature, however
                # many launches repeat the same collision
                prev["sigs"].add(sig)
                self.collisions.append({
                    "surface": surface,
                    "key_digest": kd,
                    "fields_a": prev["fields"],
                    "detail_a": prev["detail"],
                    "fields_b": dict(fields),
                    "detail_b": detail,
                })

    def keys(self, surface: str) -> frozenset:
        """Observed key digests of one surface (the toggle-knob tests
        compare these across reconfigured runs)."""
        with self._mu:
            return frozenset(self.keys_by_surface.get(surface, ()))

    def report(self) -> Dict[str, Any]:
        with self._mu:
            return {
                "n_notes": self.n_notes,
                "n_keys": len(self.by_key),
                "keys_by_surface": {
                    s: len(v)
                    for s, v in sorted(self.keys_by_surface.items())},
                "collisions": list(self.collisions),
            }

    def reset(self) -> None:
        with self._mu:
            self.by_key.clear()
            self.collisions.clear()
            self.keys_by_surface.clear()
            self.n_notes = 0


_RECORDER = KeyFlowRecorder()


def get_recorder() -> KeyFlowRecorder:
    """The process-global recorder every instrumented surface reports
    to (tests may construct private :class:`KeyFlowRecorder`\\ s)."""
    return _RECORDER


def note(surface: str, key: Any,
         fields: Optional[Mapping[str, Any]] = None,
         detail: str = "") -> None:
    """Record one key construction when ``SST_KEYCHECK=1`` — a single
    env read otherwise, so the hooks cost nothing in production."""
    if keycheck_enabled():
        _RECORDER.note(surface, key, fields=fields, detail=detail)


def registry_markdown() -> str:
    """The key-surface registry table ``dev/build_api_docs.py``
    renders into ``docs/API.md``."""
    out = [
        "## Cache-key surfaces (`utils/keycheck.py`)\n",
        "\nEvery cache-key surface, with its declared key-feeding "
        "`TpuConfig` fields — held to the code by the `keyflow` "
        "rules in `tools/sstlint` and by the `SST_KEYCHECK=1` "
        "runtime recorder.\n",
        "\n| surface | module | anchor | declared key-feeding "
        "fields |\n|---|---|---|---|\n",
    ]
    for name in sorted(KEY_SURFACES):
        s = KEY_SURFACES[name]
        fields = ", ".join(f"`{f}`" for f in s["config_fields"]) \
            or "—"
        out.append(f"| `{name}` | `{s['relpath']}` | "
                   f"`{s['anchor']}` | {fields} |\n")
    return "".join(out)
