"""ctypes bridge to the native host runtime (native/libtpusk.so).

Every function has a numpy fallback, so the package works without the build
step; `make -C native` enables the native paths.  See
native/tpusk_native.cpp for what lives there and why (SURVEY §2.3: these are
the TPU rebuild's host-side analogs of the Spark data plane the reference
delegated to the JVM).
"""

from __future__ import annotations

import ctypes
import os
from typing import Optional, Sequence, Tuple

import numpy as np

_LIB: Optional[ctypes.CDLL] = None
_TRIED = False


def _load() -> Optional[ctypes.CDLL]:
    global _LIB, _TRIED
    if _TRIED:
        return _LIB
    _TRIED = True
    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))), "native", "libtpusk.so")
    if not os.path.exists(path):
        return None
    try:
        lib = ctypes.CDLL(path)
        if lib.tpusk_abi_version() != 1:
            return None
        lib.fold_masks_fill.argtypes = [
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
            ctypes.c_int64, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_float)]
        lib.csr_to_dense_f32.argtypes = [
            ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int32), ctypes.c_int64, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_float), ctypes.c_int32]
        lib.quantile_bin_f32.argtypes = [
            ctypes.POINTER(ctypes.c_float), ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int32, ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_int32]
        _LIB = lib
    except OSError:
        _LIB = None
    return _LIB


def native_available() -> bool:
    return _load() is not None


def _fptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


def _i64ptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))


def fold_masks(
    cv_splits: Sequence[Tuple[np.ndarray, np.ndarray]],
    n_samples: int,
    dtype=np.float32,
) -> Tuple[np.ndarray, np.ndarray]:
    """(train_idx, test_idx) pairs -> dense (n_folds, n) masks.

    Native path when libtpusk.so is built and dtype is float32; numpy
    fallback otherwise (identical output, tested in test_native.py).
    """
    # splitters may yield boolean masks instead of index arrays (sklearn's
    # check_cv passes them through); normalise to indices up front
    cv_splits = [
        (np.flatnonzero(tr) if np.asarray(tr).dtype == bool
         else np.asarray(tr),
         np.flatnonzero(te) if np.asarray(te).dtype == bool
         else np.asarray(te))
        for tr, te in cv_splits]
    lib = _load()
    n_folds = len(cv_splits)
    if lib is None or dtype != np.float32:
        from spark_sklearn_tpu.parallel.taskgrid import build_fold_masks
        return build_fold_masks(cv_splits, n_samples, dtype)
    train_idx = np.ascontiguousarray(
        np.concatenate([tr for tr, _ in cv_splits]), dtype=np.int64)
    test_idx = np.ascontiguousarray(
        np.concatenate([te for _, te in cv_splits]), dtype=np.int64)
    train_offs = np.zeros(n_folds + 1, np.int64)
    test_offs = np.zeros(n_folds + 1, np.int64)
    np.cumsum([len(tr) for tr, _ in cv_splits], out=train_offs[1:])
    np.cumsum([len(te) for _, te in cv_splits], out=test_offs[1:])
    train = np.empty((n_folds, n_samples), np.float32)
    test = np.empty((n_folds, n_samples), np.float32)
    lib.fold_masks_fill(
        _i64ptr(train_idx), _i64ptr(train_offs),
        _i64ptr(test_idx), _i64ptr(test_offs),
        n_folds, n_samples, _fptr(train), _fptr(test))
    return train, test


def csr_to_dense(data, indices, indptr, shape, n_threads: int = 0
                 ) -> np.ndarray:
    """CSR buffers -> dense float32 (native multi-threaded when built)."""
    lib = _load()
    n_rows, n_cols = int(shape[0]), int(shape[1])
    if lib is None:
        from scipy.sparse import csr_matrix
        return csr_matrix((data, indices, indptr),
                          shape=shape).toarray().astype(np.float32)
    if n_threads <= 0:
        n_threads = os.cpu_count() or 1
    data = np.ascontiguousarray(data, np.float32)
    indices = np.ascontiguousarray(indices, np.int32)
    indptr = np.ascontiguousarray(indptr, np.int32)
    out = np.empty((n_rows, n_cols), np.float32)
    lib.csr_to_dense_f32(
        _fptr(data),
        indices.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        indptr.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        n_rows, n_cols, _fptr(out), n_threads)
    return out


def quantile_bin(X: np.ndarray, n_bins: int = 256, n_threads: int = 0
                 ) -> Tuple[np.ndarray, np.ndarray]:
    """Per-feature quantile binning -> (edges (d, n_bins-1), codes uint8
    (n, d)).  Prep stage for histogram-based tree learners."""
    if not 2 <= n_bins <= 256:
        raise ValueError(
            f"n_bins must be in [2, 256] (codes are uint8), got {n_bins}")
    X = np.ascontiguousarray(X, np.float32)
    n, d = X.shape
    lib = _load()
    if lib is None:
        qs = np.linspace(0, 1, n_bins + 1)[1:-1]
        edges = np.quantile(X, qs, axis=0,
                            method="lower").T.astype(np.float32)
        edges = np.ascontiguousarray(edges)
        codes = np.empty((n, d), np.uint8)
        for f in range(d):
            codes[:, f] = np.searchsorted(edges[f], X[:, f], side="right")
        return edges, codes
    if n_threads <= 0:
        n_threads = os.cpu_count() or 1
    edges = np.empty((d, n_bins - 1), np.float32)
    codes = np.empty((n, d), np.uint8)
    lib.quantile_bin_f32(
        _fptr(X), n, d, n_bins, _fptr(edges),
        codes.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), n_threads)
    return edges, codes
