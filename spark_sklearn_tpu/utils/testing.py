"""Shared test harness — reference test_utils.py parity (SURVEY §2.2 #8).

The reference gives suites a class-scoped SparkContext (`MLlibTestCase`)
and a `fixtureReuseSparkSession` decorator so one JVM serves a whole
module.  The analog: one TpuSession (mesh + config) per test class /
decorated fixture — meshes are cheap, but the pattern keeps parity for
suites ported from the reference.
"""

from __future__ import annotations

import functools
import unittest

from spark_sklearn_tpu.utils.session import TpuSession, createLocalTpuSession


class TpuTestCase(unittest.TestCase):
    """Class-scoped session, mirroring the reference's MLlibTestCase
    (class-scoped `sc`/`spark` attributes)."""

    session: TpuSession = None
    sc = None      # reference-attribute name kept for ported suites

    @classmethod
    def setUpClass(cls):
        super().setUpClass()
        cls.session = createLocalTpuSession(appName=cls.__name__)
        cls.sc = cls.session

    @classmethod
    def tearDownClass(cls):
        cls.session.stop()
        super().tearDownClass()


_shared_session = None


def fixtureReuseTpuSession(fn):
    """Decorator handing a module-shared TpuSession to the wrapped callable
    as its first argument — the reference's fixtureReuseSparkSession."""

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        global _shared_session
        if _shared_session is None:
            _shared_session = createLocalTpuSession()
        return fn(_shared_session, *args, **kwargs)

    return wrapper


fixtureReuseSparkSession = fixtureReuseTpuSession  # reference alias
