"""Session bootstrap — reference util.py / SURVEY §3.5 parity.

The reference's `createLocalSparkSession(appName)` launches an in-process
JVM (reference: python/spark_sklearn/util.py).  On TPU there is nothing to
launch for single-host — `jax.devices()` just works — so the "session" is a
TpuConfig + Mesh pair; multi-host adds one `jax.distributed.initialize`
call (the control-plane analog of Spark's driver bootstrap; data-plane
collectives ride ICI/DCN via XLA — SURVEY §5.8).
"""

from __future__ import annotations

import os
import time
from typing import Optional

from spark_sklearn_tpu.obs.log import get_logger
from spark_sklearn_tpu.obs.trace import get_tracer
from spark_sklearn_tpu.parallel.mesh import TpuConfig, build_mesh

logger = get_logger(__name__)


class TpuSession:
    """Holds the mesh + config a process uses for searches and fleets.

    A session with `TpuConfig(compilation_cache_dir=...)` points jax's
    persistent compilation cache there at construction, so every search
    in the process — and every LATER process sharing the directory —
    amortizes the python->HLO->binary walk (the session-level analog of
    a Spark cluster reusing its deployed jars)."""

    def __init__(self, config: Optional[TpuConfig] = None,
                 appName: str = "spark-sklearn-tpu"):
        from spark_sklearn_tpu.parallel.pipeline import (
            enable_persistent_cache)
        self.appName = appName
        self.config = config or TpuConfig()
        if getattr(self.config, "trace", None):
            # a session asking for tracing turns the recorder on for its
            # whole lifetime (per-search enable would lose inter-search
            # host work from the timeline)
            get_tracer().enable(
                max_events=getattr(self.config, "trace_buffer_size", None))
        with get_tracer().span("session.init", appName=appName):
            self.mesh = build_mesh(self.config)
            enable_persistent_cache(
                self.config.resolved_cache_dir(),
                self.config.persistent_cache_min_compile_s)
            # size the device data plane (parallel/dataplane.py) now:
            # every search this session runs shares the same resident
            # X/y/mask uploads — the session-lifetime sc.broadcast
            from spark_sklearn_tpu.parallel.dataplane import plane_for
            self.dataplane = plane_for(self.config)
            # persistent AOT program store (parallel/programstore.py):
            # activate it now and prewarm from the manifest, so the
            # first search's programs — and the launch-geometry plans
            # that select them — are resident before any chunk stages
            from spark_sklearn_tpu.parallel import (
                programstore as _programstore)
            self.programstore = _programstore.activate_store(self.config)
            self._prewarm_summary = {}
            manifest = _programstore.resolve_manifest(self.config)
            if self.programstore is not None and manifest and \
                    os.path.isfile(manifest):
                self._prewarm_summary = self.prewarm(manifest)
            # persistent run history (obs/runlog.py): the search
            # doctor's cross-run regression sentinel appends one
            # attribution record per fit and compares against the
            # stored baseline for the same (family, structure, env)
            from spark_sklearn_tpu.obs import runlog as _runlog
            self.runlog = _runlog.activate_runlog(self.config)
            # parse the fault-injection plan NOW so a typo in
            # TpuConfig(fault_plan=...) / SST_FAULT_PLAN fails loudly at
            # session construction, not halfway through a long search
            from spark_sklearn_tpu.parallel.faults import FaultPlan
            self.fault_plan = FaultPlan.resolve(self.config)
            # the multi-tenant search service (serve/executor.py): the
            # session owns ONE fair-share executor; submit() routes
            # searches through it.  Construction is thread-free — the
            # sst-dispatch loop and worker threads only exist once a
            # search is actually submitted
            from spark_sklearn_tpu.serve import SearchExecutor
            self.executor = SearchExecutor(self.config, appName)
            # the crash-safe service layer (serve/journal.py): durable
            # submission WAL + heartbeat lease on the journal dir.
            # Default OFF — no TpuConfig(service_journal_dir) /
            # SST_SERVICE_JOURNAL_DIR means no object, zero writes, the
            # exact no-op.  A second LIVE owner of the directory raises
            # ServiceLeaseError HERE, at construction, never mid-search
            from spark_sklearn_tpu.serve import journal as _svc_journal
            self.journal = _svc_journal.activate_service_journal(
                self.config, owner=f"{appName}:{os.getpid()}")
            self._recovery_pending = {}
            self._restart_t0 = None
            if self.journal is not None:
                self.executor.attach_journal(self.journal)
            # fleet telemetry (obs/telemetry.py + obs/fleet.py):
            # default OFF — no thread, no socket, hooks early-out.
            # TpuConfig(telemetry_port) / SST_TELEMETRY_PORT turns on
            # the process-wide aggregator, registers this session's
            # scheduler/dataplane/programstore providers, and serves
            # Prometheus + JSON snapshots on localhost
            self.telemetry = None
            self.fleet_endpoint = None
            self._telemetry_owned = False
            self._telemetry_providers = {}
            self._init_telemetry()
            # the journal scan runs AFTER telemetry init so its
            # note_recovery counters (and the crash-marker bundle's
            # embedded snapshot) land in an enabled service; the lease
            # itself was already fenced/acquired above
            if self.journal is not None:
                self._bootstrap_recovery()
        # structured logging channel (never stdout: the session has no
        # legacy print contract)
        logger.info("TpuSession %r: mesh=%s, cache_dir=%r", appName,
                    dict(self.mesh.shape),
                    self.config.resolved_cache_dir(),
                    appName=appName, n_devices=self.mesh.size)
        logger.info(
            "data plane: %s (geometry_mode=%s)",
            "disabled" if self.dataplane is None else
            f"budget={self.dataplane.byte_budget // 2 ** 20} MiB",
            getattr(self.config, "geometry_mode", "auto"))
        logger.info(
            "program store: %s",
            "disabled" if self.programstore is None else
            f"{self.programstore.directory} "
            f"(prewarmed {self._prewarm_summary.get('loaded', 0)} "
            "artifact(s))")
        logger.info(
            "run log: %s",
            "disabled" if self.runlog is None else
            f"{self.runlog.directory} (env={self.runlog.env_digest})")
        logger.info(
            "service journal: %s",
            "disabled" if self.journal is None else
            f"{self.journal.directory} "
            f"({len(self._recovery_pending)} non-terminal entr"
            f"{'y' if len(self._recovery_pending) == 1 else 'ies'}, "
            + ("fenced stale lease"
               if (self.journal.lease_info or {}).get("taken_over")
               else "clean lease") + ")")
        from spark_sklearn_tpu.obs import memory as _obs_memory
        from spark_sklearn_tpu.parallel import memledger as _memledger
        self.memledger = _memledger.ledger_for(self.config)
        if self.memledger is not None:
            budget = _obs_memory.resolve_hbm_budget(self.config)
            if budget:
                why = f"{budget // 2 ** 20} MiB"
            elif getattr(self.config, "hbm_budget_bytes", None) == 0 \
                    or os.environ.get(
                        "SST_HBM_BUDGET_BYTES", "").strip() == "0":
                why = "no ceiling — disabled by configuration"
            else:
                why = "no ceiling — no measurable device limit"
            logger.info("memory ledger: on (hbm_budget=%s)", why,
                        hbm_budget_bytes=budget)
        else:
            logger.info("memory ledger: disabled (memory_ledger=False)")
        logger.info(
            "fault supervisor: max_launch_retries=%d "
            "max_search_retries=%d backoff=%.2fs timeout=%s "
            "fault_plan=%d injection(s)",
            getattr(self.config, "max_launch_retries", 2),
            getattr(self.config, "max_search_retries", 16),
            getattr(self.config, "retry_backoff_s", 0.5),
            getattr(self.config, "launch_timeout_s", None),
            len(self.fault_plan))

    def _bootstrap_recovery(self) -> None:
        """Scan the journal at startup: count what this restart owes,
        stamp the time-to-recover clock, and — when the lease was
        fenced from a dead owner — dump the crash-marker flight bundle
        BEFORE recovery overwrites the scene."""
        from spark_sklearn_tpu.obs import telemetry as _telemetry
        from spark_sklearn_tpu.parallel import faults as _faults
        journal = self.journal
        entries = journal.entries()
        self._recovery_pending = journal.nonterminal()
        if self._recovery_pending:
            # the clock resubmit() stops on its first success: the
            # operator-facing time-to-recover
            self._restart_t0 = time.monotonic()
        info = journal.lease_info or {}
        _telemetry.note_recovery("journal_entries", len(entries))
        _telemetry.note_recovery("nonterminal_found",
                                 len(self._recovery_pending))
        if info.get("taken_over"):
            _telemetry.note_recovery("lease_takeovers")
            _telemetry.note_recovery("unclean_shutdowns")
            # no flight dir configured still gets a marker: the journal
            # directory itself is the fallback dump target
            _telemetry.flight_recorder().dump(
                "crash-marker",
                flight_dir=_telemetry.resolve_flight_dir(self.config)
                or journal.directory,
                config=self.config,
                context=_faults.crash_marker_context(
                    self._recovery_pending, info))

    def _init_telemetry(self) -> None:
        from spark_sklearn_tpu.obs import fleet as _fleet
        from spark_sklearn_tpu.obs import telemetry as _telemetry
        port = _fleet.resolve_telemetry_port(self.config)
        if port is None:
            return
        svc = _telemetry.get_telemetry()
        svc.enable(
            window_s=getattr(self.config, "telemetry_window_s", None),
            interval_s=getattr(self.config, "telemetry_interval_s",
                               None))
        self.telemetry = svc
        self._telemetry_owned = True
        # this session's own provider callables, remembered so stop()
        # (and the unwind below) tears down exactly these — never a
        # later session's registration under the same name
        self._telemetry_providers = {
            "scheduler": self.executor.telemetry_gauges}
        if self.dataplane is not None:
            plane = self.dataplane

            def _plane_gauges():
                return {**plane.stats(),
                        "tenant_bytes": {
                            str(t): b for t, b in
                            plane.tenant_usage_all().items()}}

            self._telemetry_providers["dataplane"] = _plane_gauges
        if self.programstore is not None:
            self._telemetry_providers["programstore"] = \
                self.programstore.counts
        if getattr(self.config, "memory_ledger", True):
            # the device-memory ledger's gauges (per-device pressure,
            # modeled peak, watermark) — the sampler keeps the
            # /metrics pressure series current between searches
            from spark_sklearn_tpu.parallel import (
                memledger as _memledger)
            self._telemetry_providers["memory"] = \
                _memledger.get_ledger().gauges
        try:
            for name, fn in self._telemetry_providers.items():
                svc.register_provider(name, fn)
            self.fleet_endpoint = _fleet.FleetEndpoint(
                port, service=svc).start()
        except BaseException:
            # a failed endpoint bind (port in use) must not leave the
            # process-global service enabled with a live sampler bound
            # to this half-built session — unwind to the exact no-op
            self._teardown_telemetry()
            raise
        logger.info(
            "fleet telemetry: window=%.0fs interval=%.2fs endpoint=%s",
            svc.window_s, svc.interval_s, self.fleet_endpoint.url,
            url=self.fleet_endpoint.url)

    def _teardown_telemetry(self) -> None:
        """Release this session's telemetry: drop ONE enable reference
        (refcounted — another telemetry-enabled session keeps the
        shared service alive) and unregister exactly the providers this
        session registered (identity-checked, so a later session's
        same-name registrations survive)."""
        svc = self.telemetry
        self.telemetry = None
        self._telemetry_owned = False
        if svc is None:
            return
        svc.disable()
        for name, fn in getattr(self, "_telemetry_providers",
                                {}).items():
            svc.unregister_provider(name, expected=fn)
        self._telemetry_providers = {}

    def telemetry_snapshot(self) -> dict:
        """The fleet-telemetry snapshot (schema pinned in
        ``obs.metrics.TELEMETRY_SNAPSHOT_SCHEMA``): per-tenant
        queue-wait p50/p95 / throughput / share over the sliding
        window, device occupancy, scheduler queue depth, data-plane and
        program-store gauges, fault totals and flight-recorder state.
        The zeroed ``enabled: False`` shape when telemetry is off."""
        from spark_sklearn_tpu.obs import telemetry as _telemetry
        return _telemetry.get_telemetry().snapshot()

    @property
    def n_devices(self) -> int:
        return self.mesh.size

    # -- multi-tenant serving (serve/executor.py) ------------------------
    def submit(self, search, X, y=None, **fit_params):
        """Submit a search to the session's fair-share executor and
        return a :class:`~spark_sklearn_tpu.serve.SearchFuture`
        (``result()`` / ``cancel()`` / ``progress()``).

        Concurrent submissions interleave their chunk launches on the
        device under deficit-round-robin fair share over tenants
        (``TpuConfig(tenant, tenant_weight)``), with admission control
        (``max_concurrent_searches`` / ``max_queued_searches`` ->
        :class:`~spark_sklearn_tpu.serve.AdmissionError`) and
        per-tenant data-plane byte quotas on top.  Every search's
        ``cv_results_`` is bit-exact with its solo ``fit``; a single
        submitted search short-circuits to the solo dispatch path."""
        return self.executor.submit(search, X, y,
                                    fit_params=fit_params)

    def attach(self, search):
        """Bind a search estimator to this session: its ``fit`` becomes
        sugar for ``submit(...).result()`` — identical results, routed
        through the session's executor so it fair-shares the device
        with concurrently-submitted searches.  Returns the search for
        chaining."""
        search._sst_session = self
        return search

    # -- crash recovery (serve/journal.py) -------------------------------
    def recover(self):
        """What the service journal still owes: a
        :class:`~spark_sklearn_tpu.serve.RecoveryReport` listing every
        journaled search whose last transition is non-terminal (a
        previous process was SIGKILLed mid-flight), plus the lease
        verdict (fenced takeover vs clean start).  The empty report
        when no journal is configured.

        Recovery is two-phase by design: the journal records data
        FINGERPRINTS, not data, so the caller re-binds X/y and passes
        each entry to :meth:`resubmit`."""
        from spark_sklearn_tpu.serve import journal as _svc_journal
        if self.journal is None:
            return _svc_journal.RecoveryReport()
        with get_tracer().span("session.recover"):
            self._recovery_pending = self.journal.nonterminal()
            info = self.journal.lease_info or {}
            entries = []
            for handle in sorted(self._recovery_pending):
                rec = self._recovery_pending[handle]
                entries.append(_svc_journal.RecoveryEntry(
                    handle=handle,
                    tenant=str(rec.get("tenant", "")),
                    weight=float(rec.get("weight", 1.0) or 1.0),
                    family=str(rec.get("family", "")),
                    structure_digest=str(
                        rec.get("structure_digest", "")),
                    data_fingerprint=str(
                        rec.get("data_fingerprint", "")),
                    checkpoint_dir=str(rec.get("checkpoint_dir", "")),
                    state=str(rec.get("state", "")),
                    config=dict(rec.get("config") or {})))
            return _svc_journal.RecoveryReport(
                entries=tuple(entries),
                taken_over=bool(info.get("taken_over")),
                unclean=bool(info.get("unclean")),
                journal_dir=self.journal.directory)

    def resubmit(self, entry, search, X, y=None, **fit_params):
        """Re-admit one recovered search through the NORMAL admission
        path and return its
        :class:`~spark_sklearn_tpu.serve.SearchFuture`.

        ``entry`` is a :class:`~spark_sklearn_tpu.serve.RecoveryEntry`
        from :meth:`recover` (or its journal handle string).  The
        re-bound data's blake2b fingerprint is verified against the
        journaled one FIRST — a mismatch raises
        :class:`~spark_sklearn_tpu.serve.RecoveryDataMismatchError`
        before any admission or device work, because resuming a
        checkpoint journal against different data would silently blend
        two datasets' partial results.  With the same checkpoint
        directory the resumed search replays its per-search journal,
        so the recovered ``cv_results_`` is bit-exact vs the uncrashed
        run."""
        from spark_sklearn_tpu.obs import telemetry as _telemetry
        from spark_sklearn_tpu.serve import journal as _svc_journal
        if self.journal is None:
            raise ValueError(
                "no service journal: construct the session with "
                "TpuConfig(service_journal_dir=...)")
        handle = entry if isinstance(entry, str) else entry.handle
        rec = self._recovery_pending.get(handle)
        if rec is None:
            raise KeyError(
                f"no non-terminal journal entry {handle!r} "
                "(recover() lists what this session owes)")
        expected = str(rec.get("data_fingerprint", ""))
        got = _svc_journal.data_fingerprint(X, y)
        if expected and got != expected:
            _telemetry.note_recovery("mismatch")
            raise _svc_journal.RecoveryDataMismatchError(
                f"recovered search {handle!r}: re-bound data does not "
                f"match the journaled fingerprint (expected "
                f"{expected[:12]}, got {got[:12]})",
                handle=handle, expected=expected, got=got)
        ckpt = str(rec.get("checkpoint_dir", "") or "")
        cfg = getattr(search, "config", None)
        if ckpt and not getattr(cfg, "checkpoint_dir", None) \
                and not getattr(self.config, "checkpoint_dir", None):
            # the recovered search must replay ITS checkpoint journal:
            # carry the journaled directory onto the resubmission when
            # neither the search nor the session names one
            import dataclasses as _dc
            base = cfg if cfg is not None else self.config
            try:
                search.config = _dc.replace(base, checkpoint_dir=ckpt)
            except TypeError:
                pass
        fut = self.executor.submit(search, X, y, fit_params=fit_params,
                                   recovered_from=handle)
        # retire the journaled entry, linked to its successor — the
        # successor's own WAL lifecycle carries the work from here
        self.journal.record_transition(
            handle, "recovered", qualify=False,
            successor=self.journal.qualify(fut.handle_id))
        self._recovery_pending.pop(handle, None)
        if self._restart_t0 is not None:
            # first successful resubmit stops the restart clock
            _telemetry.note_recovery(
                "recovered",
                time_to_recover_s=time.monotonic() - self._restart_t0)
            self._restart_t0 = None
        else:
            _telemetry.note_recovery("recovered")
        return fut

    def executor_stats(self) -> dict:
        """The executor's live state: active/pending search counts and
        per-tenant queue/in-flight/dispatched-cost tallies."""
        return self.executor.stats()

    def dataplane_stats(self) -> dict:
        """Cumulative hit/miss/byte counters of the session's device
        data plane (empty dict when ``dataplane_bytes=0`` disabled
        it)."""
        return {} if self.dataplane is None else self.dataplane.stats()

    def programstore_stats(self) -> dict:
        """Cumulative counters + disk state of the session's persistent
        AOT program store (empty dict when no store is configured)."""
        if self.programstore is None:
            return {}
        return {**self.programstore.counts(),
                **self.programstore.disk_stats()}

    def prewarm(self, manifest) -> dict:
        """Load the AOT program artifacts a manifest declares (path or
        parsed dict — see
        :meth:`~spark_sklearn_tpu.parallel.programstore.ProgramStore.
        prewarm`) into the store's memory cache, so the declared
        (family, grid-shape) programs resolve without disk IO when the
        first search requests them.  No-op (with a log line) when the
        session has no program store."""
        if self.programstore is None:
            logger.info("prewarm skipped: no program store configured "
                        "(TpuConfig.program_store_dir)")
            return {}
        return self.programstore.prewarm(manifest)

    def write_prewarm_manifest(self, path: Optional[str] = None) -> str:
        """Record every store artifact this process served or published
        — what the finished searches actually used — as a prewarm
        manifest for the next session's
        ``TpuConfig(prewarm_manifest=...)``.  Default path: the
        configured ``prewarm_manifest``."""
        if self.programstore is None:
            raise ValueError(
                "no program store: construct the session with "
                "TpuConfig(program_store_dir=...)")
        from spark_sklearn_tpu.parallel.programstore import (
            resolve_manifest)
        target = path or resolve_manifest(self.config)
        if not target:
            raise ValueError(
                "no manifest path: pass one, or construct the session "
                "with TpuConfig(prewarm_manifest=...)")
        return self.programstore.write_manifest(target)

    def export_trace(self, path: Optional[str] = None) -> str:
        """Write the tracer's current buffer as a Chrome trace-event
        JSON (default path: ``TpuConfig.trace`` when it is a string)
        and return the written path."""
        from spark_sklearn_tpu.obs.export import export_chrome_trace
        target = path or (self.config.trace
                          if isinstance(self.config.trace, str) else None)
        if not target:
            raise ValueError(
                "no export path: pass one, or construct the session "
                "with TpuConfig(trace='out.json')")
        return export_chrome_trace(target)

    def stop(self):
        """Shut the session's search executor down (reference API
        symmetry: SparkSession.stop).  Running searches finish, the
        waiting line cancels, new submissions raise AdmissionError.
        A session-owned telemetry endpoint and sampler stop too."""
        self.executor.shutdown()
        if self.journal is not None:
            # AFTER executor shutdown, so the pending line's "shed"
            # transitions land before the clean-shutdown record
            self.journal.release_lease(clean=True)
        if self.fleet_endpoint is not None:
            self.fleet_endpoint.stop()
            self.fleet_endpoint = None
        if self._telemetry_owned:
            self._teardown_telemetry()

    def __repr__(self):
        return (f"TpuSession(appName={self.appName!r}, "
                f"mesh={dict(self.mesh.shape)})")


def createLocalTpuSession(appName: str = "spark-sklearn-tpu",
                          config: Optional[TpuConfig] = None) -> TpuSession:
    """Drop-in analog of the reference's createLocalSparkSession."""
    return TpuSession(config=config, appName=appName)


# alias so reference-style imports keep working
createLocalSparkSession = createLocalTpuSession


def init_distributed(coordinator_address: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None) -> None:
    """Multi-host bootstrap: one call per host before building the mesh
    (SURVEY §7.3 #6 — everything else is 'same code, bigger mesh').

    With no arguments, defers entirely to jax.distributed's environment
    auto-detection (TPU pod metadata / cluster env vars)."""
    import jax

    kwargs = {}
    if coordinator_address is not None:
        kwargs["coordinator_address"] = coordinator_address
    if num_processes is not None:
        kwargs["num_processes"] = num_processes
    if process_id is not None:
        kwargs["process_id"] = process_id
    jax.distributed.initialize(**kwargs)
