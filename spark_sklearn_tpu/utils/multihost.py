"""Multi-process (multi-controller) dryrun — SURVEY §3.5 / §7.3 #6.

The reference's multi-node story is Spark's: driver + executor JVMs over
Netty (reference: util.py createLocalSparkSession is the local[*] stand-in).
The TPU-native story is JAX multi-controller SPMD: every host runs the
same program, `jax.distributed.initialize` wires the control plane, and
the mesh spans all hosts' devices so XLA collectives ride ICI/DCN.

Everything else in the engine is "same code, bigger mesh" — the one thing
a single-process virtual mesh cannot exercise is the multi-host bootstrap
and the cross-process gather of launch outputs
(`parallel.mesh.device_get_tree`).  `dryrun_multihost(n_proc, n_dev)`
exercises exactly that on CPU devices: it spawns n_proc REAL OS processes,
each claiming n_dev virtual CPU devices, forms a (n_proc*n_dev)-device
cluster, and runs one small GridSearchCV sweep through the public API
with the task grid sharded across processes.

Run directly:  python -m spark_sklearn_tpu.utils.multihost
"""

from __future__ import annotations

import os
import socket
import subprocess
import sys
import time


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def worker_main(coordinator: str, n_proc: int, pid: int, n_dev: int) -> int:
    """One cluster process: claim n_dev virtual CPU devices, join the
    jax.distributed cluster, run a sharded search over the GLOBAL mesh."""
    import jax

    # platform must be pinned before any backend init; config calls (not
    # env vars) because the axon sitecustomize imports jax first
    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_num_cpu_devices", n_dev)
    except AttributeError:
        # jax < 0.5 has no such option; XLA_FLAGS is read at backend
        # INIT (not import), so setting it here — before jax.devices()
        # — still takes effect despite the sitecustomize's early import
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={n_dev}")

    from spark_sklearn_tpu.utils.session import init_distributed
    init_distributed(coordinator_address=coordinator,
                     num_processes=n_proc, process_id=pid)

    assert jax.process_count() == n_proc, jax.process_count()
    assert jax.device_count() == n_proc * n_dev, jax.device_count()
    assert jax.local_device_count() == n_dev

    import numpy as np
    from sklearn.linear_model import LogisticRegression

    import spark_sklearn_tpu as sst

    rng = np.random.default_rng(0)
    X = rng.normal(size=(64, 6)).astype(np.float32)
    y = (X[:, 0] + 0.2 * rng.normal(size=64) > 0).astype(np.int64)

    # global mesh over every process's devices: the task axis spans the
    # cluster, so each process computes its stripe of the candidate grid
    # and `device_get_tree` all-gathers the scores
    config = sst.TpuConfig(devices=jax.devices())
    gs = sst.GridSearchCV(
        LogisticRegression(max_iter=20),
        {"C": [0.05, 0.1, 0.5, 1.0, 2.0, 4.0, 8.0, 16.0]},
        cv=2, refit=False, backend="tpu", config=config)
    gs.fit(X, y)
    scores = gs.cv_results_["mean_test_score"]
    assert np.all(np.isfinite(scores)), scores
    assert float(scores.max()) > 0.5, scores
    # public report surface; degrade to an empty mesh dict if fit has
    # not populated it (NotFittedError is also an AttributeError)
    try:
        mesh_shape = dict(gs.search_report.get("mesh", {}))
    except AttributeError:
        mesh_shape = {}
    print(f"proc {pid}/{n_proc}: {jax.local_device_count()} local of "
          f"{jax.device_count()} global devices, mesh={mesh_shape}, "
          f"best={float(scores.max()):.3f}", flush=True)
    return 0


def _wait_procs(procs, timeout_s: float, grace_s: float = 10.0):
    """Reap a cluster's worker processes under one shared deadline.

    Per-worker semantics: each process must exit before `timeout_s`
    elapses (a shared wall — a multi-controller cluster's workers
    finish together or not at all).  The moment ANY worker fails or
    times out, the rest get `grace_s` to exit (their peer's death
    typically wedges their next collective forever) and are then
    killed and reaped — no straggler is ever left waiting without a
    deadline.

    Returns (outs, failed_idx, timed_out_idx): per-process output
    strings and the process indices that exited nonzero / were killed.
    """
    import threading

    # drain every worker's stdout on a reader thread: a chatty worker
    # (crash tracebacks, verbose XLA logs) would otherwise fill the OS
    # pipe buffer, block in write(), and look "hung" until the deadline
    drained: dict = {}

    def _reader(pid, stream):
        try:
            drained[pid] = stream.read() or ""
        except (OSError, ValueError):         # pragma: no cover
            drained[pid] = "<output unreadable>"

    readers = {}
    for pid, p in enumerate(procs):
        if p.stdout is not None:
            t = threading.Thread(target=_reader, args=(pid, p.stdout),
                                 daemon=True)
            t.start()
            readers[pid] = t

    deadline = time.time() + timeout_s
    pending = dict(enumerate(procs))
    failed_idx, timed_out_idx = [], []
    while pending and time.time() < deadline:
        for pid in list(pending):
            p = pending[pid]
            if p.poll() is None:
                continue
            del pending[pid]
            if p.returncode != 0:
                failed_idx.append(pid)
                # fail fast: a dead cluster process wedges its peers'
                # next collective — give them a short grace, not the
                # whole budget
                deadline = min(deadline, time.time() + grace_s)
        if pending:
            time.sleep(0.1)
    for pid, p in sorted(pending.items()):   # stragglers: kill and reap
        timed_out_idx.append(pid)
        p.kill()
        try:
            p.wait(timeout=30)
        except subprocess.TimeoutExpired:     # pragma: no cover
            pass
    outs = []
    for pid, p in enumerate(procs):
        t = readers.get(pid)
        if t is not None:
            t.join(timeout=30)
        out = drained.get(pid, "")
        if pid in timed_out_idx:
            out += "\n<killed: exceeded deadline>"
        outs.append(out)
    return outs, sorted(failed_idx), sorted(timed_out_idx)


def dryrun_multihost(n_proc: int = 2, n_dev: int = 2,
                     timeout_s: int = 600) -> None:
    """Spawn an n_proc-process CPU cluster and run one sharded search.

    Raises RuntimeError naming WHICH process index died (plus every
    process's output) on failure, so a sandbox that forbids
    subprocesses or localhost sockets is flagged clearly rather than
    silently skipped.  Worker waits carry a per-worker deadline: a hung
    worker is killed and reaped, never awaited forever."""
    coordinator = f"127.0.0.1:{_free_port()}"
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)   # worker pins platform itself
    procs = []
    for pid in range(n_proc):
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "spark_sklearn_tpu.utils.multihost",
             "--worker", coordinator, str(n_proc), str(pid), str(n_dev)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env))
    outs, failed_idx, timed_out_idx = _wait_procs(procs, timeout_s)
    if failed_idx or timed_out_idx:
        blame = []
        if failed_idx:
            blame.append("proc(s) %s exited nonzero (%s)" % (
                failed_idx,
                ", ".join(f"{i}: rc={procs[i].returncode}"
                          for i in failed_idx)))
        if timed_out_idx:
            blame.append(f"proc(s) {timed_out_idx} killed after "
                         f"{timeout_s}s deadline")
        detail = "\n".join(
            f"--- proc {pid} (rc={p.returncode}) ---\n{outs[pid]}"
            for pid, p in enumerate(procs))
        raise RuntimeError(
            "dryrun_multihost failed: " + "; ".join(blame)
            + " (sandbox may forbid subprocesses or localhost "
            "sockets):\n" + detail)
    for pid, o in enumerate(outs):
        print(f"--- proc {pid} (rc=0) ---\n{o}".strip())
    print(f"dryrun_multihost({n_proc} procs x {n_dev} devices) OK")


def main(argv):
    if len(argv) >= 6 and argv[1] == "--worker":
        return worker_main(argv[2], int(argv[3]), int(argv[4]),
                           int(argv[5]))
    dryrun_multihost()
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
