"""Named locks + the opt-in runtime lock-order recorder.

Every lock the engine's threads contend on is created through
:func:`named_lock` / :func:`named_rlock` with a stable dotted name
(``"dataplane._TOTALS_LOCK"``, ``"grid.stage_lock"``, ...).  Two
consumers build on the names:

  - ``tools/sstlint`` finds the lock registry STATICALLY (the factory
    calls are its anchor) and checks the acquisition graph for cycles,
    cross-module nesting, and shared-state mutation outside the
    owning lock;
  - under ``SST_LOCKCHECK=1`` the factories return instrumented locks
    that record the ACTUAL acquisition orders while the test suite
    runs.  An order inversion (lock A taken under B on one thread and
    B under A on another — the deadlock precondition the static pass
    can only approximate) is recorded with both stacks and fails the
    suite via the conftest hook; holds longer than
    ``SST_LOCKCHECK_HOLD_S`` (default 1.0 s — e.g. a lock held across
    a blocking ``device_put``/``block_until_ready`` that stalls every
    other thread) are reported as warnings.

Off (the default) the factories return plain ``threading`` locks:
zero overhead, zero behavior change.
"""

from __future__ import annotations

import os
import threading
import time
import traceback
from typing import Any, Dict, List, Optional, Tuple

__all__ = [
    "CheckedLock",
    "LockOrderRecorder",
    "get_recorder",
    "lockcheck_enabled",
    "named_lock",
    "named_rlock",
]


def lockcheck_enabled() -> bool:
    """Is the runtime recorder active (``SST_LOCKCHECK=1``)?  Read at
    each factory call so tests may flip it; locks created earlier keep
    whatever instrumentation they were born with."""
    return os.environ.get("SST_LOCKCHECK", "").strip().lower() in (
        "1", "true", "on", "yes")


def _hold_threshold_s() -> float:
    try:
        return float(os.environ.get("SST_LOCKCHECK_HOLD_S", "1.0"))
    except ValueError:
        return 1.0


class LockOrderRecorder:
    """Accumulates acquisition-order edges across all instrumented
    locks.

    An *edge* (A -> B) means some thread acquired B while holding A.
    An *inversion* is a pair of edges (A -> B) and (B -> A): two
    threads interleaving those paths can deadlock.  Inversions are
    recorded once per unordered pair, with the stacks of both sides.
    """

    def __init__(self):
        self._mu = threading.Lock()
        #: (held, acquired) -> {"thread", "stack"} of the first observation
        self.edges: Dict[Tuple[str, str], Dict[str, Any]] = {}
        self.inversions: List[Dict[str, Any]] = []
        self.long_holds: List[Dict[str, Any]] = []
        self._tls = threading.local()

    # -- per-thread held stack -------------------------------------------
    def _held(self) -> List[str]:
        held = getattr(self._tls, "held", None)
        if held is None:
            held = self._tls.held = []
        return held

    # -- recording -------------------------------------------------------
    def note_acquired(self, name: str) -> None:
        held = self._held()
        if held and held[-1] != name:
            stack = "".join(traceback.format_stack(limit=8)[:-2])
            th = threading.current_thread().name
            with self._mu:
                for h in held:
                    if h == name:      # reentrant: never a self-edge
                        continue
                    edge = (h, name)
                    if edge not in self.edges:
                        self.edges[edge] = {"thread": th, "stack": stack}
                        rev = self.edges.get((name, h))
                        if rev is not None:
                            self.inversions.append({
                                "locks": (h, name),
                                "thread_a": rev["thread"],
                                "stack_a": rev["stack"],
                                "thread_b": th,
                                "stack_b": stack,
                            })
        held.append(name)

    def note_released(self, name: str, held_s: float) -> None:
        held = self._held()
        # locks may legitimately release out of LIFO order
        for i in range(len(held) - 1, -1, -1):
            if held[i] == name:
                del held[i]
                break
        if held_s >= _hold_threshold_s():
            with self._mu:
                self.long_holds.append({
                    "lock": name, "held_s": round(held_s, 4),
                    "thread": threading.current_thread().name,
                })

    # -- consumption -----------------------------------------------------
    def report(self) -> Dict[str, Any]:
        with self._mu:
            return {
                "n_edges": len(self.edges),
                "edges": sorted(self.edges),
                "inversions": list(self.inversions),
                "long_holds": list(self.long_holds),
            }

    def reset(self) -> None:
        with self._mu:
            self.edges.clear()
            self.inversions.clear()
            self.long_holds.clear()


_RECORDER = LockOrderRecorder()


def get_recorder() -> LockOrderRecorder:
    """The process-global recorder every instrumented lock reports
    to (tests may construct private :class:`LockOrderRecorder`\\ s)."""
    return _RECORDER


class CheckedLock:
    """A named wrapper over a ``threading`` lock that reports its
    acquisition order and hold times to a :class:`LockOrderRecorder`.

    Supports the context-manager protocol plus ``acquire``/``release``
    and reentrant inner locks (an RLock re-acquisition records
    nothing — it cannot order against itself)."""

    __slots__ = ("_lock", "name", "_recorder", "_depth", "_t_acquired")

    def __init__(self, lock, name: str,
                 recorder: Optional[LockOrderRecorder] = None):
        self._lock = lock
        self.name = name
        self._recorder = recorder if recorder is not None else _RECORDER
        self._depth = threading.local()
        self._t_acquired = threading.local()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = self._lock.acquire(blocking, timeout)
        if got:
            depth = getattr(self._depth, "n", 0)
            self._depth.n = depth + 1
            if depth == 0:
                self._t_acquired.t = time.perf_counter()
                self._recorder.note_acquired(self.name)
        return got

    def release(self) -> None:
        depth = getattr(self._depth, "n", 0) - 1
        self._depth.n = depth
        if depth == 0:
            held_s = time.perf_counter() - getattr(
                self._t_acquired, "t", time.perf_counter())
            self._recorder.note_released(self.name, held_s)
        self._lock.release()

    def __enter__(self) -> "CheckedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> bool:
        self.release()
        return False

    def locked(self) -> bool:
        # threading.RLock grows .locked() only in 3.14; fall back to
        # this thread's recursion depth so the instrumented variant
        # never diverges from the plain one by raising
        inner = getattr(self._lock, "locked", None)
        if inner is not None:
            return inner()
        return getattr(self._depth, "n", 0) > 0

    def __repr__(self) -> str:
        return f"CheckedLock({self.name!r})"


def named_lock(name: str):
    """A ``threading.Lock`` registered under ``name`` — instrumented
    when ``SST_LOCKCHECK=1``, a plain lock otherwise."""
    if lockcheck_enabled():
        return CheckedLock(threading.Lock(), name)
    return threading.Lock()


def named_rlock(name: str):
    """A ``threading.RLock`` registered under ``name`` — instrumented
    when ``SST_LOCKCHECK=1``, a plain RLock otherwise."""
    if lockcheck_enabled():
        return CheckedLock(threading.RLock(), name)
    return threading.RLock()
