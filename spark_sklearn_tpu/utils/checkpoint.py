"""Search checkpoint/resume — SURVEY §5.4.

The reference has none: a Spark search that dies is re-run, and fitted
artifacts persist only as pickled estimators (reference: keyed_models.py).
Here checkpointing is nearly free because the engine already works in
(compile-group x chunk) units: after each launched chunk the per-candidate
rows stream to an append-only jsonl next to nothing else, and a restarted
search with the same (estimator, grid, cv, data fingerprint) skips the
chunks it already has.

Fitted parameter pytrees save/load with numpy's npz (flat key -> array),
which round-trips every family's model dict without an orbax dependency.
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Any, Dict, Optional

import numpy as np

from spark_sklearn_tpu.utils import journalspec as _jspec


def fingerprint(*parts) -> str:
    h = hashlib.sha256()
    for p in parts:
        if isinstance(p, np.ndarray):
            h.update(np.ascontiguousarray(p).tobytes()[:1 << 20])
            h.update(str(p.shape).encode())
        else:
            h.update(repr(p).encode())
    return h.hexdigest()[:16]


class SearchCheckpoint:
    """Append-only chunk log: one json line per completed chunk, plus
    fault-journal lines (``fault_chunk_id`` records, written by the
    launch supervisor before each recovery attempt) that resume loaders
    collect into :attr:`faults` without ever mistaking them for
    results."""

    def __init__(self, directory: str, key: str):
        os.makedirs(directory, exist_ok=True)
        self.path = os.path.join(directory, f"search_{key}.jsonl")
        self._done: Dict[str, Dict[str, Any]] = {}
        self._meta: Dict[str, Any] = {}
        self.faults: list = []
        # a crash between the journal's open() and its first durable
        # append can leave a zero-byte file (or a torn, undecodable
        # tail): both are an EMPTY journal to resume from, never a
        # corrupt one that aborts the search.  errors="replace" keeps
        # text-mode iteration from raising UnicodeDecodeError on
        # garbage bytes — the mangled line then fails json.loads and
        # is skipped like any other torn tail.
        if os.path.exists(self.path) and os.path.getsize(self.path) > 0:
            with open(self.path, errors="replace") as f:
                for line in f:
                    try:
                        rec = json.loads(line)
                        # line shapes and their precedence are declared
                        # once, in utils/journalspec.py — classification
                        # is key-presence exact with every shipped
                        # loader, so old journals replay identically
                        kind, key, value = \
                            _jspec.classify_checkpoint_record(rec)
                        if kind == "fault":
                            self.faults.append(rec)
                            continue
                        if kind == "meta":
                            # journal metadata (e.g. the pinned launch-
                            # geometry plan): last record wins; loaders
                            # predating meta lines skip them on KeyError
                            self._meta[key] = value
                            continue
                        self._done[key] = rec
                    except (json.JSONDecodeError, KeyError):
                        continue  # torn tail line from a crash

    def get(self, chunk_id: str) -> Optional[Dict[str, Any]]:
        return self._done.get(chunk_id)

    def put(self, chunk_id: str, record: Dict[str, Any]):
        record = {"chunk_id": chunk_id, **record}
        with open(self.path, "a") as f:
            f.write(json.dumps(record) + "\n")
            f.flush()
            os.fsync(f.fileno())
        self._done[chunk_id] = record

    def note_fault(self, chunk_id: str, info: Dict[str, Any]):
        """Durably journal a recovery event BEFORE the retry runs, so a
        recovery that then dies still leaves the fault on disk for the
        resumed process.  Keyed ``fault_chunk_id`` (never ``chunk_id``)
        so no loader — including pre-fault-journal ones, which skip the
        line on KeyError — can mistake it for a completed chunk."""
        rec = {"fault_chunk_id": chunk_id, **info}
        with open(self.path, "a") as f:
            f.write(json.dumps(rec) + "\n")
            f.flush()
            os.fsync(f.fileno())
        self.faults.append(rec)

    def get_meta(self, name: str) -> Any:
        """Journal metadata written by :meth:`put_meta` (e.g. the
        pinned launch-geometry plan a resumed search must replay)."""
        return self._meta.get(name)

    def put_meta(self, name: str, value: Any) -> None:
        """Durably append a ``{"meta": name, "value": ...}`` record.
        Written BEFORE any chunk it governs, so a resume always sees
        the plan its chunk ids were generated under."""
        rec = {"meta": name, "value": value}
        with open(self.path, "a") as f:
            f.write(json.dumps(rec) + "\n")
            f.flush()
            os.fsync(f.fileno())
        self._meta[name] = value

    @property
    def n_done(self) -> int:
        return len(self._done)


def save_pytree(path: str, tree) -> None:
    """Flat-key npz serialisation of a model pytree (TpuModel.model or a
    keyed fleet's stacked models).

    Atomic: the archive is written to a temp file in the same directory,
    fsynced, then ``os.replace``d over the target — a crash mid-save can
    never leave a truncated ``.npz`` that poisons the next resume (the
    target either holds the old complete archive or the new one)."""
    import jax
    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    arrays = {}
    keys = []
    for i, (kpath, leaf) in enumerate(flat):
        keys.append(jax.tree_util.keystr(kpath))
        arrays[f"leaf_{i}"] = np.asarray(leaf)
    arrays["__keys__"] = np.array(keys)
    arrays["__treedef__"] = np.array([str(treedef)])
    # np.savez(path) appends ".npz" to extension-less paths; resolve the
    # real target up front so the temp file replaces the right name
    target = path if str(path).endswith(".npz") else f"{path}.npz"
    tmp = f"{target}.tmp.{os.getpid()}"
    try:
        with open(tmp, "wb") as f:
            np.savez(f, **arrays)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, target)
    finally:
        if os.path.exists(tmp):
            os.remove(tmp)


def load_pytree(path: str, like=None):
    """Load a pytree saved by save_pytree; `like` (same structure) restores
    the exact container types, otherwise a {keystr: array} dict returns."""
    import jax
    # mirror save_pytree's ".npz" normalization so an extension-less
    # journal pointer round-trips to the file save actually wrote
    if not str(path).endswith(".npz"):
        path = f"{path}.npz"
    with np.load(path, allow_pickle=False) as z:
        n = len([k for k in z.files if k.startswith("leaf_")])
        leaves = [z[f"leaf_{i}"] for i in range(n)]
        keys = [str(k) for k in z["__keys__"]]
    if like is not None:
        treedef = jax.tree_util.tree_structure(like)
        return jax.tree_util.tree_unflatten(treedef, leaves)
    return dict(zip(keys, leaves))
