"""The versioned registry of every durable journal record kind.

Two journals make the engine crash-safe: the per-search checkpoint
journal (``utils/checkpoint.py``, an append-only jsonl of chunk
results, fault lines and pinned-plan meta records) and the service
write-ahead log (``serve/journal.py``, checksummed submission/state
documents).  Both are *formats a dead process left behind for a future
one*, so drift is a resume-time surprise by construction — unless the
vocabulary lives in exactly one place.  This module is that place:

  - every checkpoint line shape and every ``put_meta`` kind is
    declared in :data:`CHECKPOINT_RECORD_KINDS` /
    :data:`CHECKPOINT_META_KINDS`, each with a format version and a
    back-compat ``decode`` normalizer;
  - every service-journal ``kind`` is declared in
    :data:`SERVICE_RECORD_KINDS`, and ``SERVICE_JOURNAL_FORMAT`` lives
    here (``serve/journal.py`` re-exports it);
  - ``tools/sstlint``'s ``journal-format`` rule loads this module
    import-light and fails any ``put_meta``/``append`` call site whose
    record kind is not declared here, and ``journal-decoder-missing``
    fails any declared kind without a decoder — format drift becomes
    a lint finding instead of a resume-time surprise.

Runtime readers stay permissive (an UNKNOWN kind in an on-disk journal
is skipped/stored exactly as before — old processes must keep reading
new journals' extra records); the registry constrains *writers*, at
lint time.  Stdlib-only: the linter executes this module without
paying the jax import.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Tuple

__all__ = [
    "CHECKPOINT_JOURNAL_FORMAT",
    "CHECKPOINT_META_KINDS",
    "CHECKPOINT_RECORD_KINDS",
    "SERVICE_JOURNAL_FORMAT",
    "SERVICE_RECORD_KINDS",
    "classify_checkpoint_record",
    "decode_meta",
    "meta_kind_spec",
    "registry_markdown",
]

#: checkpoint jsonl format version — the line shapes below.  Bump only
#: with a new discriminator scheme; the per-kind versions cover value
#: layout changes.
CHECKPOINT_JOURNAL_FORMAT = 1

#: on-disk service WAL format version (``serve/journal.py`` wraps every
#: record in ``{"service_journal_format": ..., "kind": ...,
#: "payload_sha256": ..., "record": ...}`` and skips other versions as
#: corrupt — old journals become clean empty scans, never parse
#: errors).
SERVICE_JOURNAL_FORMAT = 1


def _decode_geometry_plan(value: Any) -> Dict[str, Any]:
    """v1: ``GeometryPlan.to_dict()``.  Per-group plan keys inside are
    decoded by ``taskgrid.PlanKey.from_json``, which accepts both the
    named-dict form and the legacy positional 8/9/10/11-element
    lists older journals hold."""
    return dict(value)


def _decode_prefix_plan(value: Any) -> list:
    """v1: the per-group prefix digest list (``None`` for atomic
    groups), order-aligned with the geometry plan's groups."""
    return list(value)


def _decode_prefix_payload(value: Any) -> Dict[str, Any]:
    """v1: ``{"path": <npz path>}`` — where the journaled prefix
    matrix payload lives.  A missing/torn payload is NOT an error at
    read time (the recompute is bit-exact); extra keys pass through."""
    out = dict(value)
    out["path"] = str(out.get("path", ""))
    return out


def _decode_stream_plan(value: Any) -> Dict[str, Any]:
    """v1: ``StreamPlan.to_dict()`` — the pinned stream-shard geometry
    per-shard accumulator records are addressed under."""
    return dict(value)


def _decode_submitted(value: Any) -> Dict[str, Any]:
    """v1: the submission record.  ``state`` defaults to "admitted"
    (the WAL append and a fast worker's first transition race on file
    order; recovery treats a state-less submission as just admitted)."""
    out = dict(value)
    out.setdefault("state", "admitted")
    return out


def _decode_state(value: Any) -> Dict[str, Any]:
    """v1: a state transition — ``handle`` + ``state`` (one of the
    executor vocabulary; terminal states are
    ``serve.journal.TERMINAL_STATES``)."""
    out = dict(value)
    out["state"] = str(out.get("state", ""))
    return out


def _decode_lease(value: Any) -> Dict[str, Any]:
    """v1: a lease fencing event — the new owner, the fenced pid/owner
    and how stale its last stamp was.  Recovery treats its presence as
    evidence of an unclean predecessor."""
    out = dict(value)
    out.setdefault("event", "fenced")
    return out


def _decode_shutdown(value: Any) -> Dict[str, Any]:
    """v1: a deliberate clean shutdown by ``owner`` — the next startup
    distinguishes it from a crash (no shutdown record = unclean)."""
    out = dict(value)
    out["clean"] = bool(out.get("clean", True))
    return out


#: checkpoint jsonl line shapes, discriminated by key presence — the
#: EXACT precedence ``SearchCheckpoint`` scans with (fault first, then
#: meta, then chunk result; anything else is a torn/foreign line and
#: is skipped).
CHECKPOINT_RECORD_KINDS: Dict[str, Dict[str, Any]] = {
    "fault": {
        "version": 1,
        "discriminator": "fault_chunk_id",
        "description": (
            "launch-supervisor recovery event, journaled durably "
            "BEFORE each retry; never mistaken for a result (even by "
            "pre-fault-journal loaders, which skip it on KeyError)"),
        "decode": dict,
    },
    "meta": {
        "version": 1,
        "discriminator": "meta",
        "description": (
            "journal metadata {\"meta\": name, \"value\": ...}; kinds "
            "declared in CHECKPOINT_META_KINDS, last record wins"),
        "decode": dict,
    },
    "chunk_result": {
        "version": 1,
        "discriminator": "chunk_id",
        "description": (
            "one completed chunk's per-candidate rows (streamed runs "
            "journal per-shard accumulator records under the same "
            "shape, addressed by the pinned stream geometry)"),
        "decode": dict,
    },
}

#: every ``put_meta`` kind any module may write.  ``prefix`` entries
#: are written per fingerprint as ``prefix:<fp>`` — declared here by
#: the ``"prefix:"`` name prefix (``prefix_match=True``).
CHECKPOINT_META_KINDS: Dict[str, Dict[str, Any]] = {
    "geometry_plan": {
        "version": 1,
        "writer": "search/grid.py",
        "prefix_match": False,
        "description": (
            "the pinned launch-geometry plan a resumed search must "
            "replay (chunk ids — and therefore resume hits — only "
            "match under the widths that wrote them)"),
        "decode": _decode_geometry_plan,
    },
    "prefix_plan": {
        "version": 1,
        "writer": "search/grid.py",
        "prefix_match": False,
        "description": (
            "the shared-prefix per-group digest list; a resume whose "
            "digests drifted fails loudly instead of mixing prefix-"
            "staged and atomic chunk results"),
        "decode": _decode_prefix_plan,
    },
    "prefix:": {
        "version": 1,
        "writer": "search/grid.py",
        "prefix_match": True,
        "description": (
            "one computed prefix matrix's durable npz payload "
            "pointer, keyed by the prefix content fingerprint — "
            "kill-resume re-uploads instead of recomputing"),
        "decode": _decode_prefix_payload,
    },
    "stream_plan": {
        "version": 1,
        "writer": "search/stream.py",
        "prefix_match": False,
        "description": (
            "the pinned stream-shard geometry; per-shard accumulator "
            "records are only addressable under the geometry that "
            "wrote them"),
        "decode": _decode_stream_plan,
    },
}

#: every service-WAL record kind (``ServiceJournal.append``'s ``kind``
#: argument).
SERVICE_RECORD_KINDS: Dict[str, Dict[str, Any]] = {
    "submitted": {
        "version": 1,
        "writer": "serve/journal.py",
        "description": (
            "one admission: tenant/weight/family/compile-structure "
            "digest/data fingerprints/checkpoint dir — everything a "
            "successor needs to re-own the search"),
        "decode": _decode_submitted,
    },
    "state": {
        "version": 1,
        "writer": "serve/journal.py",
        "description": (
            "one state transition (admitted → running → finished/"
            "cancelled/failed/shed/recovered) for a journaled handle"),
        "decode": _decode_state,
    },
    # these two were WRITTEN but undeclared until the journal-format
    # rule landed — exactly the drift class this registry exists for
    "lease": {
        "version": 1,
        "writer": "serve/journal.py",
        "description": (
            "a lease fencing event: a new owner took over a stale "
            "lease (fenced pid/owner + staleness); evidence of an "
            "unclean predecessor"),
        "decode": _decode_lease,
    },
    "shutdown": {
        "version": 1,
        "writer": "serve/journal.py",
        "description": (
            "a deliberate clean shutdown by the journal owner; its "
            "absence at next startup means the previous process "
            "crashed or was fenced"),
        "decode": _decode_shutdown,
    },
}


def classify_checkpoint_record(
        rec: Dict[str, Any]) -> Tuple[str, Any, Any]:
    """Classify one parsed checkpoint-journal line.

    Returns ``(kind, key, value)``: ``("fault", chunk_id, rec)``,
    ``("meta", name, value)``, or ``("chunk_result", chunk_id, rec)``
    — the exact key-presence precedence every shipped loader has used,
    so old journals classify identically.  Raises ``KeyError`` for a
    line matching no declared shape (callers skip it as a torn tail,
    exactly as before)."""
    if "fault_chunk_id" in rec:
        return "fault", rec["fault_chunk_id"], rec
    if "meta" in rec and "chunk_id" not in rec:
        return "meta", rec["meta"], rec.get("value")
    return "chunk_result", rec["chunk_id"], rec


def meta_kind_spec(name: str) -> Dict[str, Any]:
    """The registry entry declaring meta kind ``name`` (exact match,
    then declared prefixes).  Raises ``KeyError`` if undeclared."""
    spec = CHECKPOINT_META_KINDS.get(name)
    if spec is not None and not spec["prefix_match"]:
        return spec
    for kind, s in CHECKPOINT_META_KINDS.items():
        if s["prefix_match"] and name.startswith(kind):
            return s
    raise KeyError(name)


def decode_meta(name: str, value: Any) -> Any:
    """Normalize one meta value through its declared back-compat
    decoder (``KeyError`` for undeclared kinds — runtime readers that
    must stay permissive catch it and keep the raw value)."""
    decode: Callable[[Any], Any] = meta_kind_spec(name)["decode"]
    return decode(value)


def registry_markdown() -> str:
    """The journal-record registry tables ``dev/build_api_docs.py``
    renders into ``docs/API.md``."""
    out = [
        "## Journal record registry (`utils/journalspec.py`)\n",
        "\nEvery durable journal record kind, versioned in one place "
        "— held to the write sites by the `journal-format` / "
        "`journal-decoder-missing` rules in `tools/sstlint`.\n",
        f"\nCheckpoint jsonl (format v{CHECKPOINT_JOURNAL_FORMAT}, "
        "discriminated by key presence):\n",
        "\n| kind | v | discriminator | what it holds |\n"
        "|---|---|---|---|\n",
    ]
    for kind, s in CHECKPOINT_RECORD_KINDS.items():
        out.append(f"| `{kind}` | {s['version']} | "
                   f"`{s['discriminator']}` | {s['description']} |\n")
    out.append("\nCheckpoint `put_meta` kinds:\n")
    out.append("\n| kind | v | writer | what it holds |\n"
               "|---|---|---|---|\n")
    for kind, s in CHECKPOINT_META_KINDS.items():
        shown = f"{kind}<fp>" if s["prefix_match"] else kind
        out.append(f"| `{shown}` | {s['version']} | "
                   f"`{s['writer']}` | {s['description']} |\n")
    out.append(f"\nService WAL (format v{SERVICE_JOURNAL_FORMAT}, "
               "checksummed documents):\n")
    out.append("\n| kind | v | writer | what it holds |\n"
               "|---|---|---|---|\n")
    for kind, s in SERVICE_RECORD_KINDS.items():
        out.append(f"| `{kind}` | {s['version']} | "
                   f"`{s['writer']}` | {s['description']} |\n")
    return "".join(out)
