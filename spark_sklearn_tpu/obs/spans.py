"""Span-name vocabulary — the single source of truth for trace names.

Every span, instant and async track the engine records is declared
here, so the three consumers can never drift from each other:

  - the instrumentation sites (``tracer.span("...")`` across the
    package) are linted against this table by ``tools/sstlint``'s
    ``span-unknown-name`` rule — a typo'd or ad-hoc span name fails the
    static-analysis gate instead of silently fragmenting the timeline;
  - ``tools/trace_summary.py`` aggregates exported traces with the
    same table (async spans group by their registered prefix) and
    warns on names it has never heard of;
  - ``dev/build_api_docs.py`` renders the vocabulary into
    ``docs/API.md`` so the trace names users grep for are documented
    from the definitions the code records through.

This module is deliberately import-light (stdlib only): trace_summary
loads it by file path so digesting a trace never pays the jax import.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

__all__ = [
    "SpanDef",
    "SPAN_VOCABULARY",
    "ASYNC_PREFIXES",
    "KNOWN_TRACKS",
    "known_span_names",
    "async_prefix",
    "is_known_span",
    "vocabulary_markdown",
]


class SpanDef(NamedTuple):
    """One registered trace name.

    ``kind``: "span" (complete X event), "instant" (zero-duration
    marker), or "async" (b/e pair on a virtual track; ``name`` is the
    PREFIX — the recorded name may append an identifier, e.g.
    ``launch g0c1:fused``).
    """

    name: str
    kind: str
    module: str
    description: str


#: the registered vocabulary, grouped by recording module.
SPAN_VOCABULARY: Tuple[SpanDef, ...] = (
    # search/grid.py
    SpanDef("search.fit", "span", "search.grid",
            "One whole GridSearchCV/RandomizedSearchCV fit."),
    SpanDef("prevalidate", "span", "search.grid",
            "Candidate-param constraint validation before any launch."),
    SpanDef("refit", "span", "search.grid",
            "The best_estimator_ refit after the sweep."),
    SpanDef("host.fit_and_score", "span", "search.grid",
            "Host-tier per-candidate sklearn _fit_and_score fan-out."),
    SpanDef("geometry.replan", "span", "search.grid",
            "Mid-search geometry re-plan of a halving rung's "
            "surviving candidates (lane reclamation; carries iter and "
            "whether replanning was on)."),
    SpanDef("doctor.analyze", "span", "search.grid",
            "Post-fit critical-path attribution: decomposing the "
            "search wall into lanes (compile, stage, compute, gather, "
            "queue wait, faults, padding, narrowing)."),
    SpanDef("doctor.sentinel", "span", "search.grid",
            "Cross-run regression check of the attribution block "
            "against the persistent run-log baseline."),
    # search/stream.py
    SpanDef("stream.plan", "span", "search.stream",
            "Analytic shard-plan sizing for a streamed search "
            "(carries n_shards, shard_rows, row_bytes, capped)."),
    SpanDef("stream.fit_pass", "span", "search.stream",
            "The streamed FIT pass: every live shard uploaded and "
            "folded into the per-group fit-statistic accumulators."),
    SpanDef("stream.finalize", "span", "search.stream",
            "Per-chunk candidate finalize: vmapped solves over the "
            "folded statistics (one cheap launch per live chunk)."),
    SpanDef("stream.score_pass", "span", "search.stream",
            "The streamed SCORE pass: shards re-streamed through "
            "predict into the default scorer's sufficient statistics."),
    # search/halving.py
    SpanDef("halving.rung", "span", "search.halving",
            "One successive-halving rung: fit + score of the "
            "surviving candidates at this rung's resource (carries "
            "iter, n_candidates, n_resources)."),
    SpanDef("chunkloop.segment", "span", "search.grid",
            "Host-side staging of one scan segment (chunk_loop="
            "\"scan\"): the member chunks' operands stacked along the "
            "leading step axis and uploaded as one slab (carries "
            "group, n_chunks)."),
    SpanDef("chunkloop.scan", "span", "search.grid",
            "One lax.scan launch executing a whole scan segment — "
            "n_chunks member chunks — as a single device program "
            "(carries group, n_chunks, and topk: the on-device rung "
            "elimination's keep count, 0 when the carry is score-"
            "only)."),
    SpanDef("prefix.stage", "span", "search.grid",
            "The shared-prefix stage-1 loop: every DISTINCT Pipeline "
            "prefix digest computed/restored once, vectorized over "
            "folds, before suffix chunks launch (carries "
            "n_distinct)."),
    # parallel/taskgrid.py
    SpanDef("build_compile_groups", "span", "parallel.taskgrid",
            "Partitioning candidates into static-signature groups."),
    SpanDef("pad_chunk", "span", "parallel.taskgrid",
            "Slicing + padding one chunk to its launch width."),
    # parallel/mesh.py
    SpanDef("build_mesh", "span", "parallel.mesh",
            "Mesh construction over the visible devices."),
    SpanDef("device_put.replicate", "span", "parallel.mesh",
            "Replicated device_put (the TPU-native sc.broadcast)."),
    SpanDef("device_put.shard", "span", "parallel.mesh",
            "Leading-axis sharded device_put."),
    SpanDef("device_put.broadcast", "span", "search.grid",
            "The search's whole X/y + fold-mask broadcast phase "
            "(plane-cached uploads; recorded retroactively)."),
    SpanDef("device_get", "span", "parallel.mesh",
            "Blocking device->host transfer."),
    SpanDef("device_get.allgather", "span", "parallel.mesh",
            "Multi-controller device_get via process_allgather."),
    # parallel/dataplane.py
    SpanDef("dataplane.upload", "span", "parallel.dataplane",
            "One host->device transfer (carries `bytes`)."),
    SpanDef("dataplane.tile", "span", "parallel.dataplane",
            "On-device fold-mask tiling (no host transfer)."),
    SpanDef("dataplane.derive", "span", "parallel.dataplane",
            "One derived-buffer materialization (a cache miss in "
            "DataPlane.derived — e.g. a shared-prefix transformed "
            "design matrix; carries `bytes`, `label`)."),
    # parallel/programstore.py
    SpanDef("programstore.load", "span", "parallel.programstore",
            "One AOT-artifact store lookup (carries `bytes`, `hit` and "
            "the serving `source`: memory/disk/miss)."),
    SpanDef("programstore.save", "span", "parallel.programstore",
            "Serialize + atomic publish of one AOT artifact (carries "
            "`bytes`)."),
    SpanDef("programstore.prewarm", "span", "parallel.programstore",
            "Manifest-driven artifact preload at session init."),
    # parallel/pipeline.py
    SpanDef("stage", "span", "parallel.pipeline",
            "Chunk staging (host prep + device_put) on sst-stage."),
    SpanDef("dispatch", "span", "parallel.pipeline",
            "Async launch enqueue (first dispatch includes compile)."),
    SpanDef("compute.wait", "span", "parallel.pipeline",
            "Blocking wait for a launch's outputs on sst-gather."),
    SpanDef("compute", "span", "parallel.pipeline",
            "Device-occupancy estimate on the virtual `device` track."),
    SpanDef("gather", "span", "parallel.pipeline",
            "Blocking device->host result transfer."),
    SpanDef("finalize", "span", "parallel.pipeline",
            "Result writes / checkpoint append, dispatch order."),
    SpanDef("compile", "span", "parallel.pipeline",
            "AOT lower+compile on the sst-compile thread."),
    # parallel/faults.py
    SpanDef("launch.retry", "span", "parallel.faults",
            "Transient-fault retry of a launch's phases."),
    SpanDef("launch.bisect", "span", "parallel.faults",
            "OOM recovery: chunk bisected into half-width launches."),
    SpanDef("launch.host_fallback", "span", "parallel.faults",
            "OOM recovery bottomed out into per-candidate host runs."),
    SpanDef("launch.isolate", "span", "parallel.faults",
            "FATAL recovery: chunk re-run through the quarantine "
            "bisect hook to isolate the poison candidate."),
    # serve/executor.py
    SpanDef("serve.submit", "span", "serve.executor",
            "Admission + enqueue of one submitted search."),
    SpanDef("sched.queue.wait", "span", "serve.executor",
            "A search's dispatch blocked while its chunk waits in the "
            "multi-tenant fair-share queue."),
    SpanDef("sched.dispatch", "span", "serve.executor",
            "One routed chunk launch enqueued on the shared "
            "sst-dispatch loop (carries tenant, handle, cost)."),
    SpanDef("sched.fuse", "span", "serve.executor",
            "One fused launch: same-key chunks from several searches "
            "coalesced into a single wide device program (carries "
            "n_members, lanes, cost)."),
    # serve/journal.py
    SpanDef("journal.append", "span", "serve.journal",
            "One durable service-journal append (checksummed WAL "
            "record, flushed + fsynced before the submit/transition "
            "proceeds; carries kind)."),
    # obs/telemetry.py
    SpanDef("telemetry.sample", "span", "obs.telemetry",
            "One fleet-telemetry sampler tick (provider polls)."),
    # parallel/memledger.py
    SpanDef("memory.sample", "span", "parallel.memledger",
            "One device-memory reconciliation tick: jax memory_stats "
            "across the local devices (carries bytes_in_use and "
            "whether the backend measures at all)."),
    SpanDef("memory.footprint", "instant", "parallel.memledger",
            "One compile group's modeled device footprint registered "
            "with the ledger (carries group, width, chunk_bytes, "
            "modeled_bytes and whether the HBM ceiling capped the "
            "width) — trace_summary digests these into the per-group "
            "memory line."),
    # obs/heartbeat.py
    SpanDef("heartbeat.beat", "instant", "obs.heartbeat",
            "One in-flight device beat from the scanned program's "
            "step body (jax.debug.callback; carries key, group, "
            "step) — only recorded when the heartbeat beacon is on "
            "(TpuConfig.heartbeat / SST_HEARTBEAT)."),
    # utils/session.py
    SpanDef("session.init", "span", "utils.session",
            "TpuSession bootstrap (mesh, caches, fault plan)."),
    SpanDef("session.recover", "span", "utils.session",
            "Warm-restart scan: the service journal's non-terminal "
            "entries folded into a RecoveryReport."),
    # obs/log.py
    SpanDef("log", "instant", "obs.log",
            "A stdout-parity verbose line mirrored onto the timeline."),
    # async virtual tracks (name prefixes)
    SpanDef("launch", "async", "parallel.pipeline",
            "Whole-launch span (dispatch..finalize) per chunk, on the "
            "`launches` track."),
    SpanDef("compile-group", "async", "parallel.pipeline",
            "Compile-group boundary span on the `compile-groups` "
            "track."),
    SpanDef("heartbeat.segment", "async", "obs.heartbeat",
            "One scan segment's register..complete lifetime on the "
            "`progress` track (carries group, steps, beats) — the "
            "per-segment progress lane the Chrome export lays the "
            "heartbeat.beat instants over."),
)

#: async-span name prefixes, longest first so `compile-group 3` never
#: matches a shorter prefix by accident.
ASYNC_PREFIXES: Tuple[str, ...] = tuple(sorted(
    (d.name for d in SPAN_VOCABULARY if d.kind == "async"),
    key=len, reverse=True))

#: virtual track names the exporter lays spans out on.
KNOWN_TRACKS: Tuple[str, ...] = ("device", "launches", "compile-groups",
                                 "progress")


def known_span_names() -> frozenset:
    """Exact (non-async) registered names."""
    return frozenset(d.name for d in SPAN_VOCABULARY if d.kind != "async")


def async_prefix(name: str) -> Optional[str]:
    """The registered async prefix `name` falls under, or None."""
    for p in ASYNC_PREFIXES:
        if name == p or name.startswith(p + " "):
            return p
    return None


def is_known_span(name: str) -> bool:
    """Is `name` (exact span/instant, or a registered async prefix
    form) part of the vocabulary?"""
    return name in known_span_names() or async_prefix(name) is not None


def vocabulary_markdown() -> str:
    """The span-vocabulary table ``dev/build_api_docs.py`` renders into
    ``docs/API.md`` — defined here, next to the vocabulary, so
    sstlint's ``docs-stale`` rule can compare the docs against it
    without importing the (jax-heavy) rest of the package."""
    out = [
        "## Span vocabulary\n",
        "\nEvery trace name the engine records, pinned in "
        "`spark_sklearn_tpu/obs/spans.py` (async entries are name "
        "PREFIXES on virtual tracks).\n",
        "\n| name | kind | module | description |\n|---|---|---|---|\n",
    ]
    for d in SPAN_VOCABULARY:
        out.append(f"| `{d.name}` | {d.kind} | {d.module} | "
                   f"{d.description} |\n")
    return "".join(out)
