"""Fleet exposition — Prometheus text + JSON snapshot over localhost.

The :class:`~spark_sklearn_tpu.obs.telemetry.TelemetryService` owns the
numbers; this module puts them on the wire:

  - :func:`prometheus_text` renders a snapshot in the Prometheus text
    exposition format (``sst_``-prefixed families, tenants as labels),
    so any standard scraper — or a bare ``curl`` — can watch the fleet;
  - :class:`FleetEndpoint` serves ``/metrics`` (Prometheus) and
    ``/snapshot.json`` (the raw snapshot) from a daemon
    ``ThreadingHTTPServer`` bound to ``127.0.0.1`` only.  Owned by
    :class:`~spark_sklearn_tpu.utils.session.TpuSession` when
    ``TpuConfig(telemetry_port)`` / ``SST_TELEMETRY_PORT`` is set
    (default off — constructing a session with telemetry disabled
    creates no socket and no thread).  Port ``0`` binds an ephemeral
    port (tests and ``tools/fleet_top.py`` read it back from
    ``endpoint.port``).

``tools/fleet_top.py`` tails the JSON endpoint into a terminal digest.
"""

from __future__ import annotations

import json
import os
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional

from spark_sklearn_tpu.obs.log import get_logger
from spark_sklearn_tpu.obs.telemetry import TelemetryService, get_telemetry

logger = get_logger(__name__)

__all__ = [
    "FleetEndpoint",
    "prometheus_text",
    "resolve_telemetry_port",
]

#: Prometheus metric-name grammar (validation aid for tests/smoke legs)
METRIC_LINE_RE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^{}]*\})? [-+]?("
    r"[0-9]*\.?[0-9]+([eE][-+]?[0-9]+)?|Inf|NaN)$")


def resolve_telemetry_port(config=None) -> Optional[int]:
    """The configured endpoint port: ``TpuConfig.telemetry_port`` when
    set, else the ``SST_TELEMETRY_PORT`` env var, else None (telemetry
    off).  ``0`` means "bind an ephemeral port"."""
    port = getattr(config, "telemetry_port", None) \
        if config is not None else None
    if port is None:
        env = os.environ.get("SST_TELEMETRY_PORT", "").strip()
        if not env or env.lower() in ("off", "none", "false"):
            return None
        try:
            port = int(env)
        except ValueError:
            logger.warning(
                "SST_TELEMETRY_PORT=%r is not an integer; telemetry "
                "endpoint stays off", env)
            return None
    return int(port)


def _label_escape(v: Any) -> str:
    return str(v).replace("\\", r"\\").replace('"', r'\"').replace(
        "\n", r"\n")


class _Lines:
    """Accumulates exposition lines, emitting each family's # HELP /
    # TYPE header once."""

    def __init__(self):
        self.out: List[str] = []
        self._seen: set = set()

    def add(self, name: str, value: Any, labels: Optional[Dict] = None,
            mtype: str = "gauge", help_text: str = "") -> None:
        if not isinstance(value, (int, float)) or isinstance(value, bool):
            value = float(bool(value)) if isinstance(value, bool) else None
        if value is None:
            return
        if name not in self._seen:
            self._seen.add(name)
            if help_text:
                self.out.append(f"# HELP {name} {help_text}")
            self.out.append(f"# TYPE {name} {mtype}")
        label_s = ""
        if labels:
            inner = ",".join(
                f'{k}="{_label_escape(v)}"'
                for k, v in sorted(labels.items()))
            label_s = "{" + inner + "}"
        self.out.append(f"{name}{label_s} {value}")

    def text(self) -> str:
        return "\n".join(self.out) + "\n"


def prometheus_text(snapshot: Optional[Dict[str, Any]] = None) -> str:
    """Render a telemetry snapshot (default: the global service's) in
    the Prometheus text exposition format."""
    snap = snapshot if snapshot is not None \
        else get_telemetry().snapshot()
    ln = _Lines()
    ln.add("sst_telemetry_enabled", snap.get("enabled", False),
           help_text="1 when the telemetry service is aggregating.")
    ln.add("sst_telemetry_window_seconds", snap.get("window_s", 0.0),
           help_text="Sliding-window span the rates/percentiles cover.")
    ln.add("sst_telemetry_samples_total", snap.get("n_samples", 0),
           mtype="counter",
           help_text="Sampler ticks since the service enabled.")
    for tenant, t in (snap.get("tenants") or {}).items():
        lbl = {"tenant": tenant}
        ln.add("sst_tenant_dispatches_total",
               t.get("dispatches_total", 0), labels=lbl, mtype="counter",
               help_text="Chunk dispatches per tenant.")
        ln.add("sst_tenant_tasks_total", t.get("tasks_total", 0),
               labels=lbl, mtype="counter",
               help_text="Dispatched (candidate x fold) task units per "
                         "tenant.")
        ln.add("sst_tenant_queue_wait_seconds_total",
               t.get("queue_wait_s_total", 0.0), labels=lbl,
               mtype="counter",
               help_text="Total fair-share queue wait per tenant.")
        for q, key in (("0.5", "queue_wait_p50_s"),
                       ("0.95", "queue_wait_p95_s")):
            ln.add("sst_tenant_queue_wait_seconds",
                   t.get(key, 0.0), labels={**lbl, "quantile": q},
                   help_text="Sliding-window queue-wait quantiles per "
                             "tenant (the SLO series).")
        ln.add("sst_tenant_throughput_tasks_per_second",
               t.get("throughput_tasks_per_s", 0.0), labels=lbl,
               help_text="Dispatched task units per second over the "
                         "window.")
        ln.add("sst_tenant_share_frac", t.get("share_frac", 0.0),
               labels=lbl,
               help_text="Tenant's share of all task cost dispatched "
                         "in the window.")
        ln.add("sst_tenant_residency_bytes",
               t.get("residency_bytes", None), labels=lbl,
               help_text="Data-plane bytes resident and charged to the "
                         "tenant.")
    dev = snap.get("device") or {}
    ln.add("sst_device_busy_seconds_window", dev.get("busy_s_window"),
           help_text="Device-busy seconds observed in the window.")
    ln.add("sst_device_occupancy_frac", dev.get("occupancy_frac"),
           help_text="Fraction of the window the device was busy.")
    sched = snap.get("scheduler") or {}
    ln.add("sst_scheduler_dispatches_total",
           sched.get("dispatches_total"), mtype="counter",
           help_text="All chunk dispatches through the executor.")
    ln.add("sst_scheduler_loop_idle_frac", sched.get("loop_idle_frac"),
           help_text="Fraction of the window the shared dispatch loop "
                     "was idle.")
    ln.add("sst_scheduler_queue_depth", sched.get("queue_depth"),
           help_text="Chunk requests currently waiting in the "
                     "fair-share queue.")
    ln.add("sst_scheduler_active_searches", sched.get("n_active"),
           help_text="Searches currently running in the executor.")
    ln.add("sst_scheduler_pending_searches", sched.get("n_pending"),
           help_text="Searches waiting for an admission slot.")
    dp = snap.get("dataplane") or {}
    ln.add("sst_dataplane_h2d_bytes_total", dp.get("h2d_bytes_total"),
           mtype="counter",
           help_text="Host->device bytes transferred through the data "
                     "plane.")
    ln.add("sst_dataplane_h2d_bytes_per_second",
           dp.get("h2d_bytes_per_s"),
           help_text="Host->device transfer rate over the window.")
    ln.add("sst_dataplane_hits_total", dp.get("hits"), mtype="counter",
           help_text="Cumulative data-plane cache hits.")
    ln.add("sst_dataplane_misses_total", dp.get("misses"),
           mtype="counter",
           help_text="Cumulative data-plane cache misses.")
    ln.add("sst_dataplane_bytes_in_cache", dp.get("bytes_in_cache"),
           help_text="Bytes currently resident in the plane.")
    ln.add("sst_dataplane_hits_window", dp.get("hits_window"),
           help_text="Data-plane hits within the sliding window.")
    ln.add("sst_dataplane_misses_window", dp.get("misses_window"),
           help_text="Data-plane misses within the sliding window.")
    ps = snap.get("programstore") or {}
    for key, val in sorted(ps.items()):
        if not isinstance(val, (int, float)) or isinstance(val, bool):
            continue
        ln.add(f"sst_programstore_{key}", val,
               mtype="counter" if key.endswith("_total") else "gauge",
               help_text="Program-store counter (see "
                         "search_report['programstore']).")
    mem = snap.get("memory") or {}
    for dev_id, d in sorted((mem.get("devices") or {}).items()):
        lbl = {"device": dev_id}
        ln.add("sst_memory_device_bytes_in_use",
               d.get("bytes_in_use"), labels=lbl,
               help_text="Allocator bytes in use per device (jax "
                         "memory_stats).")
        ln.add("sst_memory_device_bytes_limit",
               d.get("bytes_limit"), labels=lbl,
               help_text="Allocator byte limit per device (0 when the "
                         "backend reports none).")
        ln.add("sst_memory_device_pressure_frac",
               d.get("pressure_frac"), labels=lbl,
               help_text="Per-device occupancy fraction "
                         "(bytes_in_use / bytes_limit).")
    ln.add("sst_memory_measured", mem.get("measured"),
           help_text="1 when a local device exposes allocator "
                     "memory_stats (0 = ledger runs model-only).")
    ln.add("sst_memory_watermark_bytes", mem.get("watermark_bytes"),
           help_text="Measured bytes-in-use high-water mark sampled "
                     "at launch boundaries.")
    ln.add("sst_memory_modeled_peak_bytes",
           mem.get("modeled_peak_bytes"),
           help_text="Largest modeled in-flight footprint the ledger "
                     "has registered (resident set + widest chunk).")
    ln.add("sst_memory_safety_margin", mem.get("safety_margin"),
           help_text="The footprint model's learned over-provisioning "
                     "factor (trained by observed OOMs).")
    ln.add("sst_memory_oom_observed_total", mem.get("n_oom_observed"),
           mtype="counter",
           help_text="OOM recoveries the ledger has folded into its "
                     "safety margin.")
    faults = snap.get("faults") or {}
    for cls, n in (faults.get("by_class") or {}).items():
        ln.add("sst_faults_total", n, labels={"class": cls},
               mtype="counter",
               help_text="Observed faults by taxonomy class.")
    for action, n in (faults.get("by_action") or {}).items():
        ln.add("sst_fault_actions_total", n, labels={"action": action},
               mtype="counter",
               help_text="Recovery actions by kind "
                         "(retry/bisect/host_fallback/...).")
    reg = snap.get("regression") or {}
    ln.add("sst_regression_checks_total", reg.get("checks_total"),
           mtype="counter",
           help_text="Runs the cross-run sentinel compared against a "
                     "run-log baseline.")
    ln.add("sst_regression_flagged_total", reg.get("flagged_total"),
           mtype="counter",
           help_text="Runs the sentinel flagged as regressed.")
    ln.add("sst_regression_active",
           1 if reg.get("last_status") == "regressed" else
           (0 if reg.get("last_status") else None),
           help_text="1 while the most recent sentinel check flagged a "
                     "regression.")
    for f in (reg.get("last_flags") or []):
        if not isinstance(f, dict):
            continue
        ln.add("sst_regression_delta_seconds", f.get("delta_s"),
               labels={"metric": f.get("metric", ""),
                       "family": reg.get("last_family", "")},
               help_text="Per-lane wall regression vs the run-log "
                         "baseline, from the last flagged check.")
    prot = snap.get("protection") or {}
    ln.add("sst_protection_admitted_total", prot.get("admitted_total"),
           mtype="counter",
           help_text="Searches admitted straight into a running slot.")
    ln.add("sst_protection_queued_total", prot.get("queued_total"),
           mtype="counter",
           help_text="Searches admitted into the bounded waiting line.")
    ln.add("sst_protection_rejected_total", prot.get("rejected_total"),
           mtype="counter",
           help_text="Submissions refused with AdmissionError before "
                     "any device work.")
    for reason, n in (prot.get("rejected_by_reason") or {}).items():
        ln.add("sst_protection_rejected_by_reason_total", n,
               labels={"reason": str(reason)}, mtype="counter",
               help_text="Admission rejections by machine-readable "
                         "reason.")
    ln.add("sst_protection_shed_total", prot.get("shed_total"),
           mtype="counter",
           help_text="Candidates shed to error_score by deadline or "
                     "persistent-fault degradation.")
    ln.add("sst_protection_quarantined_total",
           prot.get("quarantined_total"), mtype="counter",
           help_text="Poison candidates quarantined to error_score "
                     "after K single-lane FATALs.")
    ln.add("sst_protection_deadline_hits_total",
           prot.get("deadline_hits_total"), mtype="counter",
           help_text="Searches whose search_deadline_s expired "
                     "mid-run.")
    fus = snap.get("fusion") or {}
    ln.add("sst_fusion_launches_total", fus.get("fused_total"),
           mtype="counter",
           help_text="Fused launches executed (one wide device program "
                     "serving several searches' same-program chunks).")
    ln.add("sst_fusion_members_total", fus.get("members_total"),
           mtype="counter",
           help_text="Member chunks that rode fused launches.")
    ln.add("sst_fusion_saved_launches_total",
           fus.get("saved_launches_total"), mtype="counter",
           help_text="Device launches avoided by fusion "
                     "(members - 1 per fused launch).")
    ln.add("sst_fusion_lanes_real_total", fus.get("lanes_real_total"),
           mtype="counter",
           help_text="Real candidate lanes carried by fused launches.")
    ln.add("sst_fusion_lanes_padded_total",
           fus.get("lanes_padded_total"), mtype="counter",
           help_text="Padded widths of fused launches (padded - real = "
                     "fleet-wide padding waste).")
    for tenant, n in (fus.get("lanes_borrowed_by_tenant") or {}).items():
        ln.add("sst_fusion_lanes_borrowed_total", n,
               labels={"tenant": str(tenant)}, mtype="counter",
               help_text="Real lanes each tenant ran on fused launches "
                         "led by another search.")
    for tenant, n in (fus.get("lanes_donated_by_tenant") or {}).items():
        ln.add("sst_fusion_lanes_donated_total", n,
               labels={"tenant": str(tenant)}, mtype="counter",
               help_text="Real lanes other tenants ran on fused "
                         "launches this tenant led.")
    flight = snap.get("flight") or {}
    ln.add("sst_flight_records_total", flight.get("n_records"),
           mtype="counter",
           help_text="Events recorded by the flight recorder ring.")
    ln.add("sst_flight_dumps_total", flight.get("n_dumps"),
           mtype="counter",
           help_text="Black-box bundles dumped.")
    hb = snap.get("heartbeat") or {}
    ln.add("sst_heartbeat_beats_total", hb.get("beats_total"),
           mtype="counter",
           help_text="In-flight device beats received from scanned "
                     "launches (one per scan step).")
    ln.add("sst_heartbeat_chunk_beats_total",
           hb.get("chunk_beats_total"), mtype="counter",
           help_text="Dispatch-time beats from the per-chunk launch "
                     "path.")
    ln.add("sst_heartbeat_segments_total", hb.get("segments_total"),
           mtype="counter",
           help_text="Scan segments registered with the heartbeat "
                     "hub.")
    ln.add("sst_heartbeat_live_segments", hb.get("live_segments"),
           help_text="Scanned launches currently in flight and "
                     "beating.")
    ln.add("sst_heartbeat_cadence_seconds",
           hb.get("cadence_p50_s"), labels={"quantile": "0.5"},
           help_text="Inter-beat gap quantiles across segments.")
    ln.add("sst_heartbeat_cadence_seconds",
           hb.get("cadence_p95_s"), labels={"quantile": "0.95"})
    ln.add("sst_heartbeat_staleness_max_seconds",
           hb.get("staleness_max_s"),
           help_text="Largest inter-beat gap observed — what "
                     "heartbeat_timeout_s must exceed.")
    for handle, pr in sorted((hb.get("searches") or {}).items()):
        if not isinstance(pr, dict):
            continue
        lbl = {"handle": str(handle)}
        ln.add("sst_heartbeat_steps_done", pr.get("steps_done"),
               labels=lbl,
               help_text="Scan steps confirmed done per live search "
                         "handle.")
        ln.add("sst_heartbeat_steps_total", pr.get("steps_total"),
               labels=lbl,
               help_text="Scan steps planned per live search handle.")
        ln.add("sst_heartbeat_eta_seconds", pr.get("eta_s"),
               labels=lbl,
               help_text="Blended remaining-time estimate per live "
                         "search handle (geometry model prior + "
                         "observed beat cadence).")
    rec = snap.get("recovery") or {}
    ln.add("sst_recovery_journal_entries_total",
           rec.get("journal_entries_total"), mtype="counter",
           help_text="Verified service-journal WAL records the restart "
                     "scan read.")
    ln.add("sst_recovery_nonterminal_found_total",
           rec.get("nonterminal_found_total"), mtype="counter",
           help_text="Journaled searches found non-terminal at warm "
                     "restart.")
    ln.add("sst_recovery_recovered_total", rec.get("recovered_total"),
           mtype="counter",
           help_text="Searches re-admitted through "
                     "TpuSession.resubmit().")
    ln.add("sst_recovery_mismatch_total", rec.get("mismatch_total"),
           mtype="counter",
           help_text="Resubmissions refused on a data-fingerprint "
                     "mismatch (RecoveryDataMismatchError).")
    ln.add("sst_recovery_lease_takeovers_total",
           rec.get("lease_takeovers_total"), mtype="counter",
           help_text="Stale service-journal leases fenced and taken "
                     "over.")
    ln.add("sst_recovery_lease_conflicts_total",
           rec.get("lease_conflicts_total"), mtype="counter",
           help_text="Lease acquisitions refused by a live owner "
                     "(ServiceLeaseError).")
    ln.add("sst_recovery_unclean_shutdowns_total",
           rec.get("unclean_shutdowns_total"), mtype="counter",
           help_text="Takeovers implying the previous owner died "
                     "without a clean shutdown.")
    ln.add("sst_recovery_time_to_recover_seconds",
           rec.get("time_to_recover_s"),
           help_text="Seconds from the restart's journal scan to its "
                     "first successful resubmit.")
    return ln.text()


class _Handler(BaseHTTPRequestHandler):
    """Routes: /metrics (Prometheus text), /snapshot.json (raw JSON).
    The owning endpoint hangs its service off the server object."""

    server_version = "sst-fleet/1"

    def _respond(self, code: int, body: bytes, ctype: str) -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self) -> None:  # noqa: N802 (http.server API)
        service: TelemetryService = self.server.sst_service
        path = self.path.split("?", 1)[0]
        try:
            if path == "/metrics":
                body = prometheus_text(service.snapshot()).encode()
                self._respond(
                    200, body, "text/plain; version=0.0.4; charset=utf-8")
            elif path in ("/", "/snapshot", "/snapshot.json"):
                body = json.dumps(service.snapshot()).encode()
                self._respond(200, body, "application/json")
            else:
                self._respond(404, b"not found\n", "text/plain")
        except (BrokenPipeError, ConnectionResetError):
            # the scraper went away mid-response; nothing to serve
            pass

    def log_message(self, fmt: str, *args: Any) -> None:
        # route http.server's stderr chatter to the structured channel
        logger.debug("fleet endpoint: " + fmt, *args)


class FleetEndpoint:
    """The localhost telemetry server.  ``start()`` binds and spawns
    the daemon serving thread; ``port`` is the actual bound port
    (meaningful when constructed with port 0); ``stop()`` shuts the
    socket down.  Never binds a non-loopback interface."""

    def __init__(self, port: int, service: Optional[TelemetryService] = None,
                 host: str = "127.0.0.1"):
        self._requested_port = int(port)
        self._host = host
        self._service = service if service is not None else get_telemetry()
        self._server: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> Optional[int]:
        return self._server.server_address[1] if self._server else None

    @property
    def url(self) -> Optional[str]:
        if self._server is None:
            return None
        return f"http://{self._host}:{self.port}"

    def start(self) -> "FleetEndpoint":
        if self._server is not None:
            return self
        server = ThreadingHTTPServer(
            (self._host, self._requested_port), _Handler)
        server.daemon_threads = True
        server.sst_service = self._service
        self._server = server
        self._thread = threading.Thread(
            target=server.serve_forever, name="sst-fleet-http",
            daemon=True)
        self._thread.start()
        logger.info("fleet telemetry endpoint serving on %s "
                    "(/metrics, /snapshot.json)", self.url, url=self.url)
        return self

    def stop(self) -> None:
        server, thread = self._server, self._thread
        self._server = self._thread = None
        if server is not None:
            server.shutdown()
            server.server_close()
        if thread is not None and thread.is_alive():
            thread.join(timeout=5.0)
