"""Critical-path attribution — the search doctor's judgment layer.

PRs 2/8/10 built the sensors: per-launch pipeline timelines, tracer
spans, the scheduler's queue-wait accounting, the fault journal and
the memory ledger.  This module *interprets* them: a deterministic
analyzer that decomposes a search's measured wall into mutually
exclusive lanes, each pinned to one cause —

  compile_s     traced-program construction ('compile' spans, else
                n_compiles x the cost model's compile_wall_s —
                n_compiles counts PROGRAMS built, never chunks or
                launches, so the estimate is launch-shape-invariant:
                a scanned group (chunk_loop="scan", one launch for
                many chunks) and the per-chunk path bill the same
                compile lane)
  stage_s       host->device staging (h2d)
  compute_s     useful device compute
  gather_s      blocking device->host result transfer
  queue_wait_s  multi-tenant fair-share contention
  fault_s       retry backoff / OOM bisection / host-fallback recovery
                (the launch.* recovery spans)
  padding_s     device compute spent on padded lanes
  narrowing_s   modeled extra launch overhead from HBM-capped widths
  other_s       host orchestration outside the launch timeline

The lanes are normalized to sum to ``wall_s`` EXACTLY: when the raw
sums overshoot (pipelined overlap double-counts host phases hidden
behind device compute) every lane scales proportionally; the
remainder otherwise lands in ``other_s``.  The result is rendered as
``search_report["attribution"]`` (schema pinned in
:data:`~spark_sklearn_tpu.obs.metrics.ATTRIBUTION_BLOCK_SCHEMA`),
per-rung for halving searches, with a one-line human verdict naming
the dominant lane and the remedy it implies.

The module is deliberately **stdlib-only** and pure (functions over
plain dicts and span tuples): ``tools/sst_doctor.py`` loads it by
file path to digest saved reports and flight bundles without paying
the jax import.
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

__all__ = [
    "FAULT_SPAN_NAMES",
    "LANES",
    "attribution_block",
    "spans_from_chrome",
    "spans_from_tracer",
]

#: the mutually exclusive wall lanes, in report (and verdict) order
LANES = (
    "compile_s", "stage_s", "compute_s", "gather_s", "queue_wait_s",
    "fault_s", "padding_s", "narrowing_s", "other_s",
)

#: recovery spans whose walls charge the fault lane (parallel/faults.py)
FAULT_SPAN_NAMES = (
    "launch.retry", "launch.bisect", "launch.host_fallback",
)


def _is_compile_span(name: str) -> bool:
    """Only the AOT compile worker's ``compile`` span measures a build
    wall (parallel/pipeline.py ``submit_precompile``).  The async
    ``compile-group <id>`` boundary spans are group ACTIVITY windows
    (first dispatch to last finalize) and must never charge the
    compile lane; builds that compile lazily at first dispatch have no
    span at all and stay on the modeled estimate."""
    return name == "compile"


#: a span distilled to what the analyzer needs: (name, t0_s, t1_s)
Span = Tuple[str, float, float]

#: per-lane verdict templates: dominant lane -> (diagnosis, remedy).
#: Kept data-driven so tools can enumerate the doctor's vocabulary.
_VERDICTS = {
    "compile": ("compile-bound",
                "a prewarmed program store would recover ~{lane:.2f}s"),
    "stage": ("h2d-bound",
              "the device data plane (dataplane_bytes) should absorb "
              "repeat transfers"),
    "compute": ("compute-bound",
                "the search is device-limited (healthy)"),
    "gather": ("gather-bound",
               "raise pipeline_depth to overlap device->host "
               "transfers"),
    "queue_wait": ("contention-bound",
                   "raise tenant_weight or reduce concurrent "
                   "searches"),
    "fault": ("fault-bound",
              "inspect search_report['faults'] and the flight "
              "bundle"),
    "padding": ("padding-bound",
                "geometry_mode='auto' re-planning would narrow "
                "chunk widths"),
    "narrowing": ("memory-narrowed",
                  "raise hbm_budget_bytes to lift the width "
                  "ceiling"),
    "other": ("host-bound",
              "raise pipeline_depth to hide host orchestration "
              "behind device compute"),
}


# ---------------------------------------------------------------------------
# span adapters — both producers reduce to (name, t0_s, t1_s)
# ---------------------------------------------------------------------------


def spans_from_tracer(events: Iterable[Sequence[Any]]) -> List[Span]:
    """Distill tracer ``Event`` tuples (``obs/trace.py``: ``(ph, name,
    t0, t1, track_key, track_name, attrs)``) to the complete spans the
    analyzer consumes, in the perf_counter timebase the pipeline's
    ``epoch_s`` shares."""
    out: List[Span] = []
    for ev in events:
        # "X" thread spans and "b" async-track spans both carry full
        # (t0, t1) bounds in the tuple (compile-group boundaries are
        # async: group g+1's stage may overlap group g's finalize)
        if ev[0] not in ("X", "b") or ev[3] is None:
            continue
        name = ev[1]
        if _is_compile_span(name) or name in FAULT_SPAN_NAMES:
            out.append((name, float(ev[2]), float(ev[3])))
    return out


def spans_from_chrome(trace_events: Iterable[Dict[str, Any]]) -> List[Span]:
    """Distill Chrome ``traceEvents`` dicts (flight bundles, exported
    traces) to analyzer spans.  Chrome timestamps are rebased to the
    earliest event, so these spans carry correct DURATIONS but not the
    pipeline's timebase — whole-search lanes are exact, per-rung span
    clipping degrades to zero."""
    out: List[Span] = []
    open_async: Dict[Any, Tuple[str, float]] = {}
    for ev in trace_events:
        name = ev.get("name", "")
        if not (_is_compile_span(name) or name in FAULT_SPAN_NAMES):
            continue
        ph = ev.get("ph")
        if ph == "X":
            t0 = float(ev.get("ts", 0.0)) / 1e6
            out.append((name, t0, t0 + float(ev.get("dur", 0.0)) / 1e6))
        elif ph == "b":
            # async pair (obs/export.py): b/e events matched by id
            open_async[(name, ev.get("id"))] = (
                name, float(ev.get("ts", 0.0)) / 1e6)
        elif ph == "e":
            opened = open_async.pop((name, ev.get("id")), None)
            if opened is not None:
                out.append((opened[0], opened[1],
                            float(ev.get("ts", 0.0)) / 1e6))
    return out


# ---------------------------------------------------------------------------
# lane math
# ---------------------------------------------------------------------------


def _span_walls(spans: Iterable[Span],
                window: Optional[Tuple[float, float]] = None,
                ) -> Tuple[float, float, int]:
    """(compile_s, fault_s, n_compile_spans) — span walls summed, or
    clipped to ``window`` (absolute perf_counter bounds) when slicing
    one halving rung."""
    compile_s = fault_s = 0.0
    n_compile = 0
    for name, t0, t1 in spans:
        dur = t1 - t0
        if window is not None:
            dur = min(t1, window[1]) - max(t0, window[0])
        if dur <= 0.0:
            continue
        if _is_compile_span(name):
            compile_s += dur
            n_compile += 1
        else:
            fault_s += dur
    return compile_s, fault_s, n_compile


def _timeline_sums(launches: Sequence[Dict[str, Any]],
                   waste_frac: float) -> Dict[str, float]:
    """Raw per-cause seconds from a slice of the pipeline's per-launch
    timeline.  Padding is carved out of device compute via the
    measured mean padded-lane fraction."""
    stage = gather = queue = compute = 0.0
    for rec in launches:
        stage += rec.get("stage_s", 0.0)
        gather += rec.get("gather_s", 0.0)
        queue += rec.get("queue_wait_s", 0.0)
        compute += rec.get("compute_s", 0.0)
    waste = min(1.0, max(0.0, waste_frac))
    return {
        "stage_s": stage,
        "gather_s": gather,
        "queue_wait_s": queue,
        "compute_s": compute * (1.0 - waste),
        "padding_s": compute * waste,
    }


def _normalize(lanes: Dict[str, float], wall_s: float) -> Dict[str, float]:
    """Make the lanes sum to ``wall_s`` exactly: proportional scaling
    when the raw sums overshoot (pipelined overlap), the remainder
    into ``other_s`` otherwise."""
    out = dict(lanes)
    out.setdefault("other_s", 0.0)
    known = sum(v for k, v in out.items() if k != "other_s")
    if wall_s <= 0.0:
        scale = 0.0
        out = {k: 0.0 for k in out}
    elif known > wall_s:
        scale = wall_s / known
        out = {k: v * scale for k, v in out.items()}
        out["other_s"] = 0.0
    else:
        out["other_s"] = wall_s - known
    out = {k: round(v, 6) for k, v in out.items()}
    # re-absorb the rounding residue so the pinned invariant
    # (sum(lanes) == wall_s) survives the 6-decimal rendering
    resid = round(wall_s, 6) - sum(out.values())
    out["other_s"] = max(0.0, round(out["other_s"] + resid, 6))
    return out


def _dominant(lanes: Dict[str, float]) -> str:
    best = LANES[0]
    for name in LANES:
        if lanes.get(name, 0.0) > lanes.get(best, 0.0):
            best = name
    return best[:-2]   # strip the _s suffix


def _verdict(lanes: Dict[str, float], wall_s: float, dominant: str,
             n_compiles: int, compile_source: str,
             n_launches: int) -> str:
    lane = lanes.get(dominant + "_s", 0.0)
    pct = int(round(100.0 * lane / wall_s)) if wall_s > 0 else 0
    diagnosis, remedy = _VERDICTS[dominant]
    if dominant == "compile":
        detail = (f"{pct}% of wall in {n_compiles} "
                  f"{compile_source} build(s)")
    elif dominant == "compute":
        detail = (f"{pct}% of wall on device across "
                  f"{n_launches} launch(es)")
    else:
        detail = f"{pct}% of wall"
    return f"{diagnosis}: {detail}; {remedy.format(lane=lane)}"


def _empty_regression() -> Dict[str, Any]:
    """The sentinel-off placeholder; ``obs/runlog.py`` overwrites it in
    place when a run log is active."""
    return dict(status="off")


def _rung_records(halving: Dict[str, Any],
                  launches: Sequence[Dict[str, Any]],
                  spans: Sequence[Span], epoch_s: float,
                  waste_frac: float) -> List[Dict[str, Any]]:
    """One lane decomposition per halving rung, over the rung's slice
    of the launch timeline (``launches_end`` boundaries recorded by
    the rung scheduler).  Compile/fault spans are clipped to the
    rung's time window; narrowing stays whole-search only."""
    out: List[Dict[str, Any]] = []
    prev = 0
    for r in halving.get("rungs", ()):
        end = int(r.get("launches_end", prev))
        chunk = launches[prev:end]
        prev = end
        lanes = _timeline_sums(chunk, waste_frac)
        window = None
        bounds = [(rec["t0_s"], rec["t1_s"]) for rec in chunk
                  if "t0_s" in rec and "t1_s" in rec]
        if bounds and epoch_s > 0.0:
            window = (epoch_s + min(b[0] for b in bounds),
                      epoch_s + max(b[1] for b in bounds))
        compile_s = fault_s = 0.0
        if window is not None:
            compile_s, fault_s, _ = _span_walls(spans, window)
        lanes["compile_s"] = compile_s
        lanes["fault_s"] = fault_s
        lanes["narrowing_s"] = 0.0
        wall = float(r.get("wall_s", 0.0))
        lanes = _normalize(lanes, wall)
        rec = dict(iter=int(r.get("iter", len(out))),
                   wall_s=round(wall, 6))
        rec.update((k, lanes.get(k, 0.0)) for k in LANES)
        rec["dominant"] = _dominant(lanes)
        out.append(rec)
    return out


# ---------------------------------------------------------------------------
# the block builder — the registered producer of ATTRIBUTION_BLOCK_SCHEMA
# ---------------------------------------------------------------------------


def attribution_block(report: Dict[str, Any], wall_s: float,
                      spans: Sequence[Span] = ()) -> Dict[str, Any]:
    """Decompose ``wall_s`` (the measured search wall) into the pinned
    lanes using the blocks already rendered into ``report`` plus the
    distilled ``spans``, and return the attribution block.

    Deterministic: same report + spans + wall in, same block out — the
    doctor CLI re-running the analyzer on a saved report reproduces
    the in-process verdict bit-for-bit.
    """
    pipe = report.get("pipeline") or {}
    launches = pipe.get("launches") or []
    n_compiles = int(pipe.get("n_compiles", 0) or 0)
    waste = float((report.get("padding_waste") or {}).get("mean")
                  or 0.0)
    cost = (report.get("geometry") or {}).get("cost_model") or {}
    mem_groups = (report.get("memory") or {}).get("groups") or []
    n_capped = sum(1 for g in mem_groups if g.get("capped"))
    epoch_s = float(pipe.get("epoch_s", 0.0) or 0.0)

    compile_traced, fault_s, n_spans = _span_walls(spans)
    if n_spans > 0:
        compile_source = "traced"
        compile_s = compile_traced
    else:
        compile_source = "modeled"
        # n_compiles is the pipeline's PROGRAM build count (grid.py
        # bills _program_build_count deltas), and compile_wall_s is
        # the cost model's per-program EMA (observe(n_builds=...)) —
        # both sides count programs, so coarse launch shapes (a
        # scanned compile group is ONE launch serving many chunks)
        # don't inflate the modeled compile lane
        compile_s = n_compiles * float(cost.get("compile_wall_s", 0.0)
                                       or 0.0)
        if compile_s <= 0.0 and n_compiles > 0:
            # uncalibrated cost model (first-ever run): each group's
            # first dispatch blocks on its build, so the dispatch wall
            # is the best untraced compile estimate available
            compile_s = float(pipe.get("dispatch_wall_s", 0.0) or 0.0)

    lanes = _timeline_sums(launches, waste)
    lanes["compile_s"] = compile_s
    lanes["fault_s"] = fault_s
    lanes["narrowing_s"] = n_capped * float(
        cost.get("launch_overhead_s", 0.0) or 0.0)
    lanes = _normalize(lanes, float(wall_s))

    dominant = _dominant(lanes)
    verdict = _verdict(lanes, float(wall_s), dominant, n_compiles,
                       compile_source, len(launches))
    # cross-search fusion note: when the scheduler fused this search's
    # chunks into shared launches, name the lane exchange and where
    # the scatter cost lands — fused result slicing is lazy device
    # slicing materialized at gather, so its overhead rides gather_s
    sched = report.get("scheduler") or {}
    n_fused = int(sched.get("n_fused", 0) or 0)
    if n_fused > 0:
        verdict += (
            f" [{n_fused} chunk(s) rode cross-search fused launches "
            f"(lanes borrowed {int(sched.get('lanes_borrowed', 0) or 0)},"
            f" donated {int(sched.get('lanes_donated', 0) or 0)}); "
            "per-member scatter overhead rides the gather lane]")
    rungs = _rung_records(report.get("halving") or {}, launches,
                          spans, epoch_s, waste)
    return {
        "enabled": True,
        "wall_s": round(float(wall_s), 6),
        "compile_s": lanes["compile_s"],
        "stage_s": lanes["stage_s"],
        "compute_s": lanes["compute_s"],
        "gather_s": lanes["gather_s"],
        "queue_wait_s": lanes["queue_wait_s"],
        "fault_s": lanes["fault_s"],
        "padding_s": lanes["padding_s"],
        "narrowing_s": lanes["narrowing_s"],
        "other_s": lanes["other_s"],
        "compile_source": compile_source,
        "n_compiles": n_compiles,
        "dominant": dominant,
        "verdict": verdict,
        "rungs": rungs,
        "regression": _empty_regression(),
    }
