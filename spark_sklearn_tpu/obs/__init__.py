"""Observability subsystem — tracing, metrics, structured logging.

The performance story of this engine (pipelined chunk launches, AOT
compile-ahead, persistent compile caches) lives or dies on being able to
*see* where wall-clock goes — the executor-timeline problem of
distributed-Spark ML (arXiv:1612.01437) and the per-stage-visibility
problem of MPMD pipeline schedulers (arXiv:2412.14374).  Four pieces:

  - ``obs.trace``   — a low-overhead, thread-aware span tracer recording
    into a bounded in-memory ring buffer (documented <2% overhead
    budget, enforced by test; exactly zero recorded work when disabled);
  - ``obs.export``  — Chrome trace-event JSON export: load the file in
    Perfetto (https://ui.perfetto.dev) or ``chrome://tracing`` to see
    the stage/dispatch/compute/gather threads, compile-group boundaries
    and per-launch chunk spans on a shared timeline;
  - ``obs.metrics`` — a registry of named counters/gauges/histograms
    behind ``search_report``: the report's schema is pinned in ONE
    place (``SEARCH_REPORT_SCHEMA``) instead of hand-assembled dicts;
  - ``obs.log``     — a structured logger the ``verbose > 0`` paths
    route through; its stdout-parity emit preserves sklearn's
    ``[CV i/n] END ...`` line format byte-for-byte;
  - ``obs.telemetry`` + ``obs.fleet`` — fleet telemetry for the
    multi-tenant serving path: a process-wide sampler aggregating
    per-tenant SLO series (queue-wait p50/p95, throughput, share),
    device occupancy and fault counters across searches, a localhost
    Prometheus/JSON endpoint owned by the session
    (``TpuConfig(telemetry_port)`` / ``SST_TELEMETRY_PORT``), and an
    always-on flight recorder that dumps a correlated black-box bundle
    to ``SST_FLIGHT_DIR`` on FATAL faults, watchdog timeouts, OOMs,
    cancellations and store quarantines;
  - ``obs.heartbeat`` — in-flight device telemetry for the scanned
    chunk loop: a ``jax.debug.callback`` beacon in the scan step body
    feeds a process-global ``HeartbeatHub`` (live progress/ETA, the
    heartbeat-aware watchdog, the ``search_report["heartbeat"]``
    block), enabled with ``TpuConfig(heartbeat=True)`` /
    ``SST_HEARTBEAT`` — off is an exact no-op.

Enable tracing per search with ``TpuConfig(trace=True)`` (record only)
or ``TpuConfig(trace="out.json")`` (record + export), or process-wide
with the ``SST_TRACE`` environment variable (``1`` or a path).
"""

from spark_sklearn_tpu.obs.trace import (
    Tracer,
    current_correlation,
    get_tracer,
    search_tracing,
    set_correlation,
)
from spark_sklearn_tpu.obs.export import chrome_trace_events, export_chrome_trace
from spark_sklearn_tpu.obs.metrics import (
    SEARCH_REPORT_SCHEMA,
    MetricsRegistry,
    schema_markdown,
    search_registry,
)
from spark_sklearn_tpu.obs.log import StructuredLogger, get_logger
from spark_sklearn_tpu.obs.telemetry import (
    FlightRecorder,
    TelemetryService,
    flight_recorder,
    get_telemetry,
)

#: obs.fleet re-exports resolve lazily (PEP 562): fleet pulls in
#: http.server, which every `import spark_sklearn_tpu` would otherwise
#: pay at startup with telemetry off — against the zero-cold-start
#: objective.  The session imports fleet only when telemetry_port is
#: actually configured.
_FLEET_EXPORTS = ("FleetEndpoint", "prometheus_text",
                  "resolve_telemetry_port")


def __getattr__(name):
    if name in _FLEET_EXPORTS:
        from spark_sklearn_tpu.obs import fleet
        return getattr(fleet, name)
    raise AttributeError(
        f"module {__name__!r} has no attribute {name!r}")

__all__ = [
    "Tracer",
    "current_correlation",
    "get_tracer",
    "search_tracing",
    "set_correlation",
    "chrome_trace_events",
    "export_chrome_trace",
    "MetricsRegistry",
    "SEARCH_REPORT_SCHEMA",
    "search_registry",
    "schema_markdown",
    "StructuredLogger",
    "get_logger",
    "FlightRecorder",
    "TelemetryService",
    "flight_recorder",
    "get_telemetry",
    "FleetEndpoint",
    "prometheus_text",
    "resolve_telemetry_port",
]
