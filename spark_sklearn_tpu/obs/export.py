"""Chrome trace-event JSON export.

Serializes the tracer's ring buffer into the Trace Event Format that
Perfetto (https://ui.perfetto.dev) and ``chrome://tracing`` load
directly: ``{"traceEvents": [...]}`` with

  - ``ph: "X"`` complete events for thread-local spans (``ts``/``dur``
    in microseconds, ``pid``/``tid`` integers, attributes in ``args``);
  - ``ph: "b"``/``"e"`` async pairs for spans that may overlap on one
    virtual track (per-launch chunk spans, compile-group boundaries);
  - ``ph: "i"`` instants for zero-duration markers;
  - ``ph: "M"`` metadata naming the process and each thread/track, so
    the viewer shows ``sst-stage``/``sst-gather``/``sst-compile``/
    ``device`` tracks by name.

Timestamps are rebased to the earliest event so the viewer opens at
t=0 regardless of process uptime.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional

from spark_sklearn_tpu.obs.trace import Event, get_tracer

__all__ = ["chrome_trace_events", "export_chrome_trace"]

#: stable viewer ordering: the dispatching main thread first, then the
#: pipeline workers, then the virtual tracks
_SORT_HINTS = (
    ("MainThread", 0),
    ("sst-stage", 1),
    ("sst-compile", 2),
    ("sst-gather", 3),
    ("device", 10),
    ("launches", 11),
    ("compile-groups", 12),
    ("progress", 13),
)


def _jsonable(v: Any) -> Any:
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    return str(v)


def _sort_index(track_name: str) -> int:
    for prefix, idx in _SORT_HINTS:
        if track_name.startswith(prefix):
            return idx
    return 5


def chrome_trace_events(events: Optional[List[Event]] = None,
                        pid: Optional[int] = None) -> List[Dict[str, Any]]:
    """Convert tracer events (default: the global tracer's buffer) to a
    list of Chrome trace-event dicts."""
    if events is None:
        events = get_tracer().events()
    pid = os.getpid() if pid is None else pid
    out: List[Dict[str, Any]] = [{
        "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
        "args": {"name": "spark_sklearn_tpu"},
    }]
    if not events:
        return out
    t_base = min(e[2] for e in events)
    tids: Dict[Any, int] = {}

    def tid_for(key: Any, tname: str) -> int:
        # composite key: CPython recycles thread idents, so a later
        # thread (e.g. the next search's sst-stage) can reuse a dead
        # thread's ident — the name keeps their tracks separate
        tkey = (key, tname)
        tid = tids.get(tkey)
        if tid is None:
            tid = tids[tkey] = len(tids) + 1
            out.append({
                "name": "thread_name", "ph": "M", "pid": pid, "tid": tid,
                "args": {"name": str(tname)},
            })
            out.append({
                "name": "thread_sort_index", "ph": "M", "pid": pid,
                "tid": tid, "args": {"sort_index": _sort_index(str(tname))},
            })
        return tid

    async_id = 0
    for ph, name, t0, t1, key, tname, attrs in events:
        tid = tid_for(key, tname)
        ts = round((t0 - t_base) * 1e6, 3)
        args = {k: _jsonable(v) for k, v in attrs.items()}
        if ph == "X":
            out.append({
                "name": name, "cat": "sst", "ph": "X", "ts": ts,
                "dur": round((t1 - t0) * 1e6, 3), "pid": pid, "tid": tid,
                "args": args,
            })
        elif ph == "i":
            out.append({
                "name": name, "cat": "sst", "ph": "i", "s": "t", "ts": ts,
                "pid": pid, "tid": tid, "args": args,
            })
        else:  # "b": async span -> b/e pair
            async_id += 1
            base = {"name": name, "cat": "sst-async", "pid": pid,
                    "tid": tid, "id": async_id}
            out.append({**base, "ph": "b", "ts": ts, "args": args})
            out.append({**base, "ph": "e",
                        "ts": round((t1 - t_base) * 1e6, 3)})
    return out


def export_chrome_trace(path: str,
                        events: Optional[List[Event]] = None) -> str:
    """Write a Perfetto/``chrome://tracing``-loadable JSON file and
    return its path.  Parent directories are created as needed."""
    parent = os.path.dirname(os.path.abspath(path))
    if parent:
        os.makedirs(parent, exist_ok=True)
    payload = {
        "traceEvents": chrome_trace_events(events),
        "displayTimeUnit": "ms",
    }
    with open(path, "w") as f:
        json.dump(payload, f)
    return path
