"""Persistent run history + the cross-run regression sentinel.

The attribution analyzer (``obs/attribution.py``) judges ONE run; this
module remembers what "normal" looks like.  :class:`RunLog` is a
ProgramStore-style persistent store (``parallel/programstore.py``):
records live under a directory versioned by run-log format and the
stable environment digest (``obs/provenance.py``), every append is an
atomic checksummed write (tmp + fsync + ``os.replace`` via
``utils/atomic.py``; a torn or bit-rotted record is skipped, never a
failed search), and the store is byte-budgeted with oldest-first
pruning.  Each record carries the search's attribution block, launch
geometry, compile count, cost-model state and provenance stamp,
keyed by ``(estimator family, compile-structure digest)`` — the same
identity the program store uses, so "the same search" means the same
compiled structure, not merely the same estimator class.

The **regression sentinel** compares each new run's attribution lanes
(wall / compile / queue wait / padding) against the newest stored
baseline for its key: a lane that grew beyond the noise band
(``TpuConfig.runlog_noise_frac``, plus an absolute floor so
microsecond jitter never pages anyone) flags a regression into the
search report (``attribution["regression"]``), the fleet-telemetry
snapshot (``regression`` block, ``sst_regression_*`` on ``/metrics``)
and a flight-style sentinel bundle (``obs/telemetry.FlightRecorder``)
that ``tools/sst_doctor.py`` digests post-mortem.

``TpuConfig(runlog=False)`` — or simply no configured directory
(``runlog_dir`` / ``SST_RUNLOG_DIR``) — is an exact no-op: no store,
no records, byte-identical reports.
"""

from __future__ import annotations

import hashlib
import json
import os
import time
from typing import Any, Dict, List, Optional

from spark_sklearn_tpu.obs import provenance as _provenance
from spark_sklearn_tpu.obs.log import get_logger
from spark_sklearn_tpu.utils.atomic import atomic_write as _atomic_write
from spark_sklearn_tpu.utils.locks import named_lock

logger = get_logger(__name__)

__all__ = [
    "DEFAULT_NOISE_FRAC",
    "DEFAULT_RUNLOG_BUDGET",
    "RUNLOG_FORMAT",
    "RunLog",
    "activate_runlog",
    "active_runlog",
    "compare_to_baseline",
    "deactivate_runlog",
    "note_run",
    "structure_digest",
]

#: on-disk format version: bump when the record layout changes — old
#: run logs become clean no-baseline lookups, never parse errors.
RUNLOG_FORMAT = 1

#: default store byte budget (32 MiB): thousands of bench-scale run
#: records; oldest records prune beyond it.
DEFAULT_RUNLOG_BUDGET = 32 * 2 ** 20

#: default relative noise band: a lane must grow beyond baseline x
#: (1 + frac) before the sentinel flags it.
DEFAULT_NOISE_FRAC = 0.25

#: absolute floor (seconds) under the relative band: sub-50ms growth
#: is timer jitter at bench scale, never a regression.
_ABS_FLOOR_S = 0.05

#: the attribution lanes the sentinel watches (ISSUE: wall / compile /
#: queue wait / padding)
_SENTINEL_LANES = ("wall_s", "compile_s", "queue_wait_s", "padding_s")

_SUFFIX = ".json"


def _slug(s: str, n: int = 40) -> str:
    return "".join(c if c.isalnum() or c in "-_" else "_"
                   for c in str(s))[:n]


def structure_digest(*parts: Any) -> str:
    """Stable digest of a search's structural identity (family,
    estimator class, candidate/fold counts, data shape, dtype) — the
    second half of a run record's baseline key."""
    h = hashlib.blake2b(repr(tuple(parts)).encode(), digest_size=8)
    return h.hexdigest()


class RunLog:
    """Byte-budgeted on-disk history of per-search run records.

    Layout::

        <directory>/v<RUNLOG_FORMAT>/<env_digest>/run-*.json

    Records from other jax versions / device fleets live under other
    ``env_digest`` directories, so a baseline can never be compared
    across environments.  Thread-safe: concurrent searches submitted
    to one session all append at fit end.
    """

    def __init__(self, directory: str,
                 byte_budget: int = DEFAULT_RUNLOG_BUDGET,
                 noise_frac: float = DEFAULT_NOISE_FRAC):
        self.directory = os.path.abspath(directory)
        self.env = _provenance.env_fingerprint(include_pid=False)
        self.env_digest = _provenance.env_digest()
        self.byte_budget = int(byte_budget)
        self.noise_frac = float(noise_frac)
        self._dir = os.path.join(
            self.directory, f"v{RUNLOG_FORMAT}", self.env_digest)
        os.makedirs(self._dir, exist_ok=True)
        self._lock = named_lock("runlog.RunLog._lock")
        self._seq = 0
        self._counts = {"appends": 0, "corrupt": 0, "evictions": 0,
                        "checks": 0, "flagged": 0}

    # -- naming ------------------------------------------------------------
    @staticmethod
    def key(family: str, structure_digest: str) -> str:
        return f"run-{_slug(family)}-{_slug(structure_digest, 16)}"

    def path_for(self, name: str) -> str:
        return os.path.join(self._dir, name)

    # -- record IO ---------------------------------------------------------
    def append(self, family: str, structure_digest: str,
               record: Dict[str, Any]) -> Optional[str]:
        """Atomically persist one run record and return its path (or
        None on failure — history is an optimization, never a failed
        search).  The payload is checksummed so a torn write is
        detected at read time, and the store is pruned back under its
        byte budget afterwards."""
        payload = json.dumps(record, sort_keys=True, default=str)
        doc = {
            "runlog_format": RUNLOG_FORMAT,
            "family": str(family),
            "structure_digest": str(structure_digest),
            "payload_sha256": hashlib.sha256(payload.encode()).hexdigest(),
            "record": json.loads(payload),
        }
        with self._lock:
            self._seq += 1
            seq = self._seq
            self._counts["appends"] += 1
        name = (f"{self.key(family, structure_digest)}"
                f"-{os.getpid()}-{seq:04d}{_SUFFIX}")
        path = self.path_for(name)
        try:
            _atomic_write(path, json.dumps(doc).encode())
        except (OSError, TypeError, ValueError) as exc:
            logger.warning("run log: append failed for %s (%r)",
                           name, exc)
            return None
        self._evict_over_budget(keep=name)
        return path

    def _read_record(self, path: str) -> Optional[Dict[str, Any]]:
        """One verified record document, or None (mismatched format or
        failed checksum — a clean skip either way)."""
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, json.JSONDecodeError, UnicodeDecodeError):
            with self._lock:
                self._counts["corrupt"] += 1
            return None
        if doc.get("runlog_format") != RUNLOG_FORMAT:
            return None
        payload = json.dumps(doc.get("record", {}), sort_keys=True,
                             default=str)
        sha = hashlib.sha256(payload.encode()).hexdigest()
        if sha != doc.get("payload_sha256"):
            with self._lock:
                self._counts["corrupt"] += 1
            return None
        return doc

    def records(self, family: Optional[str] = None,
                structure_digest: Optional[str] = None,
                ) -> List[Dict[str, Any]]:
        """Verified record documents (newest first), optionally
        filtered to one ``(family, structure digest)`` key."""
        prefix = None
        if family is not None and structure_digest is not None:
            prefix = self.key(family, structure_digest)
        entries = []
        try:
            for fn in os.listdir(self._dir):
                if not fn.endswith(_SUFFIX):
                    continue
                if prefix is not None and not fn.startswith(prefix):
                    continue
                st = os.stat(os.path.join(self._dir, fn))
                entries.append((st.st_mtime, fn))
        except OSError:
            return []
        out = []
        for _, fn in sorted(entries, reverse=True):
            doc = self._read_record(self.path_for(fn))
            if doc is not None:
                out.append(doc)
        return out

    def baseline(self, family: str,
                 structure_digest: str) -> Optional[Dict[str, Any]]:
        """The newest verified record for this key — what the sentinel
        compares a fresh run against."""
        docs = self.records(family, structure_digest)
        return docs[0]["record"] if docs else None

    # -- pruning -----------------------------------------------------------
    def _evict_over_budget(self, keep: Optional[str] = None) -> None:
        try:
            entries = []
            for fn in os.listdir(self._dir):
                if not fn.endswith(_SUFFIX):
                    continue
                st = os.stat(os.path.join(self._dir, fn))
                entries.append((st.st_mtime, st.st_size, fn))
            total = sum(e[1] for e in entries)
            entries.sort()
            evicted = 0
            for _mtime, size, fn in entries:
                if total <= self.byte_budget or fn == keep:
                    continue
                os.remove(self.path_for(fn))
                total -= size
                evicted += 1
            if evicted:
                with self._lock:
                    self._counts["evictions"] += evicted
        except OSError as exc:
            logger.debug("run log eviction scan failed: %r", exc)

    # -- stats -------------------------------------------------------------
    def note_check(self, flagged: bool) -> None:
        """Count one sentinel comparison (and whether it flagged)."""
        with self._lock:
            self._counts["checks"] += 1
            if flagged:
                self._counts["flagged"] += 1

    def counts(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._counts)

    def disk_stats(self) -> Dict[str, int]:
        n = total = 0
        try:
            for fn in os.listdir(self._dir):
                if fn.endswith(_SUFFIX):
                    n += 1
                    total += os.stat(self.path_for(fn)).st_size
        except OSError:
            pass
        return {"n_records": n, "log_bytes": total}


# ---------------------------------------------------------------------------
# the sentinel comparison
# ---------------------------------------------------------------------------


def compare_to_baseline(baseline: Optional[Dict[str, Any]],
                        attribution: Dict[str, Any],
                        noise_frac: float = DEFAULT_NOISE_FRAC,
                        ) -> Dict[str, Any]:
    """The ``attribution["regression"]`` struct: this run's watched
    lanes vs the stored baseline's, flagged when a lane grew beyond
    ``baseline x (1 + noise_frac)`` AND by more than the absolute
    floor.  Deterministic and stdlib-pure so tests (and the doctor)
    can re-judge a saved pair of records."""
    if baseline is None:
        return {"status": "no-baseline", "noise_frac": round(
            float(noise_frac), 6), "flags": []}
    base_attr = baseline.get("attribution") or {}
    flags: List[Dict[str, Any]] = []
    for lane in _SENTINEL_LANES:
        base = float(base_attr.get(lane, 0.0) or 0.0)
        cur = float(attribution.get(lane, 0.0) or 0.0)
        delta = cur - base
        band = max(noise_frac * base, _ABS_FLOOR_S)
        if delta > band:
            flags.append({
                "metric": lane,
                "baseline_s": round(base, 6),
                "current_s": round(cur, 6),
                "delta_s": round(delta, 6),
                "ratio": round(cur / base, 4) if base > 0 else 0.0,
            })
    return {
        "status": "regressed" if flags else "none",
        "baseline_ts_unix_s": float(baseline.get("ts_unix_s", 0.0)),
        "baseline_wall_s": round(float(
            base_attr.get("wall_s", 0.0) or 0.0), 6),
        "noise_frac": round(float(noise_frac), 6),
        "flags": flags,
    }


# ---------------------------------------------------------------------------
# Process-global activation (mirrors programstore.activate_store)
# ---------------------------------------------------------------------------

_RUNLOG: Optional[RunLog] = None
_RUNLOG_LOCK = named_lock("runlog._RUNLOG_LOCK")


def _resolve_dir(config) -> Optional[str]:
    if config is not None and not getattr(config, "runlog", True):
        return None
    d = getattr(config, "runlog_dir", None) if config is not None \
        else None
    if not d:
        d = os.environ.get("SST_RUNLOG_DIR", "").strip() or None
    return d


def _resolve_budget(config) -> int:
    b = getattr(config, "runlog_bytes", None) if config is not None \
        else None
    if b is None:
        env = os.environ.get("SST_RUNLOG_BYTES", "").strip()
        if env:
            # a typo'd budget fails loudly at activation, not mid-search
            b = int(env)
    return DEFAULT_RUNLOG_BUDGET if b is None else int(b)


def activate_runlog(config=None) -> Optional[RunLog]:
    """The run log a search/session should use under ``config`` — or
    ``None`` when disabled (``TpuConfig.runlog=False``), no directory
    is configured (``TpuConfig.runlog_dir`` / ``SST_RUNLOG_DIR``), or
    the byte budget disables it."""
    directory = _resolve_dir(config)
    if not directory:
        return None
    budget = _resolve_budget(config)
    if budget <= 0:
        return None
    noise = float(getattr(config, "runlog_noise_frac",
                          DEFAULT_NOISE_FRAC) or DEFAULT_NOISE_FRAC) \
        if config is not None else DEFAULT_NOISE_FRAC
    global _RUNLOG
    with _RUNLOG_LOCK:
        if _RUNLOG is None or \
                _RUNLOG.directory != os.path.abspath(directory):
            _RUNLOG = RunLog(directory, budget, noise_frac=noise)
        else:
            _RUNLOG.byte_budget = int(budget)
            _RUNLOG.noise_frac = noise
        return _RUNLOG


def active_runlog() -> Optional[RunLog]:
    """The currently active run log (``None`` when never activated)."""
    with _RUNLOG_LOCK:
        return _RUNLOG


def deactivate_runlog() -> None:
    """Drop the process-global run log (tests; a later
    :func:`activate_runlog` builds a fresh one)."""
    global _RUNLOG
    with _RUNLOG_LOCK:
        _RUNLOG = None


# ---------------------------------------------------------------------------
# fit-end orchestration — record + judge, called by the search engine
# ---------------------------------------------------------------------------


def note_run(report: Dict[str, Any], family: str,
             structure_digest: str, config=None) -> None:
    """Record this search into the run log and run the sentinel.

    Mutates ``report["attribution"]["regression"]`` in place (the
    block is already rendered into the registry), feeds the telemetry
    aggregator, and on a flagged regression dumps a flight-style
    sentinel bundle.  Exact no-op when no run log resolves — the
    report keeps the sentinel-off placeholder."""
    attribution = report.get("attribution")
    if not attribution:
        return
    log = activate_runlog(config)
    if log is None:
        return
    baseline = log.baseline(family, structure_digest)
    regression = compare_to_baseline(
        baseline, attribution, noise_frac=log.noise_frac)
    attribution["regression"] = regression
    log.note_check(regression["status"] == "regressed")
    pipe = report.get("pipeline") or {}
    geometry = report.get("geometry") or {}
    record = {
        "ts_unix_s": time.time(),
        "family": str(family),
        "structure_digest": str(structure_digest),
        "provenance": _provenance.provenance_block(),
        "attribution": {k: v for k, v in attribution.items()
                        if k != "regression"},
        "geometry": geometry,
        "n_compiles": int(pipe.get("n_compiles", 0) or 0),
        "cost_model": geometry.get("cost_model") or {},
        "regression_status": regression["status"],
    }
    log.append(family, structure_digest, record)
    from spark_sklearn_tpu.obs import telemetry as _telemetry
    _telemetry.note_regression(regression["status"], str(family),
                               regression["flags"])
    if regression["status"] == "regressed":
        logger.warning(
            "regression sentinel: %s/%s regressed vs baseline "
            "(%d lane(s) beyond the %.0f%% band)",
            family, structure_digest, len(regression["flags"]),
            100.0 * log.noise_frac)
        _telemetry.flight_recorder().dump(
            f"regression-{family}", config=config,
            context={"regression": regression,
                     "verdict": attribution.get("verdict", ""),
                     "family": str(family),
                     "structure_digest": str(structure_digest)})
