"""Device-memory observability — measuring the HBM side of the ledger.

Every observable so far watched *time*: the span tracer (PR 2), the
fleet telemetry service (PR 8) and the geometry cost model all price
launches in seconds.  But the reference's whole value proposition was
fitting many candidates inside FIXED per-executor memory, and memory
pressure — not compute — is what kills such workloads at scale
(arXiv:1612.01437's straggler analysis keeps landing on memory).  Until
this module the engine discovered device memory exhaustion only by
catching ``RESOURCE_EXHAUSTED`` and bisecting (PR 3): OOM was the
*discovery* mechanism, not the fallback.

This module is the measurement half of the device-memory ledger
(:mod:`spark_sklearn_tpu.parallel.memledger` is the modeling half):

  - :func:`device_memory_stats` reads every local device's
    ``memory_stats()`` (bytes in use, peak, allocator limit) where the
    backend provides it.  XLA:CPU typically does not — the reading then
    degrades to ``measured: False`` and the ledger runs model-only,
    exactly like the tracer's no-op discipline: nothing raises, nothing
    allocates per call beyond the result dicts.
  - :func:`detect_device_memory_bytes` is the budget default's input:
    the smallest per-device allocator limit across the fleet (0 when no
    backend reports one).
  - :func:`resolve_hbm_budget` turns ``TpuConfig(hbm_budget_bytes)`` /
    ``SST_HBM_BUDGET_BYTES`` into the planner's byte ceiling, defaulting
    to :data:`DEFAULT_HBM_FRACTION` of the detected device memory so a
    TPU process never *plans* a chunk it cannot fit — and to "no
    ceiling" on backends (CPU) that report no limit.

Readings are cheap (one runtime call per device); the ledger samples
them at launch boundaries (``parallel/pipeline.py``) under a
``memory.sample`` span and the PR 8 telemetry sampler polls them on its
interval, so the pressure series in ``/metrics`` stays current between
searches.
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional

__all__ = [
    "DEFAULT_HBM_FRACTION",
    "detect_device_memory_bytes",
    "device_memory_stats",
    "pressure",
    "resolve_hbm_budget",
]

#: default planner budget as a fraction of the detected per-device
#: allocator limit — headroom for XLA scratch/temp buffers the
#: shape-level model cannot see (the ledger's safety margin tightens
#: the rest from observed OOMs).
DEFAULT_HBM_FRACTION = 0.8


def _one_device_stats(dev) -> Dict[str, Any]:
    """One device's memory reading.  ``measured`` is False when the
    backend has no ``memory_stats`` (XLA:CPU) or returns nothing."""
    rec: Dict[str, Any] = {
        "id": int(getattr(dev, "id", -1)),
        "platform": str(getattr(dev, "platform", "?")),
        "measured": False,
        "bytes_in_use": 0,
        "peak_bytes_in_use": 0,
        "bytes_limit": 0,
    }
    stats_fn = getattr(dev, "memory_stats", None)
    if stats_fn is None:
        return rec
    try:
        stats = stats_fn()
    except (RuntimeError, NotImplementedError, OSError):
        # a backend that raises instead of returning None (seen on some
        # plugin PJRT clients) is the same "unmeasured" outcome
        return rec
    if not stats:
        return rec
    rec["measured"] = True
    rec["bytes_in_use"] = int(stats.get("bytes_in_use", 0) or 0)
    rec["peak_bytes_in_use"] = int(
        stats.get("peak_bytes_in_use", rec["bytes_in_use"]) or 0)
    rec["bytes_limit"] = int(stats.get("bytes_limit", 0) or 0)
    return rec


def device_memory_stats() -> List[Dict[str, Any]]:
    """Per-local-device memory readings (``measured: False`` rows for
    backends without allocator stats).  Import-light until called: the
    jax import only happens on first use."""
    import jax

    return [_one_device_stats(d) for d in jax.local_devices()]


def pressure(rec: Dict[str, Any]) -> float:
    """One device's occupancy fraction (0.0 when unmeasured or the
    backend reports no limit)."""
    limit = rec.get("bytes_limit", 0)
    if not rec.get("measured") or not limit:
        return 0.0
    return min(1.0, max(0.0, rec.get("bytes_in_use", 0) / limit))


def detect_device_memory_bytes(
        stats: Optional[List[Dict[str, Any]]] = None) -> int:
    """The smallest measured per-device allocator limit across the
    fleet — the number the default HBM budget is a fraction of.  0 when
    no device reports a limit (ledger-only mode)."""
    stats = device_memory_stats() if stats is None else stats
    limits = [r["bytes_limit"] for r in stats
              if r.get("measured") and r.get("bytes_limit", 0) > 0]
    return min(limits) if limits else 0


def resolve_hbm_budget(config=None,
                       stats: Optional[List[Dict[str, Any]]] = None) -> int:
    """The geometry planner's per-device byte ceiling.

    ``TpuConfig.hbm_budget_bytes`` wins when set (0 disables the
    ceiling explicitly); else the ``SST_HBM_BUDGET_BYTES`` env var;
    else :data:`DEFAULT_HBM_FRACTION` of the detected device memory.
    Backends with no measurable limit (XLA:CPU) default to 0 — no
    ceiling, bit-identical planning to the pre-ledger engine."""
    budget = getattr(config, "hbm_budget_bytes", None) \
        if config is not None else None
    if budget is None:
        env = os.environ.get("SST_HBM_BUDGET_BYTES", "").strip()
        if env:
            try:
                budget = int(env)
            except ValueError:
                from spark_sklearn_tpu.obs.log import get_logger
                get_logger(__name__).warning(
                    "SST_HBM_BUDGET_BYTES=%r is not an integer; the "
                    "HBM width ceiling stays at its default", env)
                budget = None
    if budget is not None:
        return max(0, int(budget))
    detected = detect_device_memory_bytes(stats)
    return int(detected * DEFAULT_HBM_FRACTION) if detected else 0
