"""Shared provenance stamp — ONE helper every persisted artifact uses.

Bench payloads (``bench.py``), flight bundles (``obs/telemetry.py``)
and run-log records (``obs/runlog.py``) all persist outside the
process that produced them, and a postmortem comparing two of them
must know whether they came from the same world.  Before this module
each writer rolled its own fingerprint (or none: bench payloads
carried no environment identity at all, so BENCH files from different
machines compared apples-to-oranges silently).  Now the fingerprint,
the stable environment digest and the repo version string are built
here and stamped everywhere via :func:`provenance_block`.

The module stays import-light: jax is imported lazily and every
failure degrades to a partial fingerprint — provenance must never be
the reason an artifact failed to write.
"""

from __future__ import annotations

import hashlib
import os
import sys
from typing import Any, Dict

__all__ = [
    "env_digest",
    "env_fingerprint",
    "provenance_block",
    "repo_version",
]

#: bump when the fingerprint's key set changes incompatibly — digests
#: from different formats must never collide into one runlog baseline
PROVENANCE_FORMAT = 1


def repo_version() -> str:
    """The package version string (``"?"`` when unimportable)."""
    try:
        import spark_sklearn_tpu

        return str(getattr(spark_sklearn_tpu, "__version__", "?"))
    except ImportError:
        return "?"


def env_fingerprint(include_pid: bool = True) -> Dict[str, Any]:
    """Versions/platform/device-fleet identity of this process.

    ``include_pid=False`` drops the per-process ``pid`` key, leaving
    only fields stable across runs of the same environment — the
    subset :func:`env_digest` hashes so run-log baselines match
    across processes.
    """
    import platform

    info: Dict[str, Any] = {
        "python": platform.python_version(),
        "platform": sys.platform,
    }
    if include_pid:
        info["pid"] = os.getpid()
    try:
        import jax
        import jaxlib

        info["jax"] = jax.__version__
        info["jaxlib"] = jaxlib.__version__
        info["backend"] = jax.default_backend()
        info["n_devices"] = len(jax.devices())
    except (ImportError, AttributeError, RuntimeError):
        # a stamp from a jax-less/uninitializable context still records
        # the host identity above
        pass
    info["spark_sklearn_tpu"] = repo_version()
    return info


def env_digest(hexchars: int = 12) -> str:
    """Stable digest of the pid-less fingerprint — the key run-log
    directories (and baseline lookups) are partitioned by."""
    fp = env_fingerprint(include_pid=False)
    blob = repr(tuple(sorted(fp.items()))).encode()
    return hashlib.blake2b(blob, digest_size=16).hexdigest()[:hexchars]


def provenance_block() -> Dict[str, Any]:
    """The stamp persisted artifacts carry: fingerprint + stable
    digest + version, under one pinned shape."""
    return {
        "provenance_format": PROVENANCE_FORMAT,
        "env": env_fingerprint(),
        "env_digest": env_digest(),
        "version": repo_version(),
    }
