"""Span tracer — thread-aware, nestable, bounded, near-free when off.

Design constraints (ISSUE 2 tentpole):

  - **thread-aware**: every span records the thread it closed on, so
    the pipeline's ``sst-stage`` / ``sst-gather`` / ``sst-compile``
    workers and the dispatching main thread each get their own track in
    the exported trace;
  - **nestable**: ``tracer.span(...)`` is a context manager; nesting
    follows Python's ``with`` stack, so spans on one thread are always
    properly nested (the Chrome trace viewer infers the hierarchy from
    timestamp containment);
  - **monotonic timestamps**: ``time.perf_counter()`` throughout —
    wall-clock adjustments can never produce negative durations;
  - **bounded**: events land in a ``deque(maxlen=...)`` ring buffer
    (default 65536); a pathological span storm evicts the oldest spans
    instead of growing without bound;
  - **overhead budget**: tracing OFF costs one attribute read per
    instrumentation site (the shared no-op span is returned before any
    allocation) and must be bit-exact with uninstrumented behavior;
    tracing ON is budgeted at **<2% of search wall** — spans are
    per-launch/per-phase (tens per search), never per-sample.  Both
    sides are enforced by ``tests/test_obs.py``.

Enablement: ``TpuConfig(trace=...)`` per search (``True`` records;
a string records AND exports a Chrome trace there after ``fit``), or
the ``SST_TRACE`` environment variable process-wide (``1``/``true`` to
record, any other value is treated as an export path).
"""

from __future__ import annotations

import contextlib
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

__all__ = [
    "Tracer",
    "current_correlation",
    "get_tracer",
    "search_tracing",
    "set_correlation",
]

#: default ring-buffer capacity (events, not bytes)
DEFAULT_BUFFER_SIZE = 65536

# ---------------------------------------------------------------------------
# Correlation context (multi-tenant attribution)
# ---------------------------------------------------------------------------

#: thread-local {tenant, handle} stamped onto every event a thread
#: records (ISSUE 8 satellite: a multi-tenant Perfetto export used to
#: interleave three searches' spans with no way to tell whose is
#: whose).  Set by the serve executor's worker threads; propagated by
#: ChunkPipeline onto its stage/gather/compile workers; None for a
#: standalone fit, so untenanted traces stay byte-identical.
_CORR = threading.local()


def set_correlation(attrs: Optional[Dict[str, Any]]) -> None:
    """Bind (or clear, with None) the calling thread's correlation
    attributes.  Explicit span attributes win over correlation keys on
    collision."""
    _CORR.attrs = dict(attrs) if attrs else None


def current_correlation() -> Optional[Dict[str, Any]]:
    """The calling thread's correlation attrs, or None."""
    return getattr(_CORR, "attrs", None)


def _stamp(attrs: Dict[str, Any]) -> Dict[str, Any]:
    """Merge the thread's correlation under explicit attrs (explicit
    keys win).  One getattr when no correlation is set — negligible on
    the recording path, absent entirely when tracing is off."""
    corr = getattr(_CORR, "attrs", None)
    if not corr:
        return attrs
    return {**corr, **attrs}

#: event tuples: (ph, name, t0, t1, track_key, track_name, attrs)
#:   ph "X" — complete span (t0..t1 on one thread or virtual track)
#:   ph "i" — instant event (t1 is None)
#:   ph "b" — async span (may overlap others on its virtual track;
#:            the exporter emits a Chrome b/e pair)
Event = Tuple[str, str, float, Optional[float], Any, str, Dict[str, Any]]


class _NullSpan:
    """Shared no-op span handed out when tracing is disabled — the
    entire cost of an instrumentation site with tracing off."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, **attrs):
        return self


_NULL_SPAN = _NullSpan()


class _Span:
    __slots__ = ("_tracer", "_name", "_attrs", "_t0")

    def __init__(self, tracer: "Tracer", name: str, attrs: Dict[str, Any]):
        self._tracer = tracer
        self._name = name
        self._attrs = attrs
        self._t0 = 0.0

    def set(self, **attrs):
        """Attach attributes after the span opened (e.g. results)."""
        self._attrs.update(attrs)
        return self

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        t1 = time.perf_counter()
        th = threading.current_thread()
        # deque.append is atomic under the GIL: no lock on the hot path
        self._tracer._events.append(
            ("X", self._name, self._t0, t1, th.ident, th.name,
             _stamp(self._attrs)))
        return False


class Tracer:
    """Recorder of spans/instants into a bounded ring buffer.

    One process-global instance (``get_tracer()``) is shared by every
    instrumented layer; tests may construct private ones.
    """

    def __init__(self, max_events: int = DEFAULT_BUFFER_SIZE):
        self._events: deque = deque(maxlen=max_events)
        self._enabled = False

    # -- lifecycle -------------------------------------------------------
    @property
    def enabled(self) -> bool:
        return self._enabled

    @property
    def max_events(self) -> int:
        return self._events.maxlen or 0

    def enable(self, max_events: Optional[int] = None) -> None:
        if max_events and max_events != self._events.maxlen:
            self._events = deque(self._events, maxlen=int(max_events))
        self._enabled = True

    def disable(self) -> None:
        """Stop recording; already-recorded events stay exportable."""
        self._enabled = False

    def clear(self) -> None:
        self._events.clear()

    # -- recording -------------------------------------------------------
    def span(self, name: str, **attrs):
        """Context manager timing a block on the current thread."""
        if not self._enabled:
            return _NULL_SPAN
        return _Span(self, name, attrs)

    def instant(self, name: str, **attrs) -> None:
        """Zero-duration marker on the current thread."""
        if not self._enabled:
            return
        th = threading.current_thread()
        self._events.append(
            ("i", name, time.perf_counter(), None, th.ident, th.name,
             _stamp(attrs)))

    def record_span(self, name: str, t0: float, t1: float,
                    track: Optional[str] = None, **attrs) -> None:
        """Retroactively record a span from explicit perf_counter
        timestamps — on the current thread, or on a named virtual track
        (e.g. the ``device`` occupancy track).  Spans on one virtual
        track must not overlap; use :meth:`record_async` when they can.
        """
        if not self._enabled:
            return
        if track is None:
            th = threading.current_thread()
            key, tname = th.ident, th.name
        else:
            key = tname = track
        self._events.append(("X", name, t0, t1, key, tname, _stamp(attrs)))

    def record_async(self, name: str, t0: float, t1: float, track: str,
                     **attrs) -> None:
        """Record a possibly-overlapping span on a virtual track (the
        exporter emits a Chrome async b/e pair, which the viewers lay
        out on parallel lanes)."""
        if not self._enabled:
            return
        self._events.append(("b", name, t0, t1, track, track,
                             _stamp(attrs)))

    # -- consumption -----------------------------------------------------
    def events(self) -> List[Event]:
        """Snapshot of the ring buffer, oldest first."""
        return list(self._events)

    def __len__(self) -> int:
        return len(self._events)


_GLOBAL = Tracer()


def get_tracer() -> Tracer:
    """The process-global tracer every instrumented layer records to."""
    return _GLOBAL


def _env_spec() -> Tuple[bool, Optional[str]]:
    """(enabled, export_path) requested by the SST_TRACE env var."""
    v = os.environ.get("SST_TRACE", "").strip()
    if not v or v.lower() in ("0", "false", "off", "no"):
        return False, None
    if v.lower() in ("1", "true", "on", "yes"):
        return True, None
    return True, v


def _config_spec(config) -> Tuple[bool, Optional[str]]:
    """(enabled, export_path) requested by TpuConfig.trace."""
    spec = getattr(config, "trace", None) if config is not None else None
    if isinstance(spec, str) and spec:
        return True, spec
    return bool(spec), None


@contextlib.contextmanager
def search_tracing(config=None):
    """Scope the global tracer to one search.

    Enables recording when ``TpuConfig(trace=...)`` or ``SST_TRACE``
    asks for it (clearing the buffer so the export covers exactly this
    search), exports a Chrome trace afterwards when a path was given,
    and restores the tracer's prior state — a tracer something else
    enabled (a bench harness, an outer search) is never cleared or
    disabled here.
    """
    cfg_on, cfg_path = _config_spec(config)
    env_on, env_path = _env_spec()
    path = cfg_path or env_path
    tracer = _GLOBAL
    we_enabled = (cfg_on or env_on) and not tracer.enabled
    if we_enabled:
        tracer.clear()
        tracer.enable(max_events=getattr(config, "trace_buffer_size", None))
    try:
        yield tracer
    finally:
        if path and (tracer.enabled or we_enabled):
            from spark_sklearn_tpu.obs.export import export_chrome_trace
            try:
                export_chrome_trace(path)
            except OSError:
                from spark_sklearn_tpu.obs.log import get_logger
                get_logger(__name__).debug(
                    "trace export to %r failed", path)
        if we_enabled:
            tracer.disable()


# process-wide opt-in via environment (import-time, so even code that
# never constructs a TpuConfig records)
if _env_spec()[0]:
    _GLOBAL.enable()
