"""Fleet telemetry — process-wide metrics, per-tenant SLO accounting,
and the fault flight recorder.

PR 7 turned the engine into the reference's "shared Spark cluster": a
long-lived :class:`~spark_sklearn_tpu.utils.session.TpuSession` serving
many tenants' searches through one fair-share executor.  Every
observable so far died with its search (``search_report``, the span
tracer's per-fit export), so an operator of that service could not
answer "is tenant A starved *right now*?", "what is the device doing
between searches?", or "what led up to that 3 a.m. OOM?" — the
continuous telemetry loop online shared-cluster tuning assumes as input
(arXiv:2309.01901) and the fleet-level resource visibility that
distributed-ML performance analysis shows is where the wins come from
(arXiv:1612.01437).  Three pieces:

  - :class:`TelemetryService` — a process-global, session-scoped
    aggregator.  Cheap ``note_*`` hooks (one attribute read when
    disabled — the tracer's exact-no-op discipline) feed per-tenant
    queue-wait/throughput/share rolling windows, device-occupancy and
    dispatch-loop busy series, fault/retry/bisection counters and
    host->device byte totals from the executor, pipeline, supervisor,
    data plane and program store; a low-overhead **sampler thread**
    polls registered providers (scheduler queue depth, data-plane
    residency, program-store counters) on an interval so gauges stay
    current between searches.  ``snapshot()`` renders the whole state
    as one JSON-able dict whose top-level schema is pinned in
    ``obs.metrics.TELEMETRY_SNAPSHOT_SCHEMA``.
  - **exposition** lives in :mod:`spark_sklearn_tpu.obs.fleet`: a
    localhost HTTP endpoint (Prometheus text + JSON snapshot) owned by
    the session (``TpuConfig(telemetry_port)`` / ``SST_TELEMETRY_PORT``,
    default off), plus ``session.telemetry_snapshot()`` in-process and
    the ``tools/fleet_top.py`` terminal digest.
  - :class:`FlightRecorder` — the always-on black box.  A bounded ring
    of recent scheduler dispatch events, fault events and warning-level
    structured log records (each stamped with the thread's
    tenant/search-handle correlation, so cross-search causality is
    reconstructable), dumped as a correlated bundle — ring records,
    trace slice (Chrome ``traceEvents``, loadable by
    ``tools/trace_summary.py``), scheduler state, faults block, config
    and environment fingerprint — to ``TpuConfig(flight_dir)`` /
    ``SST_FLIGHT_DIR`` on any FATAL fault, watchdog timeout, first OOM
    recovery, cancellation, or program-store quarantine.  With no
    flight dir configured the ring still records (bounded, in-memory)
    and dumping is a no-op.

Enabling telemetry also enables the span tracer (the flight recorder's
"recent spans" ring); disabling restores the tracer's prior state.
Telemetry off is an exact no-op: hooks early-out before any allocation,
``search_report`` / ``cv_results_`` / exported traces are byte-identical
to a telemetry-less build, and no thread or socket exists.
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

from spark_sklearn_tpu.obs.trace import current_correlation, get_tracer
from spark_sklearn_tpu.utils.atomic import atomic_write
from spark_sklearn_tpu.utils.locks import named_lock, named_rlock

__all__ = [
    "DEFAULT_WINDOW_S",
    "DEFAULT_INTERVAL_S",
    "FlightRecorder",
    "RollingWindow",
    "TelemetryService",
    "flight_recorder",
    "get_telemetry",
    "note_admission",
    "note_dispatch",
    "note_fault",
    "note_fusion",
    "note_h2d",
    "note_launch",
    "note_programstore",
    "note_protection",
    "note_recovery",
    "note_sched_busy",
    "percentile",
    "resolve_flight_dir",
]

#: sliding-window span (seconds) the SLO percentiles/rates cover
DEFAULT_WINDOW_S = 120.0
#: sampler tick period (seconds)
DEFAULT_INTERVAL_S = 0.5
#: bounded flight-recorder ring (records, not bytes)
DEFAULT_FLIGHT_RECORDS = 4096
#: per-window sample bound — a million-chunk burst must not grow an
#: unbounded deque; rates/percentiles degrade to the newest samples
MAX_WINDOW_SAMPLES = 4096


def percentile(sorted_vals: List[float], p: float) -> float:
    """Nearest-rank percentile of an ascending list (0.0 when empty) —
    the same estimator ``bench.py`` uses, so endpoint and bench numbers
    agree sample-for-sample."""
    if not sorted_vals:
        return 0.0
    i = min(len(sorted_vals) - 1,
            int(round(p / 100.0 * (len(sorted_vals) - 1))))
    return float(sorted_vals[i])


class RollingWindow:
    """Bounded (timestamp, value) samples over a sliding time window.

    Appends are O(1); reads evict expired samples first.  NOT
    internally locked — the owning :class:`TelemetryService` serializes
    access under its own named lock."""

    __slots__ = ("window_s", "_samples")

    def __init__(self, window_s: float = DEFAULT_WINDOW_S,
                 max_samples: int = MAX_WINDOW_SAMPLES):
        self.window_s = float(window_s)
        self._samples: deque = deque(maxlen=int(max_samples))

    def add(self, value: Any, t: Optional[float] = None) -> None:
        self._samples.append(
            (time.perf_counter() if t is None else t, value))

    def _evict(self, now: Optional[float] = None) -> None:
        cutoff = (time.perf_counter() if now is None else now) \
            - self.window_s
        while self._samples and self._samples[0][0] < cutoff:
            self._samples.popleft()

    def values(self, now: Optional[float] = None) -> List[Any]:
        self._evict(now)
        return [v for _, v in self._samples]

    def sum(self, now: Optional[float] = None) -> float:
        return float(sum(self.values(now)))

    def count(self, now: Optional[float] = None) -> int:
        self._evict(now)
        return len(self._samples)

    def span_s(self, now: Optional[float] = None) -> float:
        """Elapsed time the current samples actually cover (capped at
        the window) — rates divide by this, not the full window, so a
        service younger than one window reports honest rates."""
        self._evict(now)
        if not self._samples:
            return 0.0
        now = time.perf_counter() if now is None else now
        return min(self.window_s, max(1e-9, now - self._samples[0][0]))

    def percentile(self, p: float, now: Optional[float] = None) -> float:
        return percentile(sorted(self.values(now)), p)


# ---------------------------------------------------------------------------
# Flight recorder — the always-on black box
# ---------------------------------------------------------------------------


def resolve_flight_dir(config=None) -> Optional[str]:
    """The directory flight bundles dump to: ``TpuConfig.flight_dir``,
    else the ``SST_FLIGHT_DIR`` env var, else None (dumping disabled;
    the in-memory ring still records)."""
    d = getattr(config, "flight_dir", None) if config is not None else None
    return d or os.environ.get("SST_FLIGHT_DIR") or None


def _env_fingerprint() -> Dict[str, Any]:
    """Versions/platform/device-fleet identity stamped into every
    bundle, so a postmortem knows exactly which world produced it —
    the ONE shared stamp (``obs/provenance.py``) bench payloads and
    run-log records carry too."""
    from spark_sklearn_tpu.obs.provenance import env_fingerprint

    return env_fingerprint()


def _provenance_block() -> Dict[str, Any]:
    from spark_sklearn_tpu.obs.provenance import provenance_block

    return provenance_block()


def _config_jsonable(config) -> Dict[str, Any]:
    if config is None:
        return {}
    if dataclasses.is_dataclass(config):
        out = {}
        for f in dataclasses.fields(config):
            v = getattr(config, f.name, None)
            out[f.name] = v if isinstance(
                v, (str, int, float, bool, type(None))) else repr(v)
        return out
    return {"repr": repr(config)}


class FlightRecorder:
    """Bounded ring of recent dispatch/fault/log events plus the
    black-box ``dump``.

    ``note`` is called from the executor's dispatch accounting, the
    fault supervisor's event journal and the structured logger's
    warning channel — always outside their own locks, so the recorder
    introduces no cross-module lock nesting.  Records carry the calling
    thread's tenant/handle correlation
    (:func:`~spark_sklearn_tpu.obs.trace.current_correlation`)."""

    def __init__(self, max_records: int = DEFAULT_FLIGHT_RECORDS):
        self._lock = named_lock("telemetry.FlightRecorder._lock")
        self._ring: deque = deque(maxlen=int(max_records))
        self._n_dumps = 0
        self._n_records = 0

    # -- recording -------------------------------------------------------
    def note(self, kind: str, **fields: Any) -> None:
        rec = {"t_unix_s": time.time(), "t_mono_s": time.perf_counter(),
               "kind": kind}
        corr = current_correlation()
        if corr:
            rec.update(corr)
        rec.update(fields)
        with self._lock:
            self._ring.append(rec)
            self._n_records += 1

    def records(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._ring)

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {"n_records": self._n_records,
                    "n_buffered": len(self._ring),
                    "n_dumps": self._n_dumps}

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()

    # -- the black-box dump ----------------------------------------------
    def dump(self, reason: str, flight_dir: Optional[str] = None,
             config=None, faults: Optional[Dict[str, Any]] = None,
             scheduler: Optional[Dict[str, Any]] = None,
             context: Optional[Dict[str, Any]] = None) -> Optional[str]:
        """Write a correlated black-box bundle for ``reason`` and
        return its path — or None when no flight directory resolves
        (``flight_dir`` arg, ``TpuConfig.flight_dir`` via ``config``,
        or ``SST_FLIGHT_DIR``).

        The bundle is one JSON object: the ring's recent records, a
        Chrome ``traceEvents`` slice of the tracer's current buffer
        (``tools/trace_summary.py`` digests the bundle file directly),
        the scheduler state the caller supplies, the faults block,
        the config, and an environment fingerprint.  Dump failures are
        logged and swallowed — the black box must never turn an
        incident into a second failure."""
        target_dir = flight_dir or resolve_flight_dir(config)
        if not target_dir:
            return None
        with self._lock:
            records = list(self._ring)
            self._n_dumps += 1
            seq = self._n_dumps
        corr = current_correlation() or {}
        tracer = get_tracer()
        trace_events: List[Dict[str, Any]] = []
        if len(tracer):
            from spark_sklearn_tpu.obs.export import chrome_trace_events
            trace_events = chrome_trace_events(tracer.events())
        svc = get_telemetry()
        # the device-memory ledger's full state (resident set, modeled
        # group footprints, watermark, safety margin) rides in every
        # bundle — an OOM postmortem shows what was resident and why
        from spark_sklearn_tpu.parallel.memledger import get_ledger
        bundle = {
            "flight_format": 1,
            "reason": reason,
            "ts_unix_s": time.time(),
            "correlation": dict(corr),
            "context": dict(context or {}),
            "env": _env_fingerprint(),
            # the shared stamp (obs/provenance.py): fingerprint +
            # env_digest + repo version, the same block bench payloads
            # and run-log records carry, so cross-artifact correlation
            # is a digest comparison
            "provenance": _provenance_block(),
            "config": _config_jsonable(config),
            "scheduler": dict(scheduler or {}),
            "faults": dict(faults or {}),
            "telemetry": svc.snapshot() if svc.enabled else {},
            "memory": get_ledger().snapshot(),
            "records": records,
            "traceEvents": trace_events,
        }
        slug = "".join(c if c.isalnum() or c in "-_" else "-"
                       for c in reason)[:40]
        path = os.path.join(
            target_dir, f"flight-{slug}-{os.getpid()}-{seq:04d}.json")
        try:
            os.makedirs(target_dir, exist_ok=True)
            # the hardened tmp+fsync+replace path (utils/atomic.py) —
            # bundles are written mid-incident, when a crash is most
            # likely, and a torn black box is worse than none
            atomic_write(path, json.dumps(bundle, default=str).encode())
        except (OSError, TypeError, ValueError) as exc:
            from spark_sklearn_tpu.obs.log import get_logger
            get_logger(__name__).warning(
                "flight recorder: bundle write failed for %r (%r)",
                reason, exc)
            return None
        from spark_sklearn_tpu.obs.log import get_logger
        get_logger(__name__).warning(
            "flight recorder: %s bundle dumped to %s (%d record(s), "
            "%d trace event(s))", reason, path, len(records),
            len(trace_events), reason=reason, path=path)
        return path

    def protection_dump(self, verdict: str, reason: Optional[str] = None,
                        flight_dir: Optional[str] = None, config=None,
                        faults: Optional[Dict[str, Any]] = None,
                        scheduler: Optional[Dict[str, Any]] = None,
                        context: Optional[Dict[str, Any]] = None,
                        ) -> Optional[str]:
        """The ONE trigger path for protection-verdict bundles: a
        deadline-expired cancel, a quarantined poison candidate, or a
        retry-budget exhaustion all land here, so every such bundle
        carries its verdict under ``context["protection_verdict"]``
        and is greppable the same way."""
        ctx = dict(context or {})
        ctx["protection_verdict"] = str(verdict)
        return self.dump(reason or f"protection-{verdict}",
                         flight_dir=flight_dir, config=config,
                         faults=faults, scheduler=scheduler,
                         context=ctx)


_FLIGHT = FlightRecorder()


def flight_recorder() -> FlightRecorder:
    """The process-global flight recorder (always on, bounded)."""
    return _FLIGHT


# ---------------------------------------------------------------------------
# Telemetry service
# ---------------------------------------------------------------------------


class _TenantStats:
    """One tenant's SLO state: cumulative totals + rolling windows."""

    __slots__ = ("dispatches_total", "tasks_total", "queue_wait_s_total",
                 "waits", "costs")

    def __init__(self, window_s: float):
        self.dispatches_total = 0
        self.tasks_total = 0
        self.queue_wait_s_total = 0.0
        self.waits = RollingWindow(window_s)     # queue-wait seconds
        self.costs = RollingWindow(window_s)     # dispatched task units


def _zero_regression() -> Dict[str, Any]:
    """The regression block's zeroed shape (no comparisons yet)."""
    return {"checks_total": 0, "flagged_total": 0, "last_status": "",
            "last_family": "", "last_flags": []}


def _zero_recovery() -> Dict[str, Any]:
    """The recovery block's zeroed shape (no journal activity yet)."""
    return {"journal_entries_total": 0, "nonterminal_found_total": 0,
            "recovered_total": 0, "mismatch_total": 0,
            "lease_takeovers_total": 0, "lease_conflicts_total": 0,
            "unclean_shutdowns_total": 0, "time_to_recover_s": 0.0}


def _zero_fusion() -> Dict[str, int]:
    """The fusion block's zeroed counters (no fused launches yet)."""
    return {"fused_total": 0, "members_total": 0,
            "saved_launches_total": 0, "lanes_real_total": 0,
            "lanes_padded_total": 0}


class TelemetryService:
    """The process-global aggregator behind the fleet endpoint.

    Disabled (the default) every hook early-outs on one attribute read;
    enabled, hooks append to bounded rolling windows under one named
    lock and a daemon sampler thread polls the registered providers.
    The service never calls a provider while holding its own lock, and
    hooks are invoked by producers *outside* their locks — so telemetry
    adds no cross-module lock ordering."""

    def __init__(self, window_s: float = DEFAULT_WINDOW_S,
                 interval_s: float = DEFAULT_INTERVAL_S):
        # reentrant: snapshot() renders its sub-blocks through helpers
        # that take the lock again themselves, so each is safe
        # standalone (the dataplane's _evict_over_budget pattern)
        self._lock = named_rlock("telemetry.TelemetryService._lock")
        self.enabled = False
        #: enable/disable are refcounted: two telemetry-enabled
        #: sessions in one process share the global service, and
        #: stopping the first must not kill the second's endpoint view
        self._enable_count = 0
        self.window_s = float(window_s)
        self.interval_s = float(interval_s)
        self._t_enabled: Optional[float] = None
        self._we_enabled_tracer = False
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._tenants: Dict[str, _TenantStats] = {}
        self._device_busy = RollingWindow(window_s)
        self._sched_busy = RollingWindow(window_s)
        self._sched_dispatches_total = 0
        self._faults_by_class: Dict[str, int] = {}
        self._faults_by_action: Dict[str, int] = {}
        self._h2d = {"bytes_total": 0, "uploads_total": 0}
        self._h2d_window = RollingWindow(window_s)
        self._ps_events: Dict[str, int] = {}
        #: the regression sentinel's running view (obs/runlog.py):
        #: comparisons performed, regressions flagged, and the last
        #: judgment's status/family/flagged-lane list
        self._regression: Dict[str, Any] = _zero_regression()
        #: admission decisions (admitted/queued/rejected) and, for
        #: rejections, the machine-readable reason breakdown — the
        #: self-protecting service's shed/deferred counters
        self._admission: Dict[str, int] = {}
        self._admission_reasons: Dict[str, int] = {}
        #: protection actuations: candidates shed, poison candidates
        #: quarantined, deadlines expired
        self._protection: Dict[str, int] = {}
        #: cross-search launch fusion: fused-launch totals plus the
        #: per-tenant lane exchange (the head tenant "donates" the
        #: launch it leads; peers "borrow" lanes on it)
        self._fusion: Dict[str, int] = _zero_fusion()
        self._fusion_borrowed: Dict[str, int] = {}
        self._fusion_donated: Dict[str, int] = {}
        #: crash-safe service counters (serve/journal.py): journal
        #: appends seen, non-terminal entries found at warm restart,
        #: searches recovered, fingerprint mismatches, lease fencing
        #: verdicts, and the last restart's time-to-recover
        self._recovery: Dict[str, Any] = _zero_recovery()
        #: provider name -> STACK of zero-arg callables returning a
        #: JSON-able dict; the newest registration is polled, and
        #: unregistering it restores the previous one — so two
        #: sessions sharing the service survive either stop order
        self._providers: Dict[str, List[Callable[[], Dict[str, Any]]]] \
            = {}
        #: provider name -> rolling (t, polled dict) for window deltas
        self._polls: Dict[str, RollingWindow] = {}
        self._n_samples = 0

    # -- lifecycle -------------------------------------------------------
    def enable(self, window_s: Optional[float] = None,
               interval_s: Optional[float] = None) -> "TelemetryService":
        """Start aggregating (refcounted: each ``enable`` pairs with
        one :meth:`disable`, so two telemetry-enabled sessions sharing
        the global service survive the first one stopping).  Also
        enables the span tracer when it is off — the flight recorder's
        "recent spans" ring — remembering to restore it when the LAST
        disable lands."""
        mismatch = None
        with self._lock:
            self._enable_count += 1
            if self.enabled:
                # the FIRST owner's window/interval stand: resizing a
                # live service's windows would retroactively change the
                # meaning of the other session's SLO series
                if (window_s and float(window_s) != self.window_s) or \
                        (interval_s and
                         float(interval_s) != self.interval_s):
                    mismatch = (self.window_s, self.interval_s)
            else:
                if window_s:
                    self.window_s = float(window_s)
                    for ts in self._tenants.values():
                        ts.waits.window_s = self.window_s
                        ts.costs.window_s = self.window_s
                    self._device_busy.window_s = self.window_s
                    self._sched_busy.window_s = self.window_s
                    self._h2d_window.window_s = self.window_s
                if interval_s:
                    self.interval_s = float(interval_s)
        if mismatch is not None:
            from spark_sklearn_tpu.obs.log import get_logger
            get_logger(__name__).warning(
                "telemetry already enabled with window=%.0fs "
                "interval=%.2fs; the new session's settings are "
                "ignored until the last owner disables", *mismatch)
            return self
        with self._lock:
            if self.enabled:
                return self
            self.enabled = True
            self._t_enabled = time.perf_counter()
        tracer = get_tracer()
        if not tracer.enabled:
            tracer.enable()
            with self._lock:
                self._we_enabled_tracer = True
        self._ensure_sampler()
        return self

    def disable(self) -> bool:
        """Drop one enable reference; the LAST disable stops the
        sampler and the hooks (accumulated state stays readable through
        :meth:`snapshot`, whose ``enabled`` goes False).  Returns True
        when the service actually stopped — callers that own shared
        providers only tear them down then.

        Known limitation: the tracer restore is boolean, not
        refcounted — if telemetry turned the tracer on and a LATER
        consumer (e.g. a ``TpuConfig(trace=True)`` session) started
        relying on it, the last telemetry disable turns it off for
        them too; re-enable via ``get_tracer().enable()`` or construct
        the tracing session first."""
        with self._lock:
            if not self.enabled:
                self._enable_count = 0
                return True
            self._enable_count = max(0, self._enable_count - 1)
            if self._enable_count > 0:
                return False
            self.enabled = False
            thread = self._thread
            self._thread = None
            # THIS sampler's stop event (each sampler thread gets its
            # own in _ensure_sampler): a concurrent re-enable starting
            # a fresh sampler can never be killed by this late set()
            stop = self._stop
            we_enabled = self._we_enabled_tracer
            self._we_enabled_tracer = False
        stop.set()
        if thread is not None and thread.is_alive():
            thread.join(timeout=5.0)
        if we_enabled:
            get_tracer().disable()
        return True

    def reset(self) -> None:
        """Drop all accumulated series/counters (test isolation)."""
        with self._lock:
            self._tenants.clear()
            self._device_busy = RollingWindow(self.window_s)
            self._sched_busy = RollingWindow(self.window_s)
            self._sched_dispatches_total = 0
            self._faults_by_class.clear()
            self._faults_by_action.clear()
            self._h2d = {"bytes_total": 0, "uploads_total": 0}
            self._h2d_window = RollingWindow(self.window_s)
            self._ps_events.clear()
            self._regression = _zero_regression()
            self._admission.clear()
            self._admission_reasons.clear()
            self._protection.clear()
            self._fusion = _zero_fusion()
            self._fusion_borrowed.clear()
            self._fusion_donated.clear()
            self._recovery = _zero_recovery()
            self._polls.clear()
            self._n_samples = 0

    # -- providers + sampler ---------------------------------------------
    def register_provider(self, name: str,
                          fn: Callable[[], Dict[str, Any]]) -> None:
        """Register a polled gauge source (the NEWEST registration
        under a name is the one polled).  ``fn`` runs on the sampler
        thread WITHOUT the telemetry lock held, so it may take its
        subsystem's own locks freely."""
        with self._lock:
            self._providers.setdefault(name, []).append(fn)
            self._polls.setdefault(name, RollingWindow(self.window_s))

    def unregister_provider(self, name: str, expected: Optional[
            Callable[[], Dict[str, Any]]] = None) -> None:
        """Remove a provider registration.  With ``expected`` given,
        remove exactly that callable from the name's stack (wherever it
        sits) — a stopping session tears down only its own
        registration, and an earlier session's provider resumes being
        polled.  Without ``expected``, the whole name is dropped."""
        with self._lock:
            if expected is None:
                self._providers.pop(name, None)
                return
            stack = self._providers.get(name)
            if not stack:
                return
            for i in range(len(stack) - 1, -1, -1):
                if stack[i] is expected:
                    del stack[i]
                    break
            if not stack:
                self._providers.pop(name, None)

    def _ensure_sampler(self) -> None:
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return
            # a FRESH stop event per sampler: disable() sets only the
            # event of the thread it is stopping, so a disable racing
            # a re-enable cannot kill the newly started sampler
            stop = self._stop = threading.Event()
            self._thread = threading.Thread(
                target=self._sample_loop, args=(stop,),
                name="sst-telemetry", daemon=True)
            thread = self._thread
        thread.start()

    def _sample_loop(self, stop: threading.Event) -> None:
        while not stop.wait(self.interval_s):
            if not self.enabled:
                break
            self.sample_once()

    def sample_once(self) -> None:
        """One sampler tick: poll every provider (outside the lock) and
        store the results for window-delta rates.  Public so tests and
        the endpoint can force a fresh poll deterministically."""
        with self._lock:
            providers = [(name, stack[-1])
                         for name, stack in self._providers.items()
                         if stack]
        t = time.perf_counter()
        with get_tracer().span("telemetry.sample"):
            for name, fn in providers:
                try:
                    polled = dict(fn() or {})
                # a dying subsystem (executor mid-shutdown, store being
                # deactivated) must degrade to a skipped sample, never
                # kill the sampler thread — the next tick retries, so
                # the failure is self-healing and not worth a log line
                # per 0.5 s tick
                # sstlint: disable=swallowed-exception
                except Exception:
                    continue
                with self._lock:
                    win = self._polls.setdefault(
                        name, RollingWindow(self.window_s))
                    win.add(polled, t=t)
        with self._lock:
            self._n_samples += 1

    # -- hooks (each early-outs when disabled) ---------------------------
    def note_dispatch(self, tenant: str, cost: int,
                      wait_s: Optional[float] = None) -> None:
        if not self.enabled:
            return
        with self._lock:
            ts = self._tenants.get(tenant)
            if ts is None:
                ts = self._tenants[tenant] = _TenantStats(self.window_s)
            ts.dispatches_total += 1
            ts.tasks_total += int(cost)
            ts.costs.add(int(cost))
            self._sched_dispatches_total += 1
            if wait_s is not None:
                ts.queue_wait_s_total += float(wait_s)
                ts.waits.add(float(wait_s))

    def note_launch(self, compute_s: float) -> None:
        """Device-occupancy feed: one launch's device-busy estimate."""
        if not self.enabled:
            return
        with self._lock:
            self._device_busy.add(max(0.0, float(compute_s)))

    def note_sched_busy(self, busy_s: float) -> None:
        """Dispatch-loop feed: time the shared loop spent dispatching
        (its idle fraction is 1 - busy/window)."""
        if not self.enabled:
            return
        with self._lock:
            self._sched_busy.add(max(0.0, float(busy_s)))

    def note_fault(self, fault_class: str, action: str) -> None:
        if not self.enabled:
            return
        with self._lock:
            self._faults_by_class[fault_class] = \
                self._faults_by_class.get(fault_class, 0) + 1
            self._faults_by_action[action] = \
                self._faults_by_action.get(action, 0) + 1

    def note_h2d(self, nbytes: int) -> None:
        if not self.enabled:
            return
        with self._lock:
            self._h2d["bytes_total"] += int(nbytes)
            self._h2d["uploads_total"] += 1
            self._h2d_window.add(int(nbytes))

    def note_programstore(self, event: str) -> None:
        if not self.enabled:
            return
        with self._lock:
            self._ps_events[event] = self._ps_events.get(event, 0) + 1

    def note_admission(self, decision: str, tenant: str = "",
                       reason: str = "") -> None:
        """Admission-control feed (serve/executor.py): one submit's
        verdict — "admitted", "queued" (deferred to the waiting line)
        or "rejected" (with its machine-readable reason)."""
        if not self.enabled:
            return
        with self._lock:
            self._admission[decision] = \
                self._admission.get(decision, 0) + 1
            if decision == "rejected" and reason:
                self._admission_reasons[reason] = \
                    self._admission_reasons.get(reason, 0) + 1

    def note_protection(self, kind: str, n: int = 1) -> None:
        """Protection-actuation feed: "shed" (candidates written to
        error_score without running), "quarantined" (poison candidates
        isolated) or "deadline_hit" (search deadlines expired)."""
        if not self.enabled:
            return
        with self._lock:
            self._protection[kind] = self._protection.get(kind, 0) \
                + int(n)

    def note_fusion(self, tenant: str, n_members: int, lanes_total: int,
                    lanes_real: int, saved_launches: int,
                    borrowed: Optional[Dict[str, int]] = None) -> None:
        """Cross-search fusion feed (serve/executor.py): one fused
        launch — ``tenant`` led it (donating its launch slot), the
        ``borrowed`` map records how many real lanes each peer tenant
        rode along with."""
        if not self.enabled:
            return
        borrowed = dict(borrowed or {})
        with self._lock:
            self._fusion["fused_total"] += 1
            self._fusion["members_total"] += int(n_members)
            self._fusion["saved_launches_total"] += int(saved_launches)
            self._fusion["lanes_real_total"] += int(lanes_real)
            self._fusion["lanes_padded_total"] += int(lanes_total)
            donated = sum(int(v) for v in borrowed.values())
            self._fusion_donated[tenant] = \
                self._fusion_donated.get(tenant, 0) + donated
            for name, n in borrowed.items():
                self._fusion_borrowed[name] = \
                    self._fusion_borrowed.get(name, 0) + int(n)

    def note_recovery(self, kind: str, n: int = 1,
                      time_to_recover_s: Optional[float] = None) -> None:
        """Crash-recovery feed (serve/journal.py + utils/session.py):
        "journal_entries" (WAL records seen at restart scan),
        "nonterminal_found" (searches a restart owed), "recovered"
        (re-admitted through :meth:`TpuSession.resubmit`), "mismatch"
        (re-bound data failed fingerprint verification),
        "lease_takeovers" / "lease_conflicts" / "unclean_shutdowns"
        (fencing verdicts); ``time_to_recover_s`` stamps the restart's
        first successful resubmit latency."""
        if not self.enabled:
            return
        with self._lock:
            key = f"{kind}_total"
            if key in self._recovery:
                self._recovery[key] += int(n)
            if time_to_recover_s is not None:
                self._recovery["time_to_recover_s"] = round(
                    float(time_to_recover_s), 6)

    def note_regression(self, status: str, family: str,
                        flags: Optional[List[Dict[str, Any]]] = None,
                        ) -> None:
        """Regression-sentinel feed (obs/runlog.py): one baseline
        comparison's judgment at fit end."""
        if not self.enabled:
            return
        with self._lock:
            self._regression["checks_total"] += 1
            if status == "regressed":
                self._regression["flagged_total"] += 1
            self._regression["last_status"] = str(status)
            self._regression["last_family"] = str(family)
            self._regression["last_flags"] = [
                dict(f) for f in (flags or [])]

    # -- snapshot --------------------------------------------------------
    def _tenant_block(self, now: float) -> Dict[str, Any]:
        total_window_cost = sum(
            ts.costs.sum(now) for ts in self._tenants.values())
        residency = self._latest_poll("dataplane").get(
            "tenant_bytes") or {}
        out: Dict[str, Any] = {}
        for name in sorted(self._tenants):
            ts = self._tenants[name]
            span = ts.costs.span_s(now)
            win_cost = ts.costs.sum(now)
            out[name] = {
                "residency_bytes": int(residency.get(name, 0)),
                "dispatches_total": ts.dispatches_total,
                "tasks_total": ts.tasks_total,
                "queue_wait_s_total": round(ts.queue_wait_s_total, 6),
                "queue_wait_p50_s": round(ts.waits.percentile(50, now), 6),
                "queue_wait_p95_s": round(ts.waits.percentile(95, now), 6),
                "wait_samples": ts.waits.count(now),
                "throughput_tasks_per_s": round(win_cost / span, 4)
                if span > 0 else 0.0,
                "share_frac": round(win_cost / total_window_cost, 4)
                if total_window_cost > 0 else 0.0,
            }
        return out

    def _device_block(self, now: float) -> Dict[str, Any]:
        span = self._device_busy.span_s(now)
        busy = self._device_busy.sum(now)
        return {
            "busy_s_window": round(busy, 4),
            "occupancy_frac": round(min(1.0, busy / span), 4)
            if span > 0 else 0.0,
        }

    def _scheduler_block(self, now: float) -> Dict[str, Any]:
        with self._lock:
            span = self._sched_busy.span_s(now)
            busy = self._sched_busy.sum(now)
            block = {
                "dispatches_total": self._sched_dispatches_total,
                "loop_busy_s_window": round(busy, 4),
                "loop_idle_frac": round(max(0.0, 1.0 - busy / span), 4)
                if span > 0 else 1.0,
            }
            block.update(self._latest_poll("scheduler"))
            return block

    def _latest_poll(self, name: str) -> Dict[str, Any]:
        win = self._polls.get(name)
        if win is None:
            return {}
        vals = win.values()
        return dict(vals[-1]) if vals else {}

    def _poll_delta(self, name: str, keys: Tuple[str, ...]) -> Dict[str, Any]:
        """newest - oldest of a polled cumulative counter over the
        window, suffixed ``_window`` (hit/publish RATES without hooks on
        every cache lookup)."""
        win = self._polls.get(name)
        if win is None:
            return {}
        vals = win.values()
        if not vals:
            return {}
        lo, hi = vals[0], vals[-1]
        return {f"{k}_window": int(hi.get(k, 0)) - int(lo.get(k, 0))
                for k in keys if k in hi}

    def _dataplane_block(self, now: float) -> Dict[str, Any]:
        with self._lock:
            span = self._h2d_window.span_s(now)
            block = {
                "h2d_bytes_total": self._h2d["bytes_total"],
                "h2d_uploads_total": self._h2d["uploads_total"],
                "h2d_bytes_per_s": round(
                    self._h2d_window.sum(now) / span, 1)
                if span > 0 else 0.0,
            }
            block.update(self._latest_poll("dataplane"))
            block.update(self._poll_delta("dataplane",
                                          ("hits", "misses")))
            # the raw per-tenant dict surfaces under tenants instead
            block.pop("tenant_bytes", None)
            return block

    def _programstore_block(self) -> Dict[str, Any]:
        with self._lock:
            block = {f"{k}_total": v
                     for k, v in sorted(self._ps_events.items())}
            block.update(self._latest_poll("programstore"))
            return block

    def _memory_block(self) -> Dict[str, Any]:
        """Device-memory view from the sampled "memory" provider (the
        ledger's gauges: per-device pressure, modeled peak, watermark)
        plus a bounded recent max-pressure series from the poll
        window."""
        with self._lock:
            block = dict(self._latest_poll("memory"))
            win = self._polls.get("memory")
            if win is not None:
                series = [v.get("pressure_frac_max", 0.0)
                          for v in win.values()]
                if series:
                    block["pressure_window"] = [
                        round(float(x), 6) for x in series[-64:]]
            return block

    def _faults_block(self) -> Dict[str, Any]:
        return {
            "total": sum(self._faults_by_class.values()),
            "by_class": dict(sorted(self._faults_by_class.items())),
            "by_action": dict(sorted(self._faults_by_action.items())),
        }

    def _regression_block(self) -> Dict[str, Any]:
        with self._lock:
            block = dict(self._regression)
            block["last_flags"] = [dict(f)
                                   for f in block["last_flags"]]
            return block

    def _protection_block(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "admitted_total": self._admission.get("admitted", 0),
                "queued_total": self._admission.get("queued", 0),
                "rejected_total": self._admission.get("rejected", 0),
                "rejected_by_reason": dict(
                    sorted(self._admission_reasons.items())),
                "shed_total": self._protection.get("shed", 0),
                "quarantined_total": self._protection.get(
                    "quarantined", 0),
                "deadline_hits_total": self._protection.get(
                    "deadline_hit", 0),
            }

    def _recovery_block(self) -> Dict[str, Any]:
        with self._lock:
            r = self._recovery
            return {
                "journal_entries_total": r["journal_entries_total"],
                "nonterminal_found_total": r["nonterminal_found_total"],
                "recovered_total": r["recovered_total"],
                "mismatch_total": r["mismatch_total"],
                "lease_takeovers_total": r["lease_takeovers_total"],
                "lease_conflicts_total": r["lease_conflicts_total"],
                "unclean_shutdowns_total": r["unclean_shutdowns_total"],
                "time_to_recover_s": r["time_to_recover_s"],
            }

    def _fusion_block(self) -> Dict[str, Any]:
        with self._lock:
            block: Dict[str, Any] = dict(self._fusion)
            block["lanes_borrowed_by_tenant"] = dict(
                sorted(self._fusion_borrowed.items()))
            block["lanes_donated_by_tenant"] = dict(
                sorted(self._fusion_donated.items()))
            return block

    def snapshot(self) -> Dict[str, Any]:
        """The whole telemetry state as one JSON-able dict.  Top-level
        keys are pinned in ``obs.metrics.TELEMETRY_SNAPSHOT_SCHEMA``;
        the same dict backs the endpoint's ``/snapshot.json`` and the
        Prometheus rendering (``obs.fleet.prometheus_text``)."""
        now = time.perf_counter()
        # the heartbeat hub owns its own named lock — render its block
        # BEFORE taking ours (no cross-module lock nesting)
        from spark_sklearn_tpu.obs import heartbeat as _heartbeat
        hb_block = _heartbeat.snapshot_block()
        with self._lock:
            return {
                "enabled": self.enabled,
                "ts_unix_s": round(time.time(), 3),
                "window_s": self.window_s,
                "interval_s": self.interval_s,
                "n_samples": self._n_samples,
                "tenants": self._tenant_block(now),
                "device": self._device_block(now),
                "scheduler": self._scheduler_block(now),
                "dataplane": self._dataplane_block(now),
                "programstore": self._programstore_block(),
                "memory": self._memory_block(),
                "faults": self._faults_block(),
                "regression": self._regression_block(),
                "protection": self._protection_block(),
                "fusion": self._fusion_block(),
                "recovery": self._recovery_block(),
                "flight": _FLIGHT.stats(),
                "heartbeat": hb_block,
            }


_GLOBAL = TelemetryService()


def get_telemetry() -> TelemetryService:
    """The process-global service every hook reports to."""
    return _GLOBAL


# -- module-level hook spellings (what the producers call) ----------------

def note_dispatch(tenant: str, cost: int,
                  wait_s: Optional[float] = None) -> None:
    if _GLOBAL.enabled:
        _GLOBAL.note_dispatch(tenant, cost, wait_s)


def note_launch(compute_s: float) -> None:
    if _GLOBAL.enabled:
        _GLOBAL.note_launch(compute_s)


def note_sched_busy(busy_s: float) -> None:
    if _GLOBAL.enabled:
        _GLOBAL.note_sched_busy(busy_s)


def note_fault(fault_class: str, action: str) -> None:
    if _GLOBAL.enabled:
        _GLOBAL.note_fault(fault_class, action)


def note_h2d(nbytes: int) -> None:
    if _GLOBAL.enabled:
        _GLOBAL.note_h2d(nbytes)


def note_programstore(event: str) -> None:
    if _GLOBAL.enabled:
        _GLOBAL.note_programstore(event)


def note_fusion(tenant: str, n_members: int, lanes_total: int,
                lanes_real: int, saved_launches: int,
                borrowed: Optional[Dict[str, int]] = None) -> None:
    if _GLOBAL.enabled:
        _GLOBAL.note_fusion(tenant, n_members, lanes_total, lanes_real,
                            saved_launches, borrowed)


def note_regression(status: str, family: str,
                    flags: Optional[List[Dict[str, Any]]] = None) -> None:
    if _GLOBAL.enabled:
        _GLOBAL.note_regression(status, family, flags)


def note_admission(decision: str, tenant: str = "",
                   reason: str = "") -> None:
    if _GLOBAL.enabled:
        _GLOBAL.note_admission(decision, tenant, reason)


def note_protection(kind: str, n: int = 1) -> None:
    if _GLOBAL.enabled:
        _GLOBAL.note_protection(kind, n)


def note_recovery(kind: str, n: int = 1,
                  time_to_recover_s: Optional[float] = None) -> None:
    if _GLOBAL.enabled:
        _GLOBAL.note_recovery(kind, n, time_to_recover_s)
