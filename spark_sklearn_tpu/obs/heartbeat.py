"""In-flight device telemetry — scan heartbeats, live progress and ETA.

PR 16's device-resident chunk loop (``chunk_loop="scan"``) melted a
whole compile group's chunks into ONE launch, which blinded every
launch-granularity sense the service had: per-chunk spans,
``SearchFuture.progress()``, the wall-clock ``launch_timeout_s``
watchdog and the telemetry device-busy feed all see a single opaque
multi-minute dispatch.  This module is the sensor layer that restores
intra-launch visibility:

  - :class:`HeartbeatHub` — a process-global, bounded aggregator of
    *beats*.  The scanned program's step body (``search/grid.py``
    ``build_scan``) threads a ``jax.debug.callback`` beacon that calls
    :func:`device_beat` with ``(segment token, step index)`` while the
    device is still inside the launch; the per-chunk path emits a
    cheap host-side :func:`note_chunk` at dispatch.  Each beat updates
    the owning segment's ``steps_done`` / ``last_step`` /
    ``last_beat_t`` and inter-beat cadence under one named lock.
  - **live progress + ETA** — :meth:`HeartbeatHub.progress_for_handle`
    aggregates a search's live and completed segments into
    ``steps_done/steps_total`` plus an ETA whose per-step estimate
    blends the geometry cost model's prior
    (``launch_overhead_s + lanes x lane_cost_s``) with the observed
    inter-beat cadence, weighting the observation by how many beats
    back it (``serve/executor.py`` surfaces this from ``progress()``).
  - **watchdog feed** — :meth:`HeartbeatHub.staleness` tells the
    launch supervisor (``parallel/faults.py``, ``heartbeat_timeout_s``
    mode) how long ago a live segment last beat and which step it died
    on, so a hung scan is named by STEP, not by a whole-segment
    wall-clock budget.
  - **fleet surfacing** — :func:`heartbeat_block` renders the pinned
    ``search_report["heartbeat"]`` block
    (``obs.metrics.HEARTBEAT_BLOCK_SCHEMA``); :func:`snapshot_block`
    feeds the telemetry snapshot's ``heartbeat`` key (and from there
    the ``sst_heartbeat_*`` Prometheus families and
    ``tools/fleet_top.py``'s per-search progress column).

Enabled via ``TpuConfig(heartbeat=True)`` / ``SST_HEARTBEAT``
(:func:`resolve_heartbeat`).  Off (the default) is an exact no-op: no
callback is traced into the scan program (its presence joins the
program cache key in ``search/grid.py``, so on/off never alias), no
segment registers, ``cv_results_`` and ``search_report`` stay
byte-identical.  On, the contract is <2% traced wall (enforced by
``tests/test_heartbeat.py``), which is why the hub is stdlib-only and
each beat is one dict update under a lock — ``jax`` is never imported
here, so the per-chunk path and the tools can use the hub without
paying the device runtime.
"""

from __future__ import annotations

import os
import time
from collections import deque
from typing import Any, Dict, List, Optional

from spark_sklearn_tpu.obs.trace import get_tracer
from spark_sklearn_tpu.utils.locks import named_lock

__all__ = [
    "HEARTBEAT_RING_RECORDS",
    "HeartbeatHub",
    "device_beat",
    "get_hub",
    "heartbeat_block",
    "note_chunk",
    "resolve_heartbeat",
    "snapshot_block",
]

#: bounded beat-record ring (records, not bytes) — the flight
#: recorder's sizing discipline
HEARTBEAT_RING_RECORDS = 4096
#: completed segments kept for end-of-search reporting
MAX_DONE_SEGMENTS = 256
#: per-segment inter-beat gap samples kept for cadence percentiles
MAX_GAP_SAMPLES = 512


def resolve_heartbeat(config=None) -> bool:
    """Whether the in-flight heartbeat beacon is on under ``config``:
    ``TpuConfig.heartbeat``, else the ``SST_HEARTBEAT`` env var, else
    False — off is the exact-no-op default (no callback traced into
    the scan program, byte-identical reports)."""
    hb = getattr(config, "heartbeat", None) if config is not None else None
    if hb is not None:
        return bool(hb)
    env = os.environ.get("SST_HEARTBEAT", "").strip().lower()
    if not env or env in ("0", "false", "off", "no"):
        return False
    return True


def _pct(sorted_vals: List[float], p: float) -> float:
    """Nearest-rank percentile (the ``obs.telemetry.percentile``
    estimator, duplicated so the hub stays import-light)."""
    if not sorted_vals:
        return 0.0
    i = min(len(sorted_vals) - 1,
            int(round(p / 100.0 * (len(sorted_vals) - 1))))
    return float(sorted_vals[i])


class _Segment:
    """One registered scan segment's live heartbeat state."""

    __slots__ = ("key", "token", "group", "segment", "scope", "handle",
                 "tenant", "n_steps", "steps_done", "last_step",
                 "last_beat_t", "t_register", "t_done", "est_step_s",
                 "beat_count", "gaps", "gap_max_s", "cap", "host_s",
                 "complete")

    def __init__(self, key: str, token: int, *, group: int, segment: int,
                 scope: str, handle: str, tenant: str, n_steps: int,
                 est_step_s: float):
        self.key = key
        self.token = token
        self.group = group
        self.segment = segment
        self.scope = scope
        self.handle = handle
        self.tenant = tenant
        self.n_steps = int(n_steps)
        self.steps_done = 0
        self.last_step: Optional[int] = None
        self.last_beat_t: Optional[float] = None
        self.t_register = time.perf_counter()
        self.t_done: Optional[float] = None
        self.est_step_s = float(est_step_s)
        self.beat_count = 0
        self.gaps: deque = deque(maxlen=MAX_GAP_SAMPLES)
        self.gap_max_s = 0.0
        self.cap: Optional[int] = None
        self.host_s = 0.0
        self.complete = False

    def blended_step_s(self) -> float:
        """Per-step estimate blending the geometry cost model's prior
        with the observed inter-beat cadence, the observation weighted
        by its sample count — a fresh segment trusts the model, a
        well-beaten one trusts the device."""
        gaps = sorted(self.gaps)
        cadence = _pct(gaps, 50.0)
        n = len(gaps)
        model = max(0.0, self.est_step_s)
        if n == 0:
            return model
        if model <= 0.0:
            return cadence
        return (model + cadence * n) / (1.0 + n)

    def eta_s(self, now: float) -> float:
        if self.complete:
            return 0.0
        remaining = max(0, self.n_steps - self.steps_done)
        return remaining * self.blended_step_s()


class HeartbeatHub:
    """Process-global bounded aggregator of in-flight beat records.

    Producers: the scan beacon (``jax.debug.callback`` ->
    :meth:`beat`, on jax's callback thread), the per-chunk dispatch
    path (:meth:`emit_chunk`, pipeline threads) and the scan items'
    stage/finalize hooks (register/complete, worker threads).
    Consumers: the executor's ``progress()``, the supervisor's
    heartbeat watchdog, the telemetry snapshot and the report block —
    every access serializes under one named lock, and tracer calls
    happen OUTSIDE it (no cross-module lock nesting)."""

    def __init__(self, max_records: int = HEARTBEAT_RING_RECORDS):
        self._lock = named_lock("heartbeat.HeartbeatHub._lock")
        self._ring: deque = deque(maxlen=int(max_records))
        self._next_token = 1
        self._by_token: Dict[int, _Segment] = {}
        self._live_by_key: Dict[str, _Segment] = {}
        self._done: deque = deque(maxlen=MAX_DONE_SEGMENTS)
        self._beats_total = 0
        self._chunk_beats_total = 0
        self._segments_total = 0
        self._capped_dropped = 0

    # -- segment lifecycle (scan items' stage/finalize hooks) ------------
    def new_scope(self, prefix: str = "fit") -> str:
        """A fresh scope id grouping one search's segments for the
        report block — ``cid_ns`` is empty for plain (non-halving)
        fits, so the hub mints its own; a halving search's rungs share
        the scope minted at rung 0."""
        with self._lock:
            token = self._next_token
            self._next_token += 1
        return f"{prefix}-{token}"

    def register_segment(self, key: str, *, group: int = -1,
                         segment: int = 0, n_steps: int = 0,
                         scope: str = "", handle: str = "",
                         tenant: str = "",
                         est_step_s: float = 0.0) -> int:
        """Announce a scanned launch and get the runtime token its
        beats will carry.  The token is a RUNTIME operand of the cached
        scan program (never a closure capture), so one compiled
        program serves every search's segments."""
        with self._lock:
            token = self._next_token
            self._next_token += 1
            seg = _Segment(key, token, group=int(group),
                           segment=int(segment), scope=scope,
                           handle=handle, tenant=tenant,
                           n_steps=int(n_steps),
                           est_step_s=float(est_step_s))
            # a re-registered key (retry of the same segment) replaces
            # the stale registration; its token dies with it
            old = self._live_by_key.get(key)
            if old is not None:
                self._by_token.pop(old.token, None)
            self._live_by_key[key] = seg
            self._by_token[token] = seg
            self._segments_total += 1
        return token

    def complete_segment(self, key: str) -> None:
        """Mark a segment finished (its finalize ran — scan success OR
        the per-chunk OOM fallback, either way every member chunk's
        results landed), clamping ``steps_done`` to ``n_steps`` so
        progress reaches total even when beats stopped mid-scan."""
        with self._lock:
            seg = self._live_by_key.pop(key, None)
            if seg is None:
                return
            self._by_token.pop(seg.token, None)
            seg.complete = True
            seg.steps_done = seg.n_steps
            seg.t_done = time.perf_counter()
            self._done.append(seg)
        tracer = get_tracer()
        if tracer.enabled and seg.t_done is not None:
            tracer.record_async(f"heartbeat.segment {key}",
                                seg.t_register, seg.t_done,
                                track="progress", group=seg.group,
                                steps=seg.n_steps, beats=seg.beat_count)

    # -- beats -----------------------------------------------------------
    def beat(self, token: int, step: int) -> None:
        """One in-flight beat from the scanned program's step body.
        Runs on jax's callback thread while the device is mid-launch —
        kept to one locked dict update plus an optional tracer instant
        so the <2% overhead contract holds."""
        t0 = time.perf_counter()
        with self._lock:
            seg = self._by_token.get(int(token))
            if seg is None:
                return
            step = int(step)
            if seg.cap is not None and step > seg.cap:
                # injected stall drill: beats past the cap are dropped,
                # so last_step freezes exactly where the plan said
                self._capped_dropped += 1
                return
            now = time.perf_counter()
            if seg.last_beat_t is not None:
                gap = now - seg.last_beat_t
                seg.gaps.append(gap)
                if gap > seg.gap_max_s:
                    seg.gap_max_s = gap
            seg.last_beat_t = now
            seg.last_step = step if seg.last_step is None \
                else max(seg.last_step, step)
            seg.steps_done = max(seg.steps_done,
                                 min(seg.n_steps, step + 1))
            seg.beat_count += 1
            self._beats_total += 1
            self._ring.append({
                "kind": "beat", "key": seg.key, "group": seg.group,
                "segment": seg.segment, "step": step,
                "handle": seg.handle, "t_mono_s": now,
            })
            seg.host_s += time.perf_counter() - t0
            key, group = seg.key, seg.group
        tracer = get_tracer()
        if tracer.enabled:
            tracer.instant("heartbeat.beat", key=key, group=group,
                           step=step)

    def emit_chunk(self, key: str, group: int) -> None:
        """A cheap dispatch-time beat for the per-chunk launch path —
        no device callback, just the hub hearing that chunk ``key``
        entered the device stream."""
        with self._lock:
            self._chunk_beats_total += 1
            self._ring.append({
                "kind": "chunk", "key": str(key), "group": int(group),
                "segment": -1, "step": -1, "handle": "",
                "t_mono_s": time.perf_counter(),
            })

    # -- watchdog + injection feeds --------------------------------------
    def live_segment(self, key: str) -> bool:
        """Whether a registered, un-completed segment owns ``key`` —
        the supervisor's gate for heartbeat-mode waiting."""
        with self._lock:
            return key in self._live_by_key

    def staleness(self, key: str) -> Optional[Dict[str, Any]]:
        """The heartbeat watchdog's view of a live segment: seconds
        since its last beat (registration when none arrived yet), the
        last step that beat, and the segment's step count.  None when
        no live segment owns ``key``."""
        with self._lock:
            seg = self._live_by_key.get(key)
            if seg is None:
                return None
            now = time.perf_counter()
            anchor = seg.last_beat_t if seg.last_beat_t is not None \
                else seg.t_register
            return {
                "age_s": max(0.0, now - anchor),
                "last_step": seg.last_step,
                "steps_done": seg.steps_done,
                "n_steps": seg.n_steps,
            }

    def cap_beats(self, key: str, max_step: int) -> bool:
        """Deterministic stall drill (``fault_plan="hung@I:STEP"``):
        drop every beat past ``max_step`` on ``key``'s live segment so
        the heartbeat goes silent at exactly that step and the
        watchdog's staleness detector fires naming it."""
        with self._lock:
            seg = self._live_by_key.get(key)
            if seg is None:
                return False
            seg.cap = int(max_step)
            return True

    # -- progress / ETA --------------------------------------------------
    def _segments_for(self, *, handle: Optional[str] = None,
                      scope: Optional[str] = None) -> List[_Segment]:
        segs = list(self._live_by_key.values()) + list(self._done)
        if handle is not None:
            segs = [s for s in segs if s.handle == handle]
        if scope is not None:
            segs = [s for s in segs if s.scope == scope]
        return segs

    def _progress_of(self, segs: List[_Segment]) -> Optional[Dict[str, Any]]:
        if not segs:
            return None
        now = time.perf_counter()
        total = sum(s.n_steps for s in segs)
        done = sum(s.steps_done for s in segs)
        return {
            "segments": len(segs),
            "steps_total": int(total),
            "steps_done": int(done),
            "frac": round(done / total, 6) if total else 0.0,
            "eta_s": round(sum(s.eta_s(now) for s in segs), 6),
            "beats": int(sum(s.beat_count for s in segs)),
        }

    def progress_for_handle(self, handle: str) -> Optional[Dict[str, Any]]:
        """Live intra-segment progress for one executor search handle
        — None when the handle has no (heartbeat-registered) segments,
        so a heartbeat-off search's ``progress()`` dict is unchanged."""
        if not handle:
            return None
        with self._lock:
            return self._progress_of(self._segments_for(handle=handle))

    def progress_by_handle(self) -> Dict[str, Dict[str, Any]]:
        """Every handle's progress view — the telemetry snapshot's
        ``heartbeat.searches`` map (what ``tools/fleet_top.py``
        renders as the progress/ETA column)."""
        with self._lock:
            handles = sorted({s.handle for s in self._segments_for()
                              if s.handle})
            return {h: self._progress_of(self._segments_for(handle=h))
                    for h in handles}

    # -- reporting -------------------------------------------------------
    def _scope_stats(self, scope: Optional[str]) -> Dict[str, Any]:
        with self._lock:
            segs = self._segments_for(scope=scope) if scope \
                else self._segments_for()
            gaps = sorted(g for s in segs for g in s.gaps)
            walls = [((s.t_done if s.t_done is not None
                       else time.perf_counter()) - s.t_register)
                     for s in segs]
            wall = sum(walls)
            host = sum(s.host_s for s in segs)
            return {
                "beats": sum(s.beat_count for s in segs),
                "chunk_beats": self._chunk_beats_total,
                "segments": len(segs),
                "steps_total": sum(s.n_steps for s in segs),
                "steps_done": sum(s.steps_done for s in segs),
                "p50": _pct(gaps, 50.0),
                "p95": _pct(gaps, 95.0),
                "stale_max": max([s.gap_max_s for s in segs],
                                 default=0.0),
                "host_s": host,
                "wall_s": wall,
            }

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "beats_total": self._beats_total,
                "chunk_beats_total": self._chunk_beats_total,
                "segments_total": self._segments_total,
                "live_segments": len(self._live_by_key),
                "capped_dropped": self._capped_dropped,
            }

    def reset(self) -> None:
        """Drop all beat/segment state (test isolation)."""
        with self._lock:
            self._ring.clear()
            self._by_token.clear()
            self._live_by_key.clear()
            self._done.clear()
            self._beats_total = 0
            self._chunk_beats_total = 0
            self._segments_total = 0
            self._capped_dropped = 0


_HUB = HeartbeatHub()


def get_hub() -> HeartbeatHub:
    """The process-global heartbeat hub every beacon reports to."""
    return _HUB


def device_beat(token, step) -> None:
    """The ``jax.debug.callback`` target the scan step body calls:
    receives the segment token and step index as numpy scalars while
    the device is mid-launch (``search/grid.py`` makes the jax call —
    this module never imports jax)."""
    _HUB.beat(int(token), int(step))


def note_chunk(key: str, group: int) -> None:
    """Per-chunk dispatch beat (``parallel/pipeline.py`` calls this
    only when the pipeline resolved heartbeat on, so off stays an
    exact no-op)."""
    _HUB.emit_chunk(str(key), int(group))


def heartbeat_block(scope: str = "") -> Dict[str, Any]:
    """Render the ``search_report["heartbeat"]`` block for one
    search's scope (schema pinned in
    ``obs.metrics.HEARTBEAT_BLOCK_SCHEMA``).  Rendered ONLY when the
    heartbeat resolved on — off keeps the report byte-identical to
    the pre-heartbeat shape, like the memory block's discipline."""
    st = _HUB._scope_stats(scope or None)
    wall = st["wall_s"]
    return {
        "enabled": True,
        "beats_total": int(st["beats"]),
        "chunk_beats_total": int(st["chunk_beats"]),
        "n_segments": int(st["segments"]),
        "steps_total": int(st["steps_total"]),
        "steps_done": int(st["steps_done"]),
        "cadence_p50_s": round(st["p50"], 6),
        "cadence_p95_s": round(st["p95"], 6),
        "staleness_max_s": round(st["stale_max"], 6),
        "overhead_est_s": round(st["host_s"], 6),
        "overhead_frac": round(st["host_s"] / wall, 6)
        if wall > 0 else 0.0,
    }


def snapshot_block() -> Dict[str, Any]:
    """The telemetry snapshot's ``heartbeat`` entry: process-wide beat
    totals plus every live search handle's progress/ETA view (the
    fleet endpoint's ``sst_heartbeat_*`` families and
    ``tools/fleet_top.py`` read this)."""
    st = _HUB._scope_stats(None)
    block: Dict[str, Any] = _HUB.stats()
    block["cadence_p50_s"] = round(st["p50"], 6)
    block["cadence_p95_s"] = round(st["p95"], 6)
    block["staleness_max_s"] = round(st["stale_max"], 6)
    block["searches"] = _HUB.progress_by_handle()
    return block
