"""Structured logger — the `verbose > 0` paths' replacement for print().

Two emit channels with different contracts:

  - :meth:`StructuredLogger.print` — **stdout parity**: writes exactly
    the given line via the builtin ``print`` (sklearn's ``[CV i/n] END
    ...`` verbose format is pinned byte-for-byte by test), and mirrors
    a structured record to the stdlib ``logging`` channel plus an
    instant event into the tracer when one is recording — so verbose
    output lands on the exported timeline next to the launches that
    produced it.
  - :meth:`StructuredLogger.info` / :meth:`StructuredLogger.debug` —
    logging-channel only (never stdout): operational messages that have
    no legacy print contract (pipeline per-launch records, session
    bootstrap, compile-ahead fallbacks).

Loggers live under the ``spark_sklearn_tpu.*`` namespace of the stdlib
``logging`` module, so users attach handlers/levels the standard way.

Two fleet-telemetry integrations (ISSUE 8), both zero-cost on the
default path:

  - every structured record is stamped with the calling thread's
    tenant/search-handle correlation
    (:func:`~spark_sklearn_tpu.obs.trace.current_correlation`), so a
    multi-tenant log stream attributes each line to its search;
  - WARNING-and-up records additionally land in the always-on flight
    recorder ring (:mod:`spark_sklearn_tpu.obs.telemetry`), so a
    black-box bundle carries the warnings that led up to the incident.
"""

from __future__ import annotations

import logging
from typing import Any, Dict

from spark_sklearn_tpu.obs.trace import current_correlation, get_tracer
from spark_sklearn_tpu.utils import locks as _locks

__all__ = ["StructuredLogger", "get_logger"]


class StructuredLogger:
    """Thin wrapper pairing print-parity emits with structured
    records."""

    __slots__ = ("_log",)

    def __init__(self, name: str):
        self._log = logging.getLogger(name)

    @property
    def logger(self) -> logging.Logger:
        return self._log

    def print(self, msg: str, **fields: Any) -> None:
        """Emit `msg` to stdout byte-for-byte (the legacy ``print()``
        contract) and mirror it as a DEBUG logging record + a trace
        instant carrying the structured fields."""
        print(msg)
        if self._log.isEnabledFor(logging.DEBUG):
            self._log.debug("%s", msg,
                            extra={"sst_fields": dict(fields)})
        tracer = get_tracer()
        if tracer.enabled:
            tracer.instant("log", message=msg, **fields)

    def _emit(self, level: int, msg: str, args, fields: Dict[str, Any]):
        if self._log.isEnabledFor(level):
            corr = current_correlation()
            stamped = {**corr, **fields} if corr else dict(fields)
            self._log.log(level, msg, *args,
                          extra={"sst_fields": stamped})
        if level >= logging.WARNING:
            # the black box keeps the warnings that led up to an
            # incident (correlation is stamped by the recorder itself)
            from spark_sklearn_tpu.obs import telemetry as _telemetry
            try:
                rendered = msg % args if args else msg
            except (TypeError, ValueError):
                rendered = msg
            _telemetry.flight_recorder().note(
                "log", level=logging.getLevelName(level),
                logger=self._log.name, message=rendered, **fields)

    def info(self, msg: str, *args: Any, **fields: Any) -> None:
        self._emit(logging.INFO, msg, args, fields)

    def debug(self, msg: str, *args: Any, **fields: Any) -> None:
        self._emit(logging.DEBUG, msg, args, fields)

    def warning(self, msg: str, *args: Any, **fields: Any) -> None:
        self._emit(logging.WARNING, msg, args, fields)


_LOGGERS: Dict[str, StructuredLogger] = {}
_LOGGERS_LOCK = _locks.named_lock("log._LOGGERS_LOCK")


def get_logger(name: str) -> StructuredLogger:
    """Cached StructuredLogger for a dotted module name."""
    lg = _LOGGERS.get(name)
    if lg is None:
        with _LOGGERS_LOCK:
            lg = _LOGGERS.setdefault(name, StructuredLogger(name))
    return lg
