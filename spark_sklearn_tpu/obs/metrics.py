"""Metrics registry — ``search_report``'s schema pinned in one place.

Before this module the search engine hand-assembled ``search_report``
dicts in ``search/grid.py`` (and ``parallel/pipeline.py`` its
``pipeline`` block): the schema lived implicitly in a dozen mutation
sites.  Now every report key is declared once in
:data:`SEARCH_REPORT_SCHEMA` (name, kind, description), the engine
updates typed metric handles (counters / gauges / histograms / series /
structs), and the report the user reads is the registry's rendered
view — so the schema is documented from the same definitions the code
writes through (``schema_markdown()`` feeds ``docs/API.md``).

Backward compatibility contract: the rendered dict is key-for-key and
value-type compatible with the pre-registry reports; a registry in
strict mode (the default for ``search_registry``) refuses to create a
metric whose name or kind is not declared, so the schema cannot drift
silently.
"""

from __future__ import annotations

import dataclasses
from collections import OrderedDict
from typing import Any, Dict, Iterable, Optional

__all__ = [
    "MetricDef",
    "MetricsRegistry",
    "SEARCH_REPORT_SCHEMA",
    "PIPELINE_BLOCK_SCHEMA",
    "FAULTS_BLOCK_SCHEMA",
    "DATAPLANE_BLOCK_SCHEMA",
    "GEOMETRY_BLOCK_SCHEMA",
    "PROGRAMSTORE_BLOCK_SCHEMA",
    "SCHEDULER_BLOCK_SCHEMA",
    "HALVING_BLOCK_SCHEMA",
    "CHUNKLOOP_BLOCK_SCHEMA",
    "PREFIX_BLOCK_SCHEMA",
    "MEMORY_BLOCK_SCHEMA",
    "STREAMING_BLOCK_SCHEMA",
    "ATTRIBUTION_BLOCK_SCHEMA",
    "PROTECTION_BLOCK_SCHEMA",
    "HEARTBEAT_BLOCK_SCHEMA",
    "RECOVERY_BLOCK_SCHEMA",
    "TELEMETRY_SNAPSHOT_SCHEMA",
    "search_registry",
    "schema_markdown",
]


@dataclasses.dataclass(frozen=True)
class MetricDef:
    """One declared metric: its name, kind and human description."""

    name: str
    kind: str          # counter | gauge | histogram | series | struct | label
    description: str
    #: which backends emit it ("tpu", "host", "tpu,host")
    backends: str = "tpu"


#: the pinned schema of ``BaseSearchTPU.search_report``
SEARCH_REPORT_SCHEMA = (
    MetricDef(
        "backend", "label",
        "Execution tier that ran the search: 'tpu' (compiled, the "
        "candidates x folds grid lowered onto the mesh) or 'host' "
        "(sklearn `_fit_and_score` fanned out with joblib).",
        backends="tpu,host"),
    MetricDef(
        "n_compile_groups", "gauge",
        "Number of static-signature compile groups the candidate grid "
        "partitioned into (one jitted program pair per group)."),
    MetricDef(
        "n_launches", "counter",
        "Device launches executed (fit/score/calibrate/fused chunks; "
        "resumed chunks do not launch)."),
    MetricDef(
        "n_chunks_resumed", "counter",
        "Chunks whose results were restored from the checkpoint "
        "instead of launched (TpuConfig.checkpoint_dir)."),
    MetricDef(
        "fit_wall_s", "gauge",
        "Summed device wall attributed to fitting across all launches "
        "(fused launches attribute out the calibrated score share)."),
    MetricDef(
        "score_wall_s", "gauge",
        "Summed device wall attributed to scoring across all launches, "
        "including the per-group warm calibration launch."),
    MetricDef(
        "mesh", "struct",
        "Mesh geometry the search ran on: {'task': n_task_shards, "
        "'data': n_data_shards}."),
    MetricDef(
        "per_group", "struct",
        "Per-compile-group record: static_params (repr), n_launches, "
        "fit_wall_s, score_wall_s, score_path "
        "(scan-fused/wide-fused/wide/nested) and, when fused chunks "
        "calibrated, score_s_per_task_calibrated."),
    MetricDef(
        "solver_iters_per_launch", "series",
        "Per-launch max executed solver iterations over the launch's "
        "lanes (lockstep semantics; -1 launches are omitted)."),
    MetricDef(
        "solver_iters_sum_per_launch", "series",
        "Per-launch sum of executed solver iterations over lanes "
        "(per-lane semantics for scan-sequential families)."),
    MetricDef(
        "lanes_per_launch", "series",
        "Per-launch padded lane count (candidate x fold program "
        "instances actually computed, including padding)."),
    MetricDef(
        "padding_waste", "histogram",
        "Per-launch fraction of computed lanes that were padding "
        "(chunk tail repeated to the group's uniform width) — the "
        "price of one-compile-per-group chunking."),
    MetricDef(
        "pipeline", "struct",
        "The chunk scheduler's timeline (see the pipeline-block schema "
        "below): per-phase walls, overlap_frac, n_compiles, "
        "n_precompiled, persistent-cache traffic and the per-launch "
        "records."),
    MetricDef(
        "faults", "struct",
        "The launch supervisor's recovery record (see the faults-block "
        "schema below): retry/bisection/host-fallback/timeout counters, "
        "per-class fault counts and the per-event journal "
        "(parallel/faults.py).  On the host tier the block carries the "
        "exception that pushed the compiled tier to fall back, when "
        "one did.",
        backends="tpu,host"),
    MetricDef(
        "dataplane", "struct",
        "The device data plane's traffic during this search (see the "
        "dataplane-block schema below): cache hits/misses, bytes "
        "uploaded vs reused, staging bytes, and the plane's "
        "end-of-search state (parallel/dataplane.py)."),
    MetricDef(
        "geometry", "struct",
        "The waste-aware launch-geometry plan this search ran under "
        "(see the geometry-block schema below): per-group chunk "
        "widths, the cost model that chose them, and whether the plan "
        "was computed, served from the in-process plan cache, seeded "
        "from the persistent program store, or replayed from the "
        "checkpoint journal (parallel/taskgrid.plan_geometry)."),
    MetricDef(
        "programstore", "struct",
        "The persistent AOT program store's traffic during this "
        "search (see the programstore-block schema below): artifact "
        "hits/misses/publishes, bytes loaded vs saved, quarantines, "
        "and the store's end-of-search state "
        "(parallel/programstore.py)."),
    MetricDef(
        "scheduler", "struct",
        "The multi-tenant fair-share executor's per-search view (see "
        "the scheduler-block schema below): queue waits, interleave "
        "fraction and measured tenant shares when the search was "
        "submitted to a TpuSession's SearchExecutor; the zeroed "
        "enabled=False shape for a standalone fit "
        "(serve/executor.py)."),
    MetricDef(
        "halving", "struct",
        "Successive-halving searches only (see the halving-block "
        "schema below): per-rung candidate counts, resources, chunk "
        "widths, walls and the lanes reclaimed by mid-search "
        "geometry re-planning (search/halving.py).  Absent on "
        "exhaustive searches.",
        backends="tpu,host"),
    MetricDef(
        "chunkloop", "struct",
        "The chunk-loop mode's per-search view (see the "
        "chunkloop-block schema below): whether the device-resident "
        "scan loop ran (TpuConfig.chunk_loop='scan' / SST_CHUNK_LOOP), "
        "segments executed and chunks melted into them, launches "
        "saved, fallback reasons, and halving's device-vs-host rung "
        "elimination counts (search/grid.py scan path)."),
    MetricDef(
        "prefix", "struct",
        "The shared-prefix scheduler's per-search view (see the "
        "prefix-block schema below): whether Pipeline prefixes were "
        "staged (TpuConfig.prefix_reuse / SST_PREFIX_REUSE), distinct "
        "prefix digests vs candidates, device launches vs plane/"
        "journal re-use, recomputations saved and the recorded "
        "fallback reasons (search/prefix.py + search/grid.py stage-1 "
        "scheduler)."),
    MetricDef(
        "memory", "struct",
        "The device-memory ledger's per-search view (see the "
        "memory-block schema below): modeled per-compile-group "
        "footprints, the HBM budget/width-ceiling state, the measured "
        "watermark and the model-vs-measured error "
        "(parallel/memledger.py).  Absent when "
        "TpuConfig(memory_ledger=False) — the byte-identical "
        "pre-ledger report shape."),
    MetricDef(
        "streaming", "struct",
        "The streaming-fold data plane's per-search view (see the "
        "streaming-block schema below): the analytic shard plan "
        "(rows/shards/bytes, whether the HBM budget capped it), "
        "shards streamed vs resumed per pass, and the measured "
        "host->device bytes (search/stream.py).  Present only when "
        "the search ran with data_mode='stream'."),
    MetricDef(
        "attribution", "struct",
        "The search doctor's critical-path decomposition (see the "
        "attribution-block schema below): the measured search wall "
        "split into pinned causes (compile/stage/compute/gather/"
        "queue wait/faults/padding/memory-cap narrowing), a one-line "
        "verdict, per-rung lanes for halving searches and the "
        "regression sentinel's judgment against the run log's "
        "baseline (obs/attribution.py).  Absent when "
        "TpuConfig(attribution=False) — the byte-identical "
        "pre-doctor report shape."),
    MetricDef(
        "protection", "struct",
        "The self-protecting service's verdict for this search (see "
        "the protection-block schema below): deadline state, shed and "
        "quarantined candidates, and whether the returned cv_results_ "
        "is declared partial (parallel/faults.py protection_block).  "
        "Absent when protection is off (no search_deadline_s, "
        "partial_results='raise', admission_mode='static') — the "
        "byte-identical pre-protection report shape.",
        backends="tpu,host"),
    MetricDef(
        "heartbeat", "struct",
        "The in-flight heartbeat view for this search (see the "
        "heartbeat-block schema below): beats and steps observed, "
        "inter-beat cadence percentiles, staleness and the host-side "
        "overhead estimate (obs/heartbeat.py).  Absent when the "
        "heartbeat is off (TpuConfig.heartbeat / SST_HEARTBEAT "
        "unset) — the byte-identical beacon-less report shape."),
    MetricDef(
        "n_tasks", "gauge",
        "Host tier: number of (candidate, fold) fit-and-score tasks.",
        backends="host"),
    MetricDef(
        "n_jobs", "gauge",
        "Host tier: joblib worker count the fan-out used.",
        backends="host"),
)

#: sub-keys of ``search_report["pipeline"]`` (written by
#: ``parallel.pipeline.ChunkPipeline.report`` plus the engine's cache /
#: compile counters) — documented here so the whole report schema lives
#: in one module.
PIPELINE_BLOCK_SCHEMA = (
    MetricDef("depth", "gauge",
              "Pipeline depth the search ran at (0 = synchronous)."),
    MetricDef("n_launches", "counter",
              "Launches the pipeline executed."),
    MetricDef("wall_s", "gauge", "The run's actual wall."),
    MetricDef("stage_wall_s", "gauge",
              "Sum of host staging walls (stage thread)."),
    MetricDef("dispatch_wall_s", "gauge",
              "Sum of dispatch walls (async enqueue; a first dispatch "
              "includes trace+compile)."),
    MetricDef("compute_wall_s", "gauge",
              "Sum of device-occupancy estimates."),
    MetricDef("gather_wall_s", "gauge",
              "Sum of blocking device->host transfer walls."),
    MetricDef("finalize_wall_s", "gauge",
              "Sum of result-write/checkpoint walls."),
    MetricDef("queue_wait_wall_s", "gauge",
              "Sum of multi-tenant fair-share queue waits across "
              "launches (serve/executor.py; subtracted out of "
              "dispatch_wall_s so contention never poisons the "
              "geometry cost model)."),
    MetricDef("overlap_frac", "gauge",
              "Host work hidden behind device compute, as a fraction "
              "of all host work."),
    MetricDef("n_precompiled", "counter",
              "Programs the compile thread AOT-compiled ahead of "
              "dispatch."),
    MetricDef("n_compiles", "counter",
              "Distinct traced-program constructions this search "
              "(program-cache misses)."),
    MetricDef("persistent_cache_hits", "counter",
              "Persistent XLA compilation-cache hits during this "
              "search."),
    MetricDef("persistent_cache_misses", "counter",
              "Persistent XLA compilation-cache misses during this "
              "search."),
    MetricDef("stage_bytes_total", "gauge",
              "Total host->device bytes the launches' stage phases "
              "transferred (data-plane accounting; cache hits "
              "transfer nothing and count zero)."),
    MetricDef("epoch_s", "gauge",
              "The run epoch: perf_counter timestamp of the first "
              "run() call — per-launch t0_s/t1_s (and tracer spans) "
              "are in this timebase."),
    MetricDef("launches", "series",
              "One record per launch: key, group, kind "
              "(fit/score/calibrate/fused/scan), n_tasks, n_chunks "
              "(chunks the launch served: 1 per-chunk, the segment's "
              "member count for scan), stage_bytes "
              "(host->device transfer during its stage), per-phase "
              "walls (stage_s/stage_wait_s/dispatch_s/compute_s/"
              "gather_s/finalize_s) and the launch's t0_s/t1_s "
              "window relative to the pipeline's run epoch (what the "
              "attribution analyzer slices per halving rung)."),
)

#: sub-keys of ``search_report["dataplane"]`` (written by
#: ``parallel.dataplane.report_block``) — this search's broadcast-cache
#: traffic plus the plane's end-of-search state.
DATAPLANE_BLOCK_SCHEMA = (
    MetricDef("enabled", "label",
              "Whether the device data plane was active "
              "(TpuConfig.dataplane_bytes > 0)."),
    MetricDef("hits", "counter",
              "Cache hits this search: device arrays (X/y, fold "
              "masks, tiled masks, pad zeros) reused without any "
              "host->device transfer."),
    MetricDef("misses", "counter",
              "Cache misses this search (each one uploaded or "
              "device-tiled a new resident entry)."),
    MetricDef("evictions", "counter",
              "LRU entries dropped this search to respect the byte "
              "budget."),
    MetricDef("bytes_uploaded", "gauge",
              "Host->device bytes of CACHEABLE broadcast traffic this "
              "search (X/y, fold masks, pad zeros).  Zero on a fully "
              "warm search — the acceptance signal that nothing was "
              "re-shipped."),
    MetricDef("bytes_tiled", "gauge",
              "Bytes materialized by ON-DEVICE mask tiling this "
              "search (no host->device transfer; replaces the host "
              "np.tile + upload per compile group)."),
    MetricDef("bytes_staged", "gauge",
              "Host->device bytes of per-chunk dynamic-parameter "
              "staging this search (inherently per-launch; not "
              "cacheable)."),
    MetricDef("n_entries", "gauge",
              "Entries resident in the plane after the search."),
    MetricDef("bytes_in_cache", "gauge",
              "Bytes resident in the plane after the search."),
    MetricDef("budget_bytes", "gauge",
              "The plane's byte budget (TpuConfig.dataplane_bytes)."),
    MetricDef("mask_tiling", "label",
              "How task-batched fold masks were produced: 'device' "
              "(plane-cached on-device broadcast), 'host' (legacy "
              "np.tile + upload), or 'n/a' (family does not tile)."),
)

#: sub-keys of ``search_report["geometry"]`` (written by
#: ``parallel.taskgrid.GeometryPlan.report_block``) — the launch
#: geometry the search ran under, pinned so resumes can replay it.
GEOMETRY_BLOCK_SCHEMA = (
    MetricDef("mode", "label",
              "TpuConfig.geometry_mode: 'auto' (waste-aware planner) "
              "or 'fixed' (legacy width rule)."),
    MetricDef("source", "label",
              "Where the plan came from: 'computed' (fresh), "
              "'plan-cache' (first in-process plan for this structure "
              "reused), 'store' (seeded from the persistent program "
              "store's plans.json, so a fresh process replays the "
              "publishing process's widths), or 'journal' (replayed "
              "from the checkpoint so resume reuses the exact same "
              "chunk ids)."),
    MetricDef("planned_launches", "gauge",
              "Total chunk launches the plan schedules across all "
              "compile groups."),
    MetricDef("planned_waste_frac", "gauge",
              "Fraction of planned candidate lanes that are padding "
              "(the quantity the planner minimizes against launch "
              "overhead)."),
    MetricDef("cost_model", "struct",
              "The cost-model snapshot that priced the plan: "
              "launch_overhead_s, lane_cost_s, compile_wall_s (a "
              "PER-PROGRAM build wall — observe() divides the "
              "compile excess by the launch's program-build count, "
              "so chunk_loop=\"scan\"'s coarse launches don't skew "
              "it), n_observations, source "
              "(default/measured/override)."),
    MetricDef("groups", "series",
              "Per compile group: group index, n_candidates, chosen "
              "width, n_chunks, and whether convergence-sorted "
              "chunking pinned the width."),
)

#: sub-keys of ``search_report["programstore"]`` (written by
#: ``parallel.programstore.report_block``) — this search's persistent
#: AOT-artifact traffic plus the store's end-of-search state.
PROGRAMSTORE_BLOCK_SCHEMA = (
    MetricDef("enabled", "label",
              "Whether a persistent program store was active "
              "(TpuConfig.program_store_dir / SST_PROGRAM_STORE_DIR)."),
    MetricDef("hits", "counter",
              "Programs served from serialized AOT artifacts this "
              "search — each one skipped the whole python->jaxpr->"
              "StableHLO walk.  Covering every compile group makes a "
              "cold process's n_compiles zero."),
    MetricDef("misses", "counter",
              "Store lookups that found no artifact this search (the "
              "program traced, was exported, and published for the "
              "next process)."),
    MetricDef("publishes", "counter",
              "Artifacts serialized and atomically written this "
              "search."),
    MetricDef("bytes_loaded", "gauge",
              "Artifact bytes read from disk this search (memory-"
              "cache and prewarmed hits read nothing and count "
              "zero)."),
    MetricDef("bytes_saved", "gauge",
              "Artifact bytes published this search."),
    MetricDef("quarantined", "counter",
              "Corrupt artifacts moved to the store's quarantine "
              "directory this search (each fell back to a clean jit "
              "recompile; never a failed search)."),
    MetricDef("evictions", "counter",
              "Oldest artifacts dropped this search to respect the "
              "store byte budget (TpuConfig.program_store_bytes)."),
    MetricDef("prewarmed", "counter",
              "Artifacts loaded by manifest prewarm this PROCESS "
              "(TpuSession.prewarm; cumulative, not per-search)."),
    MetricDef("n_entries", "gauge",
              "Artifacts resident on disk for this environment after "
              "the search."),
    MetricDef("store_bytes", "gauge",
              "Artifact bytes resident on disk for this environment "
              "after the search."),
    MetricDef("dir", "label",
              "The store's root directory."),
)

#: sub-keys of ``search_report["faults"]`` (written by
#: ``parallel.faults.LaunchSupervisor``) — the recovery contract's
#: observable surface, pinned next to the rest of the report schema.
FAULTS_BLOCK_SCHEMA = (
    MetricDef("retries", "counter",
              "Transient-fault retry attempts performed (exponential "
              "backoff + deterministic jitter; budgets: "
              "TpuConfig.max_launch_retries / max_search_retries)."),
    MetricDef("bisections", "counter",
              "OOM chunk bisections performed (each split relaunches "
              "the chunk as two half-width launches, lanes re-padded "
              "via parallel/taskgrid.pad_chunk)."),
    MetricDef("host_fallbacks", "counter",
              "Ranges degraded to per-candidate host execution with "
              "exact sklearn error_score semantics (bisection bottomed "
              "out, or the item had no bisect hook)."),
    MetricDef("timeouts", "counter",
              "Launches failed by the watchdog for exceeding "
              "TpuConfig.launch_timeout_s (each raises a clean "
              "LaunchTimeoutError naming the chunk and compile "
              "group)."),
    MetricDef("injected", "counter",
              "Faults injected by the deterministic fault plan "
              "(TpuConfig.fault_plan / SST_FAULT_PLAN)."),
    MetricDef("by_class", "struct",
              "Observed fault counts keyed by taxonomy class "
              "(transient/oom/hung/fatal)."),
    MetricDef("events", "series",
              "Per-event journal (bounded at 64 records): key, group, "
              "class, action (retry/recover/bisect/host_fallback/"
              "fail/raise/retries_exhausted), attempt, error."),
    MetricDef("fallback_exception", "label",
              "Host tier only: the exception type (and truncated "
              "message) that made the compiled tier fall back to the "
              "host backend, when the search started compiled."),
)


#: sub-keys of ``search_report["scheduler"]`` (written by
#: ``serve.executor.report_block`` / ``SearchExecutor.search_block``) —
#: the multi-tenant fair-share executor's per-search view.
SCHEDULER_BLOCK_SCHEMA = (
    MetricDef("enabled", "label",
              "Whether the search ran under a session's fair-share "
              "executor (TpuSession.submit / attach); False for a "
              "standalone fit, with every other key zeroed."),
    MetricDef("tenant", "label",
              "The search's tenant id (TpuConfig.tenant / SST_TENANT; "
              "'default' when unset)."),
    MetricDef("handle", "label",
              "The executor-assigned search handle id "
              "(tenant/s<sequence>)."),
    MetricDef("weight", "gauge",
              "The tenant's fair-share weight "
              "(TpuConfig.tenant_weight / SST_TENANT_WEIGHT)."),
    MetricDef("n_dispatches", "counter",
              "Chunk dispatches the search issued through the "
              "executor (queued + fastpath)."),
    MetricDef("n_fastpath", "counter",
              "Dispatches short-circuited inline because this was the "
              "only active search with empty queues — the solo-search "
              "zero-overhead path."),
    MetricDef("n_interleaved", "counter",
              "Dispatches immediately preceded on the shared dispatch "
              "stream by a DIFFERENT search's dispatch."),
    MetricDef("interleave_frac", "gauge",
              "n_interleaved / n_dispatches — > 0 proves the device "
              "stream interleaved this search's chunks with "
              "concurrent searches'."),
    MetricDef("queue_wait_s", "gauge",
              "Total time the search's chunks waited in the "
              "fair-share queue before dispatch."),
    MetricDef("queue_wait_mean_s", "gauge",
              "Mean queue wait per routed (non-fastpath) dispatch."),
    MetricDef("queue_wait_max_s", "gauge",
              "Worst single queue wait."),
    MetricDef("share_frac", "gauge",
              "This search's dispatched task-cost share of ALL cost "
              "dispatched during its active window."),
    MetricDef("tenant_shares", "struct",
              "Measured per-tenant dispatched-cost shares over this "
              "search's active window — under contention these track "
              "the configured tenant weights."),
    MetricDef("waits", "series",
              "Per routed dispatch: {tenant, wait_s} record of the "
              "seconds waited in the queue (bounded sample, tenant-"
              "stamped so merged samples from concurrent searches "
              "still attribute; bench derives PER-TENANT p50/p95 "
              "from it)."),
    MetricDef("n_fused", "counter",
              "Chunks of this search that rode a cross-search fused "
              "launch (one wide device program serving several "
              "tenants' same-program chunks).  Present only when "
              "fusion is enabled (TpuConfig.fusion / SST_FUSION)."),
    MetricDef("lanes_donated", "counter",
              "Real candidate lanes OTHER searches ran on fused "
              "launches this search led.  Present only when fusion "
              "is enabled."),
    MetricDef("lanes_borrowed", "counter",
              "Real candidate lanes this search ran on fused launches "
              "led by ANOTHER search.  Present only when fusion is "
              "enabled."),
    MetricDef("fusion_saved_launches", "counter",
              "Device launches avoided by fused launches this search "
              "led (members - 1 per fused launch).  Present only when "
              "fusion is enabled."),
)


#: sub-keys of ``search_report["halving"]`` (written by
#: ``search.halving._render_halving_block``) — the adaptive-search
#: scheduler's observable surface: what each rung cost and what lane
#: reclamation saved.
HALVING_BLOCK_SCHEMA = (
    MetricDef("enabled", "label",
              "Always True when present: the block only renders for "
              "HalvingGridSearchCV / HalvingRandomSearchCV fits."),
    MetricDef("factor", "gauge",
              "The halving factor: each rung keeps "
              "ceil(n_candidates / factor) survivors."),
    MetricDef("resource", "label",
              "The budgeted resource: 'n_samples' (fold-mask "
              "subsampling) or an estimator parameter (e.g. "
              "'n_estimators' via the masked-prefix trick)."),
    MetricDef("replan", "label",
              "Whether mid-search lane reclamation was on "
              "(TpuConfig.halving_replan): rungs re-planned into "
              "narrower chunks vs. survivors padded to rung-0 "
              "widths."),
    MetricDef("min_rung_width", "gauge",
              "The configured floor on re-planned rung widths "
              "(TpuConfig.min_rung_width; 0 = shard multiple only)."),
    MetricDef("n_rungs", "gauge",
              "Rungs executed (== n_iterations_)."),
    MetricDef("lanes_reclaimed_total", "gauge",
              "Total (candidate x fold) lanes the re-planner retired "
              "across rungs, vs. running every rung at its rung-0 "
              "chunk widths — freed device lanes instead of padding "
              "waste."),
    MetricDef("rungs", "series",
              "One record per rung: iter, n_candidates, n_resources, "
              "wall_s, widths (per compile group), "
              "n_launches_planned, n_chunks_resumed, "
              "lanes_reclaimed, padding_saved_frac, pipe_wall_s, "
              "cost_observations (the geometry cost model's "
              "observation count when the rung planned — increasing "
              "across rungs proves mid-search feedback) and "
              "launches_end (the rung's end boundary in the shared "
              "pipeline's cumulative launch timeline, consumed by "
              "the attribution analyzer's per-rung slicing)."),
)


#: sub-keys of ``search_report["chunkloop"]`` (written by
#: ``search.grid.chunkloop_block`` and mutated in place by the scan
#: finalizers and halving's elimination accounting) — the
#: device-resident chunk loop's per-search view.  Emitted for BOTH
#: loop modes: per-chunk searches report the zeroed ``enabled=False``
#: shape so the report schema never changes.
CHUNKLOOP_BLOCK_SCHEMA = (
    MetricDef("mode", "label",
              "The resolved chunk-loop mode: 'per_chunk' (default; "
              "one launch per chunk) or 'scan' "
              "(TpuConfig.chunk_loop / SST_CHUNK_LOOP)."),
    MetricDef("enabled", "label",
              "True when the scan path actually ran: mode='scan' AND "
              "the fused score path was available (the scan body is "
              "the fused program)."),
    MetricDef("n_segments", "counter",
              "Scan segments executed — each is ONE device launch "
              "serving a whole compile group (or the memory-ledger-"
              "sized slice of one)."),
    MetricDef("n_chunks_scanned", "counter",
              "Chunks melted into scan segments (journalled "
              "per chunk, so kill-resume replays at scan-segment "
              "granularity)."),
    MetricDef("n_launches_saved", "counter",
              "Launch boundaries the scan melted: sum over segments "
              "of (member chunks - 1) vs. the per-chunk path."),
    MetricDef("segment_lengths", "series",
              "Member-chunk count of each executed segment, in "
              "dispatch order."),
    MetricDef("fallbacks", "series",
              "Why (parts of) the search stayed per-chunk: "
              "'unfused-score-path' (scan requested without the fused "
              "program), 'segment-capped:<group>' (the HBM budget "
              "split the group into multiple segments), "
              "'oom-per-chunk:<group>' (an OOM on a scanned segment "
              "fell back to the per-chunk recovery path for that "
              "segment)."),
    MetricDef("rung_topk_device", "counter",
              "Halving rungs whose top-k elimination ran ON DEVICE "
              "inside the scanned launch (no score round-trip between "
              "rungs)."),
    MetricDef("rung_topk_host", "counter",
              "Halving rungs that fell back to sklearn's host _top_k "
              "(partial scan, multiple segments, or a recovered "
              "segment) while scan was enabled."),
    MetricDef("score_attribution", "label",
              "'folded' when scan melted the score launch into the "
              "segment wall (score-time columns are 0.0 and the whole "
              "wall lands in fit time); 'calibrated' on the per-chunk "
              "path (warm calibration launch splits fused walls)."),
)


#: sub-keys of ``search_report["prefix"]`` (written by
#: ``search.prefix.prefix_block``) — the shared-prefix scheduler's
#: per-search view: how many distinct Pipeline prefixes the candidate
#: grid collapsed to, how many device transforms actually launched vs
#: were re-used from the data plane or the checkpoint journal, and why
#: an eligible-looking search stayed atomic.  Emitted for EVERY search
#: (atomic searches report the zeroed ``enabled=False`` shape); a
#: halving search accumulates all rungs into this one block.
PREFIX_BLOCK_SCHEMA = (
    MetricDef("mode", "label",
              "The resolved sharing mode: 'shared' (default; distinct "
              "prefixes computed once and fanned over suffixes) or "
              "'atomic' (TpuConfig.prefix_reuse=False / "
              "SST_PREFIX_REUSE=0 — every candidate recomputes its "
              "full chain inline, the exact escape hatch)."),
    MetricDef("enabled", "label",
              "True when the prefix stage actually ran: mode='shared' "
              "AND the search passed the eligibility gate (compiled "
              "Pipeline family, dense unsharded device X, wide score "
              "path)."),
    MetricDef("n_candidates_total", "counter",
              "Pipeline candidates whose prefix the staged schedule "
              "covered (summed over halving rungs)."),
    MetricDef("n_prefixes_distinct", "counter",
              "Distinct prefix digests among those candidates — the "
              "number of transformed design matrices that exist, vs "
              "n_candidates_total the atomic path would compute."),
    MetricDef("n_prefix_launches", "counter",
              "Prefix transforms actually computed on device (one "
              "vectorized-over-folds launch each).  The headline "
              "reduction is n_candidates_total / n_prefix_launches."),
    MetricDef("n_prefix_reused", "counter",
              "Prefix stages satisfied by a live DataPlane derived "
              "buffer (zero device work; e.g. halving rungs that kept "
              "their fold masks, or a repeated search on resident "
              "data)."),
    MetricDef("n_prefix_resumed", "counter",
              "Prefix stages restored from the checkpoint journal's "
              "saved payload after a restart (one upload, no "
              "recompute)."),
    MetricDef("recompute_saved", "counter",
              "Per-candidate prefix computations the schedule avoided: "
              "n_candidates_total - n_prefix_launches."),
    MetricDef("bytes_cached", "counter",
              "Bytes of transformed (F, n, d') design matrices held "
              "as DataPlane derived buffers for this search, charged "
              "to the owning tenant."),
    MetricDef("prefix_wall_s", "gauge",
              "Wall seconds the stage-1 prefix loop spent (compute + "
              "journal writes), already excluded from per-candidate "
              "fit walls."),
    MetricDef("fallbacks", "series",
              "Why the search (or a rung) stayed atomic: "
              "'not-a-compiled-pipeline', 'no-prefix-steps', "
              "'task-batched-final', 'data-sharded', 'no-device-x', "
              "'sparse-device-data', 'nested-score', "
              "'dataplane-disabled', 'no-x-fingerprint', "
              "'undigestable-prefix'."),
)


#: sub-keys of ``search_report["memory"]`` (written by
#: ``parallel.memledger.report_block``) — the device-memory ledger's
#: per-search view: what the search modeled, what the budget allowed,
#: and what the allocator measured.
#: sub-keys of ``search_report["streaming"]`` (written by
#: ``search.stream.run_stream``) — the streamed tier's analytic shard
#: plan plus what actually crossed host->device.  The plan numbers are
#: journaled with the checkpoint (``StreamPlan``), so a resumed run
#: reports the geometry it replayed, not a recomputed one.
STREAMING_BLOCK_SCHEMA = (
    MetricDef("n_samples", "gauge",
              "Host dataset rows the streamed passes covered."),
    MetricDef("shard_rows", "gauge",
              "Planned rows per sample shard (every shard pads to "
              "this with zero-weight rows, so each pass compiles "
              "exactly one program shape per group)."),
    MetricDef("n_shards", "gauge",
              "ceil(n_samples / shard_rows) — device launches per "
              "pass."),
    MetricDef("row_bytes", "gauge",
              "Modeled host bytes one sample row contributes (data "
              "arrays + fold-mask columns)."),
    MetricDef("target_shard_bytes", "gauge",
              "The requested per-shard slab "
              "(TpuConfig.stream_shard_bytes / "
              "SST_STREAM_SHARD_BYTES)."),
    MetricDef("budget_bytes", "gauge",
              "The HBM planning budget the shard width was sized "
              "against (0 = unbudgeted: the target alone decides)."),
    MetricDef("reserved_bytes", "gauge",
              "Modeled resident program footprint (chunk operands + "
              "fold accumulators + finalized models) subtracted from "
              "the budget before sizing shards."),
    MetricDef("capped", "label",
              "True when the budget shrank the shard below the "
              "requested target — the analytic stand-in for an OOM "
              "bisection, decided before the first upload."),
    MetricDef("fit_shards_streamed", "counter",
              "Shards uploaded and folded during the fit pass (a "
              "resumed run streams only the journal's suffix)."),
    MetricDef("score_shards_streamed", "counter",
              "Shards uploaded and scored during the score pass."),
    MetricDef("fit_shards_resumed", "counter",
              "Fit-pass shards restored from the per-shard journal "
              "instead of streamed."),
    MetricDef("score_shards_resumed", "counter",
              "Score-pass shards restored from the per-shard journal "
              "instead of streamed."),
    MetricDef("h2d_bytes", "gauge",
              "Measured host->device bytes the streamed passes "
              "transferred (data-plane counter delta; fingerprint "
              "dedup makes a re-streamed shard free)."),
    MetricDef("n_live_chunks", "gauge",
              "Candidate chunks actually computed (checkpoint-"
              "resumed chunks skip both passes)."),
)


MEMORY_BLOCK_SCHEMA = (
    MetricDef("enabled", "label",
              "Always True when present: the block only renders when "
              "the ledger is on (TpuConfig.memory_ledger, default "
              "True); disabled, the report is byte-identical to the "
              "pre-ledger shape."),
    MetricDef("measured", "label",
              "Whether any local device exposes allocator "
              "memory_stats.  False (XLA:CPU) runs the ledger "
              "model-only: watermark and error stay 0."),
    MetricDef("budget_bytes", "gauge",
              "The resolved HBM planning budget "
              "(TpuConfig.hbm_budget_bytes / SST_HBM_BUDGET_BYTES; "
              "default a fraction of detected device memory, 0 = no "
              "width ceiling)."),
    MetricDef("device_limit_bytes", "gauge",
              "Smallest measured per-device allocator limit (0 when "
              "no backend reports one)."),
    MetricDef("safety_margin", "gauge",
              "The footprint model's learned over-provisioning factor "
              "— trained upward by observed OOM bisections, so the "
              "width ceiling tightens instead of repeating a bad "
              "plan."),
    MetricDef("peak_modeled_bytes", "gauge",
              "This search's largest modeled in-flight footprint: "
              "resident broadcast set + the widest chunk's modeled "
              "bytes."),
    MetricDef("resident_bytes", "gauge",
              "Modeled resident broadcast set (X/y + fold masks) this "
              "search holds on device — the data plane's share of the "
              "budget."),
    MetricDef("watermark_bytes", "gauge",
              "Measured per-device bytes-in-use high-water mark "
              "sampled at launch boundaries (0 unmeasured)."),
    MetricDef("model_error_frac", "gauge",
              "Relative error between the modeled peak and the "
              "measured watermark delta over this search (0.0 when "
              "unmeasured) — how much to trust the model."),
    MetricDef("n_samples", "counter",
              "Device memory_stats samples taken during this search "
              "(launch boundaries + telemetry sampler)."),
    MetricDef("groups", "series",
              "Per (compile group, width): modeled dyn/mask/output "
              "byte breakdown, per-candidate slope, chunk_bytes, the "
              "resident share and whether the HBM ceiling capped the "
              "planned width."),
)


#: sub-keys of ``search_report["attribution"]`` (written by
#: ``obs.attribution.attribution_block``) — the search doctor's
#: critical-path decomposition.  The lane gauges are mutually
#: exclusive seconds that sum to ``wall_s`` exactly (the analyzer
#: normalizes), so every second of a slow search is charged to one
#: pinned cause.
ATTRIBUTION_BLOCK_SCHEMA = (
    MetricDef("enabled", "label",
              "Always True when present: the block only renders when "
              "the doctor is on (TpuConfig.attribution, default "
              "True); disabled, the report is byte-identical to the "
              "pre-doctor shape."),
    MetricDef("wall_s", "gauge",
              "The measured search wall the lanes decompose (timed "
              "around the whole candidate loop, so it includes host "
              "orchestration the pipeline never sees)."),
    MetricDef("compile_s", "gauge",
              "Seconds charged to traced-program construction: "
              "summed 'compile' span walls when the search was "
              "traced, else n_compiles x the geometry cost model's "
              "compile_wall_s estimate (programs built, not chunks "
              "or launches — invariant to chunk_loop=\"scan\"'s "
              "coarser launch shape)."),
    MetricDef("stage_s", "gauge",
              "Seconds charged to host->device staging (h2d "
              "transfer) that was not hidden behind device compute."),
    MetricDef("compute_s", "gauge",
              "Seconds charged to useful device compute (padding, "
              "fault recovery and queue wait are carved out into "
              "their own lanes)."),
    MetricDef("gather_s", "gauge",
              "Seconds charged to blocking device->host result "
              "transfer."),
    MetricDef("queue_wait_s", "gauge",
              "Seconds charged to multi-tenant fair-share queue "
              "contention (serve/executor.py)."),
    MetricDef("fault_s", "gauge",
              "Seconds charged to fault recovery: retry backoff, "
              "OOM bisection relaunches and host fallbacks (summed "
              "from the recovery spans)."),
    MetricDef("padding_s", "gauge",
              "Seconds of device compute charged to padded lanes "
              "(chunk tails repeated to the group's uniform width) — "
              "compute that produced no new result."),
    MetricDef("narrowing_s", "gauge",
              "Modeled seconds of extra launch overhead caused by "
              "the HBM ceiling capping planned chunk widths "
              "(memory-block groups with capped=True)."),
    MetricDef("other_s", "gauge",
              "The wall remainder: host orchestration (chunk prep, "
              "result writes, sklearn bookkeeping) outside the "
              "pipeline's per-launch timeline."),
    MetricDef("compile_source", "label",
              "Where compile_s came from: 'traced' (compile spans in "
              "the tracer buffer) or 'modeled' (cost-model "
              "estimate)."),
    MetricDef("n_compiles", "gauge",
              "Distinct traced-program constructions the pipeline "
              "counted — the divisor behind the compile verdict."),
    MetricDef("dominant", "label",
              "The lane with the largest share of wall_s (its name "
              "minus the _s suffix) — what the verdict leads with."),
    MetricDef("verdict", "label",
              "The one-line human judgment: dominant cause, its "
              "share, and the remedy the lane implies (e.g. "
              "'compile-bound: 61% of wall in 9 traced builds; a "
              "prewarmed program store would recover ~5.2s').  When "
              "the search's chunks rode cross-search fused launches "
              "a bracketed note names the lane exchange and that "
              "per-member scatter overhead rides the gather lane."),
    MetricDef("rungs", "series",
              "Halving searches only: one record per rung — iter, "
              "wall_s and the same lane decomposition computed over "
              "the rung's slice of the launch timeline."),
    MetricDef("regression", "struct",
              "The sentinel's judgment against the run log's stored "
              "baseline: status (none/regressed/no-baseline/off), "
              "the baseline's ts/wall and per-lane deltas that "
              "breached the noise band (obs/runlog.py)."),
)


#: sub-keys of ``search_report["protection"]`` (written by
#: ``parallel.faults.protection_block``) — the self-protecting
#: service's per-search verdict.  Present only when protection is on
#: (``TpuConfig.search_deadline_s`` / ``partial_results`` /
#: ``admission_mode``); off, the report is byte-identical to the
#: pre-protection shape.
PROTECTION_BLOCK_SCHEMA = (
    MetricDef("enabled", "label",
              "Always True when present: the block only renders when "
              "the protection layer is on."),
    MetricDef("mode", "label",
              "TpuConfig.admission_mode the search ran under: "
              "'static' (slot-count admission only) or 'predictive' "
              "(ledger-modeled footprint + SLO forecast priced at "
              "submit)."),
    MetricDef("partial_results", "label",
              "TpuConfig.partial_results policy: 'raise' (deadline/"
              "persistent faults propagate) or 'best_effort' "
              "(declared-partial cv_results_)."),
    MetricDef("deadline_s", "gauge",
              "TpuConfig.search_deadline_s the search ran under (0 = "
              "no deadline)."),
    MetricDef("deadline_hit", "label",
              "Whether the deadline expired before every candidate "
              "ran."),
    MetricDef("elapsed_s", "gauge",
              "Seconds from the deadline clock's start (submit time "
              "for executor-submitted searches — queue wait counts — "
              "else fit()) to the block's rendering."),
    MetricDef("partial", "label",
              "Whether any candidate was shed or quarantined: True "
              "means cv_results_ carries error_score cells that were "
              "never run (sklearn-exact semantics) and is DECLARED "
              "partial."),
    MetricDef("n_candidates_shed", "counter",
              "Candidates written to error_score without running "
              "(deadline shedding + persistent-fault degradation)."),
    MetricDef("n_quarantined", "counter",
              "Poison candidates quarantined to error_score after K "
              "single-lane FATAL faults "
              "(TpuConfig.quarantine_fatal_k)."),
    MetricDef("shed", "series",
              "One record per shed event: chunk key, the candidate "
              "indices shed, and the reason ('deadline' or "
              "'fault')."),
    MetricDef("quarantined", "series",
              "One record per quarantined candidate: chunk key, "
              "candidate index, fault count and the final error "
              "(each also dumps a protection flight bundle)."),
    MetricDef("verdict", "label",
              "The one-line judgment: 'complete', or 'partial-' plus "
              "the causes ('deadline', 'quarantine', 'fault') that "
              "shed work."),
)


#: pinned keys of ``search_report["heartbeat"]`` — rendered by
#: ``obs.heartbeat.heartbeat_block`` only when the in-flight heartbeat
#: resolved on (``TpuConfig.heartbeat`` / ``SST_HEARTBEAT``); off, the
#: report stays byte-identical to the beacon-less shape.
HEARTBEAT_BLOCK_SCHEMA = (
    MetricDef("enabled", "label",
              "Always True when present: the block only renders when "
              "the heartbeat beacon is on."),
    MetricDef("beats_total", "counter",
              "Device beats received for this search's scanned "
              "segments (one jax.debug.callback firing per scan "
              "step)."),
    MetricDef("chunk_beats_total", "counter",
              "Cheap dispatch-time beats from the per-chunk launch "
              "path (parallel/pipeline.py note_chunk) — process-wide "
              "while the search ran."),
    MetricDef("n_segments", "counter",
              "Scan segments registered under this search's scope "
              "(live + completed)."),
    MetricDef("steps_total", "gauge",
              "Scan steps planned across the search's segments."),
    MetricDef("steps_done", "gauge",
              "Scan steps confirmed done — beats observed plus the "
              "completion clamp, so a finished search always reports "
              "steps_done == steps_total."),
    MetricDef("cadence_p50_s", "gauge",
              "Median inter-beat gap (seconds) across the search's "
              "segments — the observed per-step cost the ETA blend "
              "weighs against the geometry model's prior."),
    MetricDef("cadence_p95_s", "gauge",
              "95th-percentile inter-beat gap (seconds)."),
    MetricDef("staleness_max_s", "gauge",
              "Largest inter-beat gap observed (seconds) — what the "
              "heartbeat watchdog's timeout must exceed to avoid "
              "false HUNG verdicts."),
    MetricDef("overhead_est_s", "gauge",
              "Host seconds spent inside the beat callback for this "
              "search (locked hub update + tracer instant)."),
    MetricDef("overhead_frac", "gauge",
              "overhead_est_s over the segments' summed wall — the "
              "<2% contract tests/test_heartbeat.py enforces."),
)


#: pinned keys of the telemetry snapshot's ``recovery`` block — the
#: crash-safe service's counters (``serve/journal.py``: durable
#: submission WAL under ``TpuConfig.service_journal_dir`` /
#: ``SST_SERVICE_JOURNAL_DIR``, lease fencing, warm restart).  The
#: zeroed shape renders when no journal is configured.
RECOVERY_BLOCK_SCHEMA = (
    MetricDef("journal_entries_total", "counter",
              "Verified WAL records the restart scan read from the "
              "service journal."),
    MetricDef("nonterminal_found_total", "counter",
              "Journaled searches whose last transition was "
              "non-terminal at restart — what the warm restart owed "
              "the caller."),
    MetricDef("recovered_total", "counter",
              "Searches re-admitted through TpuSession.resubmit() "
              "(fingerprint-verified, checkpoint journal replayed)."),
    MetricDef("mismatch_total", "counter",
              "Resubmissions refused because the re-bound data's "
              "blake2b fingerprint did not match the journaled one "
              "(RecoveryDataMismatchError)."),
    MetricDef("lease_takeovers_total", "counter",
              "Stale leases fenced: the previous owner was dead (or "
              "silent past service_lease_timeout_s) and this process "
              "took the journal directory over."),
    MetricDef("lease_conflicts_total", "counter",
              "Lease acquisitions refused because a LIVE owner held a "
              "fresh stamp (ServiceLeaseError)."),
    MetricDef("unclean_shutdowns_total", "counter",
              "Takeovers that implied the previous owner died without "
              "release_lease — each dumps a crash-marker flight "
              "bundle."),
    MetricDef("time_to_recover_s", "gauge",
              "Seconds from this process's journal scan to its first "
              "successful resubmit — the operator-facing warm-restart "
              "latency."),
)


#: top-level keys of ``TpuSession.telemetry_snapshot()`` — the fleet
#: telemetry service's JSON view (``obs/telemetry.py``), also served
#: as ``/snapshot.json`` (and rendered to Prometheus text) by the
#: session's localhost endpoint (``obs/fleet.py``,
#: ``TpuConfig.telemetry_port`` / ``SST_TELEMETRY_PORT``).
TELEMETRY_SNAPSHOT_SCHEMA = (
    MetricDef("enabled", "label",
              "Whether the telemetry service is aggregating; the "
              "zeroed shape renders when it is off."),
    MetricDef("ts_unix_s", "gauge",
              "Wall-clock timestamp the snapshot was rendered at."),
    MetricDef("window_s", "gauge",
              "Sliding-window span (seconds) the rates and "
              "percentiles below cover."),
    MetricDef("interval_s", "gauge",
              "Sampler-thread tick period (seconds)."),
    MetricDef("n_samples", "counter",
              "Sampler ticks since the service enabled."),
    MetricDef("tenants", "struct",
              "Per-tenant SLO series: dispatches/tasks/queue-wait "
              "cumulative totals plus sliding-window queue-wait "
              "p50/p95, throughput (task units per second) and "
              "share_frac — these agree with the searches' own "
              "search_report['scheduler'] blocks."),
    MetricDef("device", "struct",
              "Device occupancy over the window: busy seconds (from "
              "per-launch compute estimates) and occupancy_frac."),
    MetricDef("scheduler", "struct",
              "Dispatch-loop view: cumulative dispatches, loop busy "
              "seconds and idle fraction over the window, plus the "
              "sampler's polled queue depth and active/pending search "
              "counts."),
    MetricDef("dataplane", "struct",
              "Host->device transfer totals and window rate, plus the "
              "sampler's polled plane state (hits/misses/residency; "
              "per-tenant residency lands under tenants)."),
    MetricDef("programstore", "struct",
              "AOT-store hit/miss/publish/quarantine event totals "
              "plus the sampler's polled cumulative counters."),
    MetricDef("memory", "struct",
              "Device-memory view: per-device bytes-in-use / limit / "
              "pressure (sampled from jax memory_stats where the "
              "backend provides it), the ledger's modeled peak, "
              "measured watermark, safety margin and a bounded recent "
              "max-pressure series — these agree with the searches' "
              "search_report['memory'] blocks."),
    MetricDef("faults", "struct",
              "Observed fault totals by taxonomy class and recovery "
              "action (fed by the launch supervisor's event hook)."),
    MetricDef("regression", "struct",
              "The cross-run regression sentinel's latest judgment "
              "(obs/runlog.py): checks/flagged totals, the last "
              "run's status and the lanes that breached the noise "
              "band — also rendered as the sst_regression_* "
              "Prometheus family."),
    MetricDef("protection", "struct",
              "The self-protecting service's process totals: "
              "admission decisions (admitted/queued/rejected, by "
              "reason), candidates shed, poison candidates "
              "quarantined and deadline expiries — also rendered as "
              "the sst_protection_* Prometheus family."),
    MetricDef("fusion", "struct",
              "Cross-search launch-fusion totals: fused launches, "
              "member chunks, saved launches, real vs padded lanes, "
              "and the per-tenant lane exchange (lanes borrowed on "
              "peers' launches / donated to peers) — also rendered "
              "as the sst_fusion_* Prometheus family."),
    MetricDef("recovery", "struct",
              "Crash-safe service totals (serve/journal.py): WAL "
              "entries scanned, non-terminal searches found and "
              "recovered at warm restart, fingerprint mismatches, "
              "lease fencing verdicts and time-to-recover — keys "
              "pinned in RECOVERY_BLOCK_SCHEMA, also rendered as the "
              "sst_recovery_* Prometheus family."),
    MetricDef("flight", "struct",
              "Flight-recorder state: records seen, ring occupancy, "
              "black-box bundles dumped."),
    MetricDef("heartbeat", "struct",
              "In-flight heartbeat totals (beats, chunk beats, "
              "segments, cadence/staleness) plus every live search "
              "handle's steps_done/steps_total progress and blended "
              "ETA — also rendered as the sst_heartbeat_* Prometheus "
              "family and tools/fleet_top.py's progress column."),
)


class Counter:
    """Monotonically increasing integer metric."""

    __slots__ = ("_data", "name")

    def __init__(self, data, name):
        self._data = data
        self.name = name

    def inc(self, n: int = 1) -> None:
        self._data[self.name] += n

    @property
    def value(self) -> int:
        return self._data[self.name]


class Gauge:
    """Point-in-time numeric metric (settable and accumulable)."""

    __slots__ = ("_data", "name")

    def __init__(self, data, name):
        self._data = data
        self.name = name

    def set(self, v) -> None:
        self._data[self.name] = v

    def add(self, v) -> None:
        self._data[self.name] += v

    @property
    def value(self):
        return self._data[self.name]


class Label(Gauge):
    """String-valued metric (e.g. the backend name)."""

    __slots__ = ()


class Histogram:
    """Streaming summary of observations, rendered as a plain dict
    {count, sum, mean, min, max} so the report stays JSON-able."""

    __slots__ = ("_data", "name")

    def __init__(self, data, name):
        self._data = data
        self.name = name

    def observe(self, v: float) -> None:
        h = self._data[self.name]
        v = float(v)
        h["count"] += 1
        h["sum"] += v
        h["min"] = v if h["min"] is None else min(h["min"], v)
        h["max"] = v if h["max"] is None else max(h["max"], v)
        h["mean"] = h["sum"] / h["count"]

    @property
    def value(self) -> Dict[str, Any]:
        return self._data[self.name]


_KIND_DEFAULTS = {
    "counter": lambda: 0,
    "gauge": lambda: 0.0,
    "label": lambda: "",
    "series": list,
    "struct": dict,
    "histogram": lambda: {"count": 0, "sum": 0.0, "mean": 0.0,
                          "min": None, "max": None},
}

_KIND_HANDLES = {
    "counter": Counter,
    "gauge": Gauge,
    "label": Label,
    "histogram": Histogram,
}


class MetricsRegistry:
    """Named metrics writing into one ordered dict (``.data``).

    ``.data`` is the live rendered view: handing it to a consumer (the
    ``search_report`` property) costs nothing and stays current as the
    engine updates metrics mid-run.  In strict mode every metric must
    be declared in the schema with a matching kind — the pin that stops
    report drift.
    """

    def __init__(self, schema: Optional[Iterable[MetricDef]] = None,
                 strict: Optional[bool] = None):
        self._defs = {d.name: d for d in (schema or ())}
        self._strict = bool(self._defs) if strict is None else strict
        self.data: "OrderedDict[str, Any]" = OrderedDict()
        self._handles: Dict[str, Any] = {}

    # -- declaration / lookup -------------------------------------------
    def _resolve(self, name: str, kind: str):
        d = self._defs.get(name)
        if d is None:
            if self._strict:
                raise KeyError(
                    f"metric {name!r} is not declared in this registry's "
                    "schema; add a MetricDef before writing it")
        elif d.kind != kind:
            raise TypeError(
                f"metric {name!r} is declared as a {d.kind}, not a {kind}")
        if name not in self.data:
            self.data[name] = _KIND_DEFAULTS[kind]()

    def _handle(self, name: str, kind: str):
        h = self._handles.get(name)
        if h is None:
            self._resolve(name, kind)
            h = self._handles[name] = _KIND_HANDLES[kind](self.data, name)
        return h

    def counter(self, name: str) -> Counter:
        return self._handle(name, "counter")

    def gauge(self, name: str) -> Gauge:
        return self._handle(name, "gauge")

    def label(self, name: str) -> Label:
        return self._handle(name, "label")

    def histogram(self, name: str) -> Histogram:
        return self._handle(name, "histogram")

    def series(self, name: str) -> list:
        """The named append-only list itself (per-launch records)."""
        self._resolve(name, "series")
        return self.data[name]

    def struct(self, name: str) -> dict:
        """The named nested-dict value itself (mesh, per_group, ...)."""
        self._resolve(name, "struct")
        return self.data[name]

    def put(self, name: str, value) -> None:
        """Assign a struct wholesale (e.g. the pipeline block computed
        by ChunkPipeline.report())."""
        self._resolve(name, "struct")
        self.data[name] = value

    # -- rendering -------------------------------------------------------
    def render(self) -> Dict[str, Any]:
        """Plain-dict snapshot (shallow; series/struct values are the
        live containers — copy before mutating)."""
        return dict(self.data)

    def describe(self) -> Iterable[MetricDef]:
        return tuple(self._defs.values())


def search_registry(backend: str) -> MetricsRegistry:
    """A strict registry pre-declared with the search_report schema,
    with the backend label already set (always the first key)."""
    reg = MetricsRegistry(SEARCH_REPORT_SCHEMA)
    reg.label("backend").set(backend)
    return reg


def schema_markdown() -> str:
    """The search_report schema as a markdown section — the single
    source `docs/API.md` renders (dev/build_api_docs.py)."""
    out = [
        "## `search_report` schema\n",
        "\nRendered from `spark_sklearn_tpu.obs.metrics."
        "SEARCH_REPORT_SCHEMA` — the same definitions the engine "
        "writes through, so this table cannot drift from the code.\n",
        "\n| key | kind | backend | description |\n",
        "|---|---|---|---|\n",
    ]
    for d in SEARCH_REPORT_SCHEMA:
        out.append(
            f"| `{d.name}` | {d.kind} | {d.backends} | "
            f"{d.description} |\n")
    out.append("\n### `search_report[\"pipeline\"]` block\n")
    out.append("\n| key | kind | description |\n|---|---|---|\n")
    for d in PIPELINE_BLOCK_SCHEMA:
        out.append(f"| `{d.name}` | {d.kind} | {d.description} |\n")
    out.append("\n### `search_report[\"faults\"]` block\n")
    out.append("\n| key | kind | description |\n|---|---|---|\n")
    for d in FAULTS_BLOCK_SCHEMA:
        out.append(f"| `{d.name}` | {d.kind} | {d.description} |\n")
    out.append("\n### `search_report[\"dataplane\"]` block\n")
    out.append("\n| key | kind | description |\n|---|---|---|\n")
    for d in DATAPLANE_BLOCK_SCHEMA:
        out.append(f"| `{d.name}` | {d.kind} | {d.description} |\n")
    out.append("\n### `search_report[\"geometry\"]` block\n")
    out.append("\n| key | kind | description |\n|---|---|---|\n")
    for d in GEOMETRY_BLOCK_SCHEMA:
        out.append(f"| `{d.name}` | {d.kind} | {d.description} |\n")
    out.append("\n### `search_report[\"programstore\"]` block\n")
    out.append("\n| key | kind | description |\n|---|---|---|\n")
    for d in PROGRAMSTORE_BLOCK_SCHEMA:
        out.append(f"| `{d.name}` | {d.kind} | {d.description} |\n")
    out.append("\n### `search_report[\"scheduler\"]` block\n")
    out.append("\n| key | kind | description |\n|---|---|---|\n")
    for d in SCHEDULER_BLOCK_SCHEMA:
        out.append(f"| `{d.name}` | {d.kind} | {d.description} |\n")
    out.append("\n### `search_report[\"halving\"]` block\n")
    out.append(
        "\nPresent only on `HalvingGridSearchCV` / "
        "`HalvingRandomSearchCV` fits (`search/halving.py`).\n")
    out.append("\n| key | kind | description |\n|---|---|---|\n")
    for d in HALVING_BLOCK_SCHEMA:
        out.append(f"| `{d.name}` | {d.kind} | {d.description} |\n")
    out.append("\n### `search_report[\"chunkloop\"]` block\n")
    out.append(
        "\nThe device-resident chunk loop's per-search view "
        "(`TpuConfig.chunk_loop=\"scan\"` / `SST_CHUNK_LOOP`; "
        "`search/grid.py`).  Always present on compiled-tier "
        "searches — per-chunk runs report the zeroed "
        "`enabled=False` shape.\n")
    out.append("\n| key | kind | description |\n|---|---|---|\n")
    for d in CHUNKLOOP_BLOCK_SCHEMA:
        out.append(f"| `{d.name}` | {d.kind} | {d.description} |\n")
    out.append("\n### `search_report[\"prefix\"]` block\n")
    out.append(
        "\nThe shared-prefix scheduler's per-search view "
        "(`TpuConfig.prefix_reuse` / `SST_PREFIX_REUSE`, default on; "
        "`search/prefix.py` + the `search/grid.py` stage-1 "
        "scheduler).  Always present on compiled-tier searches — "
        "atomic runs report the zeroed `enabled=False` shape.\n")
    out.append("\n| key | kind | description |\n|---|---|---|\n")
    for d in PREFIX_BLOCK_SCHEMA:
        out.append(f"| `{d.name}` | {d.kind} | {d.description} |\n")
    out.append("\n### `search_report[\"memory\"]` block\n")
    out.append(
        "\nPresent when the device-memory ledger is on "
        "(`TpuConfig.memory_ledger`, default True; "
        "`parallel/memledger.py`).\n")
    out.append("\n| key | kind | description |\n|---|---|---|\n")
    for d in MEMORY_BLOCK_SCHEMA:
        out.append(f"| `{d.name}` | {d.kind} | {d.description} |\n")
    out.append("\n### `search_report[\"streaming\"]` block\n")
    out.append(
        "\nPresent only when the search ran the streaming-fold data "
        "plane (`TpuConfig.data_mode=\"stream\"` / `SST_DATA_MODE`; "
        "`search/stream.py`).\n")
    out.append("\n| key | kind | description |\n|---|---|---|\n")
    for d in STREAMING_BLOCK_SCHEMA:
        out.append(f"| `{d.name}` | {d.kind} | {d.description} |\n")
    out.append("\n### `search_report[\"attribution\"]` block\n")
    out.append(
        "\nPresent when the search doctor is on "
        "(`TpuConfig.attribution`, default True; "
        "`obs/attribution.py`).  The lane gauges sum to `wall_s` "
        "exactly.\n")
    out.append("\n| key | kind | description |\n|---|---|---|\n")
    for d in ATTRIBUTION_BLOCK_SCHEMA:
        out.append(f"| `{d.name}` | {d.kind} | {d.description} |\n")
    out.append("\n### `search_report[\"protection\"]` block\n")
    out.append(
        "\nPresent when the self-protecting service is on "
        "(`TpuConfig.search_deadline_s` / `partial_results` / "
        "`admission_mode`; `parallel/faults.py`).\n")
    out.append("\n| key | kind | description |\n|---|---|---|\n")
    for d in PROTECTION_BLOCK_SCHEMA:
        out.append(f"| `{d.name}` | {d.kind} | {d.description} |\n")
    out.append("\n### `search_report[\"heartbeat\"]` block\n")
    out.append(
        "\nPresent when the in-flight heartbeat beacon is on "
        "(`TpuConfig.heartbeat` / `SST_HEARTBEAT`; "
        "`obs/heartbeat.py`).\n")
    out.append("\n| key | kind | description |\n|---|---|---|\n")
    for d in HEARTBEAT_BLOCK_SCHEMA:
        out.append(f"| `{d.name}` | {d.kind} | {d.description} |\n")
    out.append("\n### telemetry `recovery` block\n")
    out.append(
        "\nThe crash-safe service's counters "
        "(`spark_sklearn_tpu/serve/journal.py`: durable submission "
        "WAL under `TpuConfig.service_journal_dir` / "
        "`SST_SERVICE_JOURNAL_DIR`, lease fencing, warm restart) — "
        "the `recovery` key of the telemetry snapshot, zeroed when no "
        "journal is configured.\n")
    out.append("\n| key | kind | description |\n|---|---|---|\n")
    for d in RECOVERY_BLOCK_SCHEMA:
        out.append(f"| `{d.name}` | {d.kind} | {d.description} |\n")
    out.append("\n### `TpuSession.telemetry_snapshot()` / fleet "
               "endpoint schema\n")
    out.append(
        "\nTop-level keys of the fleet-telemetry snapshot "
        "(`spark_sklearn_tpu/obs/telemetry.py`), served as "
        "`/snapshot.json` and rendered to Prometheus text by the "
        "session's localhost endpoint.\n")
    out.append("\n| key | kind | description |\n|---|---|---|\n")
    for d in TELEMETRY_SNAPSHOT_SCHEMA:
        out.append(f"| `{d.name}` | {d.kind} | {d.description} |\n")
    return "".join(out)
