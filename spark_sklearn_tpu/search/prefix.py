"""Shared-prefix search graphs: plan + report helpers.

spark-sklearn's home-turf workload is ``Pipeline(vectorize → reduce →
clf)`` grid search, and the compiled :class:`~spark_sklearn_tpu.models.
pipeline.PipelineFamily` fuses the transformer chain into every
candidate's fit — which means a 96-candidate grid whose candidates
share 4 distinct preprocessing configurations recomputes each
expensive prefix ~24x per fold.  Ousterhout-style overhead analysis of
distributed ML (arXiv:1612.01437) names exactly this redundant-
computation/caching axis as the dominant overhead; DrJAX
(arXiv:2403.07128) is the reference for keeping the reuse on device.

The shared-prefix scheduler (wired through ``search/grid.py``) treats
a Pipeline candidate as a DAG, not an atom:

1. **group** compile groups by a content digest of their prefix step
   params (:meth:`PipelineFamily.prefix_digest` — final-step params
   excluded, so groups differing only in classifier statics share a
   digest);
2. **compute** each DISTINCT prefix once, vectorized over folds on
   device (:meth:`PipelineFamily.prefix_transform` — the exact
   mask-weighted statistics the fused fit computes inline, so the
   split is bit-exact by construction);
3. **cache** the stacked ``(F, n, d')`` transformed design matrix in
   the :class:`~spark_sklearn_tpu.parallel.dataplane.DataPlane` as a
   derived buffer keyed on ``(digest, fold-mask fp, X fp, sharding)``
   with normal tenant/byte accounting, and journal completion in the
   search checkpoint so kill-resume never recomputes a durable prefix;
4. **fan** the suffix candidates over the cached matrices through the
   existing chunk/scan machinery (the suffix family's programs key on
   the transformed shapes plus the digest, so they never alias atomic
   programs).

Everything here is host-side bookkeeping: knob resolution
(``TpuConfig.prefix_reuse`` / ``SST_PREFIX_REUSE``), the eligibility
gate with its recorded fallback reasons, digest grouping, and the
pinned ``search_report["prefix"]`` block (schema in
``obs.metrics.PREFIX_BLOCK_SCHEMA``).  The device work lives in
``models/pipeline.py``; the stage scheduling in ``search/grid.py``.
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional

__all__ = [
    "group_prefix_digests",
    "prefix_block",
    "prefix_fallback_reason",
    "resolve_prefix_reuse",
]


def resolve_prefix_reuse(config) -> bool:
    """The search's shared-prefix knob: ``TpuConfig.prefix_reuse``
    wins, then the ``SST_PREFIX_REUSE`` env mirror (1/0), then True
    (sharing on — the bit-exact fast path)."""
    val = getattr(config, "prefix_reuse", None)
    if val is not None:
        return bool(val)
    env = os.environ.get("SST_PREFIX_REUSE", "").strip().lower()
    if env in ("", None):
        return True
    if env in ("1", "true", "on", "yes"):
        return True
    if env in ("0", "false", "off", "no"):
        return False
    raise ValueError(
        f"SST_PREFIX_REUSE={env!r} is not a boolean; expected 1/0")


def prefix_fallback_reason(family, *, all_cores: bool,
                           n_data_shards: int,
                           x_dev: Any) -> Optional[str]:
    """Why this search CANNOT stage prefixes (None = eligible).

    The reasons land verbatim in ``search_report["prefix"]
    ["fallbacks"]`` so a user who expected the speedup can see which
    contract their search broke.  Every ineligible search runs the
    atomic path unchanged — fallback is bit-exact by definition.
    (Streamed searches never reach this gate: they branch off before
    the chunked executor; unregistered/host-only pipeline steps never
    build a compiled family at all, so both fall back upstream.)
    """
    if not hasattr(family, "prefix_digest"):
        return "not-a-compiled-pipeline"
    if not getattr(family, "steps", None):
        return "no-prefix-steps"
    if hasattr(family, "fit_task_batched"):
        # task-batched finals (SVC) already fold the per-fold transform
        # into ONE fit per chunk — there is no per-candidate prefix
        # recompute to save, and their decision-cached scoring never
        # consumes the transformed X
        return "task-batched-final"
    if int(n_data_shards) != 1:
        return "data-sharded"
    if x_dev is None:
        return "no-device-x"
    if type(x_dev).__name__ == "BCOO":
        # the sparse device tier keeps X as BCOO; the stacked per-fold
        # transform would densify it wholesale
        return "sparse-device-data"
    if not all_cores:
        # the nested per-(candidate, fold) score path rebuilds views on
        # the UNtransformed X; only the wide task-batched score path
        # indexes the cached per-fold matrices
        return "nested-score"
    return None


def group_prefix_digests(groups, base_params: Dict[str, Any],
                         family) -> List[Optional[str]]:
    """Per-compile-group prefix digest (None when the group's chain
    cannot be digested).  Groups map to digests many-to-one: groups
    that differ only in final-step statics share the digest — and the
    cached matrix."""
    out: List[Optional[str]] = []
    for group in groups:
        static = {**base_params, **group.static_params}
        try:
            out.append(family.prefix_digest(static))
        # a None digest is an EXPECTED outcome, not an error: the
        # group runs atomic and the scheduler records
        # 'undigestable-prefix' in the report's fallbacks
        # sstlint: disable=swallowed-exception
        except Exception:
            out.append(None)
    return out


def prefix_block(state, *, mode="shared", enabled=False):
    """Normalize the ``search_report["prefix"]`` block in place
    (schema pinned in ``obs.metrics.PREFIX_BLOCK_SCHEMA``).

    The state dict is the registry's own ``metrics.struct("prefix")``
    object, so the stage-1 scheduler (and halving's rung re-use
    accounting) mutate the same dict this function returns — a halving
    search's rungs accumulate into one whole-search block.  Emitted
    for EVERY search: an atomic search reports the zeroed
    ``enabled=False`` shape, so the report schema never changes.
    """
    defaults = {
        "mode": mode,
        "enabled": bool(enabled),
        "n_candidates_total": 0,
        "n_prefixes_distinct": 0,
        "n_prefix_launches": 0,
        "n_prefix_reused": 0,
        "n_prefix_resumed": 0,
        "recompute_saved": 0,
        "bytes_cached": 0,
        "prefix_wall_s": 0.0,
        "fallbacks": [],
    }
    for k, v in defaults.items():
        state.setdefault(k, v)
    state["mode"] = mode
    state["enabled"] = bool(enabled)
    return state
