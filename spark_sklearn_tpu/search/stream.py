"""Streaming-fold data plane — ``data_mode="stream"`` (SURVEY §7.4).

The reference's answer to "X does not fit" was Spark's: leave the data
partitioned on the cluster and ship the *model* search to it.  This
engine's device tier had only the opposite move — ship ALL of X to the
accelerator (replicated, or sample-sharded over the mesh) — so a
dataset bigger than HBM simply could not ride the compiled path on one
chip.  The streaming-fold tier closes that gap analytically instead of
by trial-and-error:

  - **plan** — :func:`~spark_sklearn_tpu.parallel.taskgrid.
    plan_stream_shards` sizes uniform sample shards from the resolved
    HBM budget minus the modeled resident program footprint (the PR 10
    ledger's pricing: sparse rows enter nnz-proportionally), so the
    shard width is a *planning decision* journaled next to the launch
    geometry — an OOM bisection on the streamed path is a bug, not a
    discovery mechanism;
  - **pipeline** — each shard's host slice + upload runs on the
    :class:`~spark_sklearn_tpu.parallel.pipeline.ChunkPipeline` stage
    thread, overlapping the PREVIOUS shard's device compute; the data
    plane's content fingerprints dedup re-uploads, so a shard crossing
    host->device twice in one pass is a bug;
  - **fold** — families expose per-shard, per-fold fit statistics that
    are candidate-independent and additive (``stream_fit_partial``);
    the engine folds them on device in shard order, journals the
    accumulator after every shard (a kill mid-stream resumes exactly
    like a chunk kill), then vmaps ``stream_fit_finalize`` over each
    chunk's candidates — for families whose statistics are exact sums
    (the discrete NB family), the streamed fit IS the in-core fit,
    bit for bit;
  - **score** — a second pass streams the same shards through the
    ordinary ``predict``, accumulating the default scorer's sufficient
    statistics (accuracy's hit/weight sums; r2's weighted moments), so
    ``cv_results_`` matches the in-core engine without the test folds
    ever being resident at once.

Knobs: ``TpuConfig.data_mode`` / ``SST_DATA_MODE`` pick the tier
("device" default, "stream", "sparse"); ``TpuConfig.
stream_shard_bytes`` / ``SST_STREAM_SHARD_BYTES`` cap the per-shard
slab the planner targets before the budget shrinks it.
"""

from __future__ import annotations

import base64
import os
import time
from typing import Any, Dict, List, Optional

import numpy as np

from spark_sklearn_tpu.obs.log import get_logger
from spark_sklearn_tpu.obs.trace import get_tracer

logger = get_logger("search.stream")

__all__ = [
    "DATA_MODES",
    "check_stream_supported",
    "resolve_data_mode",
    "resolve_shard_bytes",
    "run_stream",
]

DATA_MODES = ("device", "stream", "sparse")

#: default shard slab the planner targets when neither the config knob
#: nor the env mirror speaks — small enough that even a modest HBM
#: budget double-buffers it, big enough to amortize dispatch overhead
DEFAULT_SHARD_BYTES = 64 << 20


def resolve_data_mode(config) -> str:
    """The search's data tier: ``TpuConfig.data_mode`` wins, then the
    ``SST_DATA_MODE`` env mirror, then ``"device"`` (the byte-identical
    legacy path)."""
    mode = getattr(config, "data_mode", None)
    if mode is None:
        mode = os.environ.get("SST_DATA_MODE", "").strip().lower() or None
    if mode is None:
        return "device"
    mode = str(mode).strip().lower()
    if mode not in DATA_MODES:
        raise ValueError(
            f"data_mode={mode!r} is not a data tier; expected one of "
            f"{DATA_MODES}")
    return mode


def resolve_shard_bytes(config) -> int:
    """Target host bytes per streamed sample shard:
    ``TpuConfig.stream_shard_bytes`` wins, then
    ``SST_STREAM_SHARD_BYTES``, then 64 MiB."""
    v = getattr(config, "stream_shard_bytes", None)
    if v is None:
        env = os.environ.get("SST_STREAM_SHARD_BYTES", "").strip()
        v = int(env) if env else None
    if v is None:
        return DEFAULT_SHARD_BYTES
    v = int(v)
    if v <= 0:
        raise ValueError(
            f"stream_shard_bytes={v} must be a positive byte count")
    return v


def check_stream_supported(family, scoring, config) -> None:
    """Fail fast (clear ValueError, never a silent densified fallback)
    when this search cannot run the streaming-fold tier."""
    if not getattr(family, "supports_stream", False):
        raise ValueError(
            f"data_mode='stream' requires a family implementing the "
            f"streaming-fold protocol (stream_fit_partial/"
            f"stream_fit_finalize); {family.name} does not.  Use "
            "data_mode='device' or backend='host'.")
    if scoring is not None:
        raise ValueError(
            "data_mode='stream' scores through the family's default "
            f"scorer only (accuracy / r2); scoring={scoring!r} is not "
            "streamable.  Use data_mode='device' or backend='host'.")
    if getattr(family, "default_scorer", None) is not None:
        raise ValueError(
            f"data_mode='stream' cannot stream {family.name}'s custom "
            "default scorer; use data_mode='device'.")
    if int(getattr(config, "n_data_shards", 1) or 1) > 1:
        raise ValueError(
            "data_mode='stream' and n_data_shards>1 are alternative "
            "answers to the same problem (X larger than one chip); "
            "pick one.")


# ---------------------------------------------------------------------------
# journal (de)serialization: accumulator pytrees as base64 leaves
# ---------------------------------------------------------------------------

def _pack_tree(tree) -> List[Dict[str, Any]]:
    """Device/host pytree -> JSON-safe leaf records, in tree order.
    f32/f64 bytes round-trip exactly, so a resumed accumulator is
    bit-identical to the one the killed run folded."""
    import jax
    out = []
    for leaf in jax.tree_util.tree_leaves(tree):
        arr = np.ascontiguousarray(np.asarray(leaf))
        out.append({"shape": list(arr.shape), "dtype": str(arr.dtype),
                    "b64": base64.b64encode(arr.tobytes()).decode()})
    return out


def _unpack_tree(packed, like):
    """Inverse of :func:`_pack_tree`; ``like`` (same structure) donates
    the treedef.  Returns None on any structural mismatch — the caller
    then treats the journal entry as absent."""
    import jax
    leaves, treedef = jax.tree_util.tree_flatten(like)
    if packed is None or len(packed) != len(leaves):
        return None
    new = []
    for rec, leaf in zip(packed, leaves):
        try:
            arr = np.frombuffer(
                base64.b64decode(rec["b64"]),
                dtype=np.dtype(str(rec["dtype"])))
            arr = arr.reshape([int(s) for s in rec["shape"]])
        except (KeyError, TypeError, ValueError):
            return None
        want = np.asarray(leaf)
        if arr.shape != want.shape or arr.dtype != want.dtype:
            return None
        new.append(arr)
    return jax.tree_util.tree_unflatten(treedef, new)


def _zeros_like_shapes(shapes):
    import jax
    import jax.numpy as jnp
    return jax.tree_util.tree_map(
        lambda s: jnp.zeros(s.shape, s.dtype), shapes)


def _streaming_counters(plan, n_live: int) -> Dict[str, Any]:
    """The initial ``search_report["streaming"]`` block (schema pinned
    in ``obs.metrics.STREAMING_BLOCK_SCHEMA``): the journaled plan's
    facts plus zeroed pass counters ``run_stream`` advances in place."""
    return {
        **plan.report_block(),
        "fit_shards_streamed": 0,
        "score_shards_streamed": 0,
        "fit_shards_resumed": 0,
        "score_shards_resumed": 0,
        "h2d_bytes": 0,
        "n_live_chunks": int(n_live),
    }


def _pad_rows(arr: np.ndarray, lo: int, hi: int, rows: int) -> np.ndarray:
    """Host row slice [lo, hi) padded to ``rows`` with ZERO rows (zero
    weight rows contribute exactly 0.0 to every partial sum, so the
    uniform shard shape costs nothing in exactness)."""
    sl = arr[lo:hi]
    if hi - lo == rows:
        return np.ascontiguousarray(sl)
    out = np.zeros((rows,) + arr.shape[1:], arr.dtype)
    out[: hi - lo] = sl
    return out


def _pad_mask(m: np.ndarray, lo: int, hi: int, rows: int) -> np.ndarray:
    """(n_folds, n) mask column slice padded with zero-weight columns."""
    sl = m[:, lo:hi]
    if hi - lo == rows:
        return np.ascontiguousarray(sl)
    out = np.zeros((m.shape[0], rows), m.dtype)
    out[:, : hi - lo] = sl
    return out


# ---------------------------------------------------------------------------
# the streamed search runner
# ---------------------------------------------------------------------------

def run_stream(search, *, groups, base_params, family, meta, scorer_names,
               data, fit_masks, test_sc_masks, train_sc_masks, repl,
               config, n_task_shards, max_cand_per_batch, n_folds, dtype,
               return_train, test_scores, train_scores, fit_times,
               score_times, ckpt, fit_failed, candidates):
    """Run every compile group's chunks through the streaming-fold data
    plane instead of :meth:`_run_groups`'s resident-X launches.

    Two shard passes over the host dataset: a FIT pass folding each
    family's additive per-fold statistics on device (journaled per
    shard), a finalize step vmapping each chunk's candidates over the
    folded statistics, then a SCORE pass streaming the same shards
    through ``predict`` into the default scorer's sufficient
    statistics.  Shard upload (stage thread) overlaps the previous
    shard's compute at ``pipeline_depth >= 1``; depth 0 is the
    synchronous bit-identical escape hatch."""
    import jax
    import jax.numpy as jnp

    from spark_sklearn_tpu.obs import memory as _obs_memory
    from spark_sklearn_tpu.parallel import dataplane as _dataplane
    from spark_sklearn_tpu.parallel import memledger as _memledger
    from spark_sklearn_tpu.parallel.pipeline import ChunkPipeline, LaunchItem
    from spark_sklearn_tpu.parallel.taskgrid import (
        GeometryMismatchError, pad_chunk, plan_stream_shards)
    from spark_sklearn_tpu.search.scorers import EPS

    # n_task_shards is part of the _run_groups lane geometry; the
    # streamed programs take fully-replicated operands, so on a wider
    # task mesh they simply run replicated (correct, if redundant) —
    # no reshard, no error
    del n_task_shards

    tracer = get_tracer()
    metrics = search._search_metrics
    plane = _dataplane.plane_for(config)
    ledger = _memledger.ledger_for(config)
    from spark_sklearn_tpu import serve as _serve
    binding = _serve.current_binding()
    tenant = binding.tenant if binding is not None else None
    dp_before = _dataplane.snapshot_counters(plane)
    is_cls = bool(family.is_classifier)
    n_samples = int(next(iter(data.values())).shape[0])

    def _put(arr, label):
        if plane is not None:
            return plane.put(arr, repl, label=label, tenant=tenant)
        return _dataplane.upload(arr, repl, label=label)

    # -- chunk geometry (fixed-width: the stream tier's launch count is
    # -- dominated by n_shards, so the waste-aware planner buys nothing)
    plans = []
    for gi, group in enumerate(groups):
        nc = int(group.n_candidates)
        width = max(1, min(nc, int(max_cand_per_batch)))
        static = {**base_params, **group.static_params}
        chunks = []
        for lo in range(0, nc, width):
            hi = min(lo + width, nc)
            chunks.append((lo, hi, f"st:{gi}:{lo}:{hi}"))
        plans.append({"gi": gi, "group": group, "static": static,
                      "nc": nc, "width": width, "chunks": chunks})

    # -- resume completed chunks (same record shape as write_cells')
    live: List[tuple] = []          # (plan, lo, hi, chunk_id)
    for plan in plans:
        group = plan["group"]
        for lo, hi, chunk_id in plan["chunks"]:
            rec = ckpt.get(chunk_id) if ckpt is not None else None
            if rec is not None and return_train \
                    and rec.get("train") is None:
                rec = None
            idx = group.candidate_indices[lo:hi]
            if rec is not None:
                for s in scorer_names:
                    test_scores[s][idx, :] = np.asarray(rec["test"][s])
                    if return_train:
                        train_scores[s][idx, :] = np.asarray(
                            rec["train"][s])
                fit_times[idx, :] = rec["fit_t"]
                score_times[idx, :] = rec["score_t"]
                if rec.get("failed") is not None:
                    fit_failed[idx, :] |= np.asarray(rec["failed"], bool)
                metrics.counter("n_chunks_resumed").inc()
            else:
                live.append((plan, lo, hi, chunk_id))

    # -- analytic shard plan: budget minus the modeled resident program
    # -- footprint (chunk operands + accumulators + finalized models),
    # -- all priced before the first upload
    row_bytes = 0
    for v in data.values():
        v = np.asarray(v)
        row_bytes += v.dtype.itemsize * int(
            np.prod(v.shape[1:], dtype=np.int64))
    n_mask_ops = 2 + (1 if return_train else 0)   # fit + test (+ train)
    row_bytes += n_mask_ops * n_folds * fit_masks.dtype.itemsize

    def _struct_rows(rows):
        d_s = {k: jax.ShapeDtypeStruct((rows,) + np.asarray(v).shape[1:],
                                       np.asarray(v).dtype)
               for k, v in data.items()}
        w_s = jax.ShapeDtypeStruct((n_folds, rows), fit_masks.dtype)
        return d_s, w_s

    def make_partial(static):
        def partial(data_s, fw_s):
            return family.stream_fit_partial(static, data_s, fw_s, meta)
        return partial

    reserved = 0
    for plan in plans:
        fp = _memledger.model_group_footprint(
            plan["group"].dynamic_params, plan["width"], n_folds,
            task_batched=False, n_samples=0,
            mask_itemsize=int(fit_masks.dtype.itemsize),
            n_scorers=len(scorer_names), return_train=return_train,
            dtype_itemsize=int(np.dtype(dtype).itemsize))
        plan["partial"] = make_partial(plan["static"])
        d1, w1 = _struct_rows(1)
        acc_shapes = jax.eval_shape(plan["partial"], d1, w1)
        plan["acc_shapes"] = acc_shapes
        acc_bytes = sum(
            int(np.prod(s.shape, dtype=np.int64))
            * np.dtype(s.dtype).itemsize
            for s in jax.tree_util.tree_leaves(acc_shapes))
        # a chunk's finalized models stay resident for the score pass:
        # price one fold's model pytree x (width x n_folds) tasks
        one_stats = jax.tree_util.tree_map(
            lambda s: jax.ShapeDtypeStruct(s.shape[1:], s.dtype),
            acc_shapes)
        dyn1 = {k: jax.ShapeDtypeStruct((), np.asarray(v).dtype)
                for k, v in plan["group"].dynamic_params.items()}
        try:
            model_shapes = jax.eval_shape(
                lambda dn, st: family.stream_fit_finalize(
                    dn, plan["static"], st, meta), dyn1, one_stats)
            model_bytes = sum(
                int(np.prod(s.shape, dtype=np.int64))
                * np.dtype(s.dtype).itemsize
                for s in jax.tree_util.tree_leaves(model_shapes))
        except Exception as exc:
            # pricing only — the real finalize traces (and raises)
            # below; an unpriceable model just doesn't shrink the shard
            logger.debug(
                "stream plan: model footprint eval_shape failed (%r); "
                "pricing finalized models at 0 bytes", exc)
            model_bytes = 0
        n_chunks = len(plan["chunks"])
        reserved += int(fp["chunk_bytes"]) + acc_bytes \
            + model_bytes * plan["width"] * n_folds * n_chunks

    budget = 0
    mem_ctx = getattr(search, "_memory_ctx", None)
    if ledger is not None and mem_ctx is not None:
        budget = int(mem_ctx.get("budget_bytes", 0))
    else:
        budget = int(_obs_memory.resolve_hbm_budget(config, None))

    t_plan0 = time.perf_counter()
    plan_sh = plan_stream_shards(
        n_samples, row_bytes, resolve_shard_bytes(config),
        budget_bytes=budget, reserved_bytes=reserved)
    tracer.record_span(
        "stream.plan", t_plan0, time.perf_counter(),
        n_shards=plan_sh.n_shards, shard_rows=plan_sh.shard_rows,
        row_bytes=plan_sh.row_bytes, capped=plan_sh.capped)
    if ckpt is not None:
        journalled = ckpt.get_meta("stream_plan")
        if journalled is not None:
            from spark_sklearn_tpu.parallel.taskgrid import StreamPlan
            jplan = StreamPlan.from_dict(journalled)
            if jplan.signature() != plan_sh.signature():
                raise GeometryMismatchError(
                    "checkpoint was written under a different stream-"
                    "shard geometry (journalled (n_samples, shard_rows, "
                    f"n_shards) = {jplan.signature()}, current = "
                    f"{plan_sh.signature()}); per-shard journal entries "
                    "are only addressable under the geometry that wrote "
                    f"them.  Delete {ckpt.path!r} or restore the "
                    "original stream_shard_bytes / HBM budget.")
            plan_sh = jplan
        else:
            ckpt.put_meta("stream_plan", plan_sh.to_dict())

    rows = int(plan_sh.shard_rows)
    n_shards = int(plan_sh.n_shards)
    if ledger is not None and mem_ctx is not None:
        rec = {"group": "stream", "width": int(rows),
               "capped": bool(plan_sh.capped),
               "resident_bytes": int(reserved),
               "chunk_bytes": int(2 * rows * row_bytes),
               "dyn_bytes": 0, "mask_bytes": 0, "out_bytes": 0,
               "per_candidate_bytes": 0}
        ledger.note_group(rec)
        mem_ctx["groups"].append(rec)

    stream_block = _streaming_counters(plan_sh, len(live))

    if not live:
        metrics.put("streaming", stream_block)
        return

    live_plans = [p for p in plans
                  if any(pl is p for pl, *_ in live)]

    # -- per-group device programs -------------------------------------
    def _tree_add(a, b):
        return jax.tree_util.tree_map(jnp.add, a, b)

    add_jit = jax.jit(_tree_add)

    for plan in live_plans:
        static = plan["static"]
        plan["partial_jit"] = jax.jit(plan["partial"])
        plan["acc"] = _zeros_like_shapes(plan["acc_shapes"])

        def make_fin(static=static, width=plan["width"]):
            def fin(dyn, stats):
                def one_cand(dyn_c):
                    def one_fold(stats_f):
                        return family.stream_fit_finalize(
                            dyn_c, static, stats_f, meta)
                    return jax.vmap(one_fold)(stats)
                models = jax.vmap(one_cand)(dyn)
                bad = None
                for leaf in jax.tree_util.tree_leaves(models):
                    if not jnp.issubdtype(leaf.dtype, jnp.inexact):
                        continue
                    b = jnp.isnan(leaf).any(
                        axis=tuple(range(2, leaf.ndim)))
                    bad = b if bad is None else (bad | b)
                if bad is None:
                    bad = jnp.zeros((width, n_folds), bool)
                return models, bad
            return fin

        plan["fin_jit"] = jax.jit(make_fin())

        def make_score(static=static):
            def score_shard(models, Xs, ys, te_m, tr_m):
                def one_cand(model_c):
                    def one_fold(model_f, te_w, tr_w):
                        pred = family.predict(model_f, static, Xs, meta)
                        out = {}
                        if is_cls:
                            ok = (pred == ys).astype(te_w.dtype)
                            out["num_te"] = jnp.sum(te_w * ok)
                            out["den_te"] = jnp.sum(te_w)
                            if return_train:
                                out["num_tr"] = jnp.sum(tr_w * ok)
                                out["den_tr"] = jnp.sum(tr_w)
                        else:
                            err = ys - pred
                            out["ssr_te"] = jnp.sum(te_w * err * err)
                            out["s0_te"] = jnp.sum(te_w)
                            out["s1_te"] = jnp.sum(te_w * ys)
                            out["s2_te"] = jnp.sum(te_w * ys * ys)
                            if return_train:
                                out["ssr_tr"] = jnp.sum(tr_w * err * err)
                                out["s0_tr"] = jnp.sum(tr_w)
                                out["s1_tr"] = jnp.sum(tr_w * ys)
                                out["s2_tr"] = jnp.sum(tr_w * ys * ys)
                        return out
                    return jax.vmap(one_fold)(model_c, te_m, tr_m)
                return jax.vmap(one_cand)(models)
            return score_shard

        plan["score_jit"] = jax.jit(make_score())

    # -- pipeline ------------------------------------------------------
    depth = config.pipeline_depth if jax.process_count() == 1 else 0
    pipe = ChunkPipeline(depth, verbose=search.verbose)
    walls = {"fit": 0.0, "score": 0.0}

    def shard_bounds(j):
        lo = j * rows
        return lo, min(lo + rows, n_samples)

    # -- FIT pass ------------------------------------------------------
    # resume: the highest contiguous journaled shard's accumulators
    start_shard = 0
    if ckpt is not None:
        j = 0
        rec = None
        while j < n_shards:
            r = ckpt.get(f"st:fit:{j}")
            if r is None:
                break
            rec = r
            j += 1
        if rec is not None and j > 0:
            restored = {}
            ok = True
            for plan in live_plans:
                acc = _unpack_tree(
                    rec.get("accs", {}).get(str(plan["gi"])),
                    plan["acc"])
                if acc is None:
                    ok = False
                    break
                restored[plan["gi"]] = acc
            if ok:
                start_shard = j
                for plan in live_plans:
                    plan["acc"] = jax.tree_util.tree_map(
                        jnp.asarray, restored[plan["gi"]])
                stream_block["fit_shards_resumed"] = int(j)
            else:
                logger.warning(
                    "streamed fit journal is structurally stale; "
                    "refolding from shard 0", chunk="st:fit")

    def fit_items():
        for j in range(start_shard, n_shards):
            lo, hi = shard_bounds(j)

            def stage(j=j, lo=lo, hi=hi):
                payload = {
                    k: _put(_pad_rows(np.asarray(v), lo, hi, rows),
                            f"stream.data.{k}.s{j}")
                    for k, v in data.items()}
                payload["__fw__"] = _put(
                    _pad_mask(fit_masks, lo, hi, rows),
                    f"stream.mask.fit.s{j}")
                return payload

            def launch(payload):
                fw = payload.pop("__fw__")
                outs = []
                for plan in live_plans:
                    part = plan["partial_jit"](payload, fw)
                    plan["acc"] = add_jit(plan["acc"], part)
                    outs.append(plan["acc"])
                return outs

            def gather(out):
                if ckpt is None:
                    return None
                return {str(plan["gi"]): _pack_tree(acc)
                        for plan, acc in zip(live_plans, out)}

            def finalize(host, tm, j=j):
                walls["fit"] += tm.dispatch_s + tm.compute_s \
                    + tm.gather_s
                metrics.counter("n_launches").inc()
                stream_block["fit_shards_streamed"] += 1
                if ckpt is not None and host is not None:
                    ckpt.put(f"st:fit:{j}", {"accs": host})

            yield LaunchItem(
                key=f"st:fit:{j}", kind="stream_fit", group=0,
                n_tasks=len(live_plans), stage=stage, launch=launch,
                gather=gather, finalize=finalize)

    t0 = time.perf_counter()
    pipe.run(fit_items())
    tracer.record_span("stream.fit_pass", t0, time.perf_counter(),
                       n_shards=n_shards - start_shard, shard_rows=rows)

    # -- finalize: one cheap launch per live chunk ---------------------
    t0 = time.perf_counter()
    models = {}
    for plan, lo, hi, chunk_id in live:
        group = plan["group"]
        width = plan["width"]
        dyn = {k: _dataplane.upload(
                   pad_chunk(np.asarray(arr), lo, hi, width, 1),
                   repl, label="stream.dyn")
               for k, arr in group.dynamic_params.items()}
        if not dyn:
            dyn["_pad"] = _dataplane.upload(
                np.zeros(width, dtype=dtype), repl, label="stream.dyn")
        mdl, bad = plan["fin_jit"](dyn, plan["acc"])
        idx = group.candidate_indices[lo:hi]
        fit_failed[idx, :] |= np.asarray(bad)[: hi - lo]
        models[chunk_id] = mdl
        metrics.counter("n_launches").inc()
    walls["fit"] += time.perf_counter() - t0
    tracer.record_span("stream.finalize", t0, time.perf_counter(),
                       n_chunks=len(live))

    # -- SCORE pass ----------------------------------------------------
    saccs = {}
    for plan, lo, hi, chunk_id in live:
        te_like = jnp.zeros((plan["width"], n_folds), fit_masks.dtype)
        if is_cls:
            keys = ["num_te", "den_te"] + (
                ["num_tr", "den_tr"] if return_train else [])
        else:
            keys = ["ssr_te", "s0_te", "s1_te", "s2_te"] + (
                ["ssr_tr", "s0_tr", "s1_tr", "s2_tr"]
                if return_train else [])
        saccs[chunk_id] = {k: te_like for k in keys}

    score_start = 0
    if ckpt is not None:
        j = 0
        rec = None
        while j < n_shards:
            r = ckpt.get(f"st:score:{j}")
            if r is None:
                break
            rec = r
            j += 1
        if rec is not None and j > 0:
            restored = {}
            ok = True
            for plan, lo, hi, chunk_id in live:
                acc = _unpack_tree(
                    rec.get("accs", {}).get(chunk_id), saccs[chunk_id])
                if acc is None:
                    ok = False
                    break
                restored[chunk_id] = acc
            if ok:
                score_start = j
                for cid, acc in restored.items():
                    saccs[cid] = jax.tree_util.tree_map(
                        jnp.asarray, acc)
                stream_block["score_shards_resumed"] = int(j)
            else:
                logger.warning(
                    "streamed score journal is structurally stale; "
                    "rescoring from shard 0", chunk="st:score")

    def score_items():
        for j in range(score_start, n_shards):
            lo, hi = shard_bounds(j)

            def stage(j=j, lo=lo, hi=hi):
                payload = {
                    "X": _put(_pad_rows(np.asarray(data["X"]),
                                        lo, hi, rows),
                              f"stream.data.X.s{j}"),
                    "y": _put(_pad_rows(np.asarray(data["y"]),
                                        lo, hi, rows),
                              f"stream.data.y.s{j}"),
                    "te": _put(_pad_mask(test_sc_masks, lo, hi, rows),
                               f"stream.mask.test.s{j}"),
                    "tr": _put(_pad_mask(train_sc_masks, lo, hi, rows),
                               f"stream.mask.train.s{j}")
                    if return_train else None,
                }
                return payload

            def launch(payload):
                te_m = payload["te"]
                tr_m = payload["tr"] if return_train else te_m
                outs = []
                for plan, lo_, hi_, chunk_id in live:
                    part = plan["score_jit"](
                        models[chunk_id], payload["X"], payload["y"],
                        te_m, tr_m)
                    saccs[chunk_id] = add_jit(saccs[chunk_id], part)
                    outs.append(saccs[chunk_id])
                return outs

            def gather(out):
                if ckpt is None:
                    return None
                return {chunk_id: _pack_tree(acc)
                        for (plan, lo_, hi_, chunk_id), acc
                        in zip(live, out)}

            def finalize(host, tm, j=j):
                walls["score"] += tm.dispatch_s + tm.compute_s \
                    + tm.gather_s
                metrics.counter("n_launches").inc()
                stream_block["score_shards_streamed"] += 1
                if ckpt is not None and host is not None:
                    ckpt.put(f"st:score:{j}", {"accs": host})

            yield LaunchItem(
                key=f"st:score:{j}", kind="stream_score", group=0,
                n_tasks=len(live), stage=stage, launch=launch,
                gather=gather, finalize=finalize)

    t0 = time.perf_counter()
    pipe.run(score_items())
    pipe.close()
    tracer.record_span("stream.score_pass", t0, time.perf_counter(),
                       n_shards=n_shards - score_start, shard_rows=rows)

    # -- reduce sufficient statistics to cv_results_ cells -------------
    sname = scorer_names[0]
    eps = np.asarray(EPS, fit_masks.dtype)
    total_real = sum((hi - lo) * n_folds for _, lo, hi, _ in live)
    fit_t = walls["fit"] / max(1, total_real)
    score_t = walls["score"] / max(1, total_real)
    metrics.gauge("fit_wall_s").add(walls["fit"])
    metrics.gauge("score_wall_s").add(walls["score"])

    def _reduce(acc, side):
        if is_cls:
            num = np.asarray(acc[f"num_{side}"])
            den = np.asarray(acc[f"den_{side}"])
            return num / (den + eps)
        ssr = np.asarray(acc[f"ssr_{side}"])
        s0 = np.asarray(acc[f"s0_{side}"])
        s1 = np.asarray(acc[f"s1_{side}"])
        s2 = np.asarray(acc[f"s2_{side}"])
        ybar = s1 / (s0 + eps)
        sstot = s2 - 2.0 * ybar * s1 + ybar * ybar * s0
        return 1.0 - ssr / np.maximum(sstot, eps)

    for plan, lo, hi, chunk_id in live:
        idx = plan["group"].candidate_indices[lo:hi]
        acc = {k: np.asarray(v) for k, v in saccs[chunk_id].items()}
        te = _reduce(acc, "te")[: hi - lo]
        test_scores[sname][idx, :] = te
        if return_train:
            tr = _reduce(acc, "tr")[: hi - lo]
            train_scores[sname][idx, :] = tr
        fit_times[idx, :] = fit_t
        score_times[idx, :] = score_t
        if ckpt is not None:
            ckpt.put(chunk_id, {
                "test": {sname: test_scores[sname][idx, :].tolist()},
                "train": ({sname: train_scores[sname][idx, :].tolist()}
                          if return_train else None),
                "fit_t": fit_t, "score_t": score_t,
                "failed": fit_failed[idx, :].tolist()})

    dp_after = _dataplane.snapshot_counters(plane)
    stream_block["h2d_bytes"] = int(
        dp_after.get("total_bytes", 0) - dp_before.get("total_bytes", 0))
    metrics.put("streaming", stream_block)
