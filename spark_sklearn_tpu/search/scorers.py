"""JAX scorer registry for the compiled (Tier A) search path.

The reference passes sklearn scorer objects into `_fit_and_score` on CPU
executors (reference: grid_search.py -> sklearn scorers).  Inside a jitted
program a scorer must be a pure function over fixed-shape arrays, with the
test fold expressed as a weight mask.  Every scorer here matches the sklearn
metric of the same name on dense inputs (oracle-tested in
tests/test_scorers.py).

Weighted-mask convention: `w` is 1.0 on the fold's samples, 0.0 elsewhere;
all means are weighted means over `w`.

Every metric is split into a **view requirement** and a **metric core**:
views are the model's outputs on the dataset ("pred", "decision",
"proba") and cores are pure reductions `core(views, y, w, meta)`.  The
split is what lets the search engine compute each view ONCE per launch
for ALL (candidate x fold) tasks — for linear families a single wide
matmul (`views_task_batched`) instead of one matvec per task per scorer —
and share it across every scorer in a multimetric search.  The public
callables keep the legacy per-task signature
`(family, model, static, data, meta, w)` for direct use and tests.
"""

from __future__ import annotations

from typing import Callable, Dict

import jax
import jax.numpy as jnp
import numpy as np

EPS = 1e-12

#: view name -> per-task builder (the generic path; families may batch
#: these over the task axis themselves via `views_task_batched`)
VIEW_BUILDERS: Dict[str, Callable] = {}


def _wsum(w):
    return jnp.sum(w) + EPS


def _feats(data):
    """Families that pre-transform the dataset (binned trees) carry their
    own representation; predict implementations know which they expect."""
    return data["X"] if "X" in data else data["codes"]


def build_view(name, family, model, static, data, meta):
    return VIEW_BUILDERS[name](family, model, static, data, meta)


VIEW_BUILDERS["pred"] = lambda family, model, static, data, meta: \
    family.predict(model, static, _feats(data), meta)
VIEW_BUILDERS["decision"] = lambda family, model, static, data, meta: \
    family.decision(model, static, _feats(data), meta)
VIEW_BUILDERS["proba"] = lambda family, model, static, data, meta: \
    family.predict_proba(model, static, _feats(data), meta)


def _scorer(*views):
    """Wrap a metric core into the legacy per-task scorer callable while
    exposing `.views` / `.core` for the engine's task-batched path."""
    def deco(core):
        def fn(family, model, static, data, meta, w):
            v = {name: build_view(name, family, model, static, data, meta)
                 for name in views}
            return core(v, data["y"], w, meta)
        fn.views = views
        fn.core = core
        fn.__name__ = core.__name__
        fn.__doc__ = core.__doc__
        return fn
    return deco


@_scorer("pred")
def _accuracy(v, y, w, meta):
    return jnp.sum(w * (v["pred"] == y)) / _wsum(w)


@_scorer("proba")
def _neg_log_loss(v, y, w, meta):
    proba = v["proba"]
    # sklearn's log_loss clips to [eps, 1-eps] at the PROBA DTYPE's
    # machine eps (_classification.py _log_loss) — and the dtype that
    # matters is the ORACLE's (libsvm/forest/KNN probas are always f64;
    # LogReg/MLP/NB preserve the user's X dtype), which the engine
    # resolves per family into meta["logloss_clip_eps"].  An f32-proba
    # oracle charges a confidently-wrong sample -log(1.19e-7) ~ 15.9
    # where an f64 one charges ~36; with saturating families (NB) that
    # difference dominated the whole score.
    # fallback for direct/legacy callers whose meta came straight from
    # prepare_data: f64 eps, the pre-round-5 behavior (the engine path
    # always sets the per-family key)
    eps = meta.get("logloss_clip_eps") or float(np.finfo(np.float64).eps)
    p = jnp.clip(proba[jnp.arange(proba.shape[0]), y], eps, 1.0 - eps)
    return -(jnp.sum(w * -jnp.log(p)) / _wsum(w))


def _binary_counts(pred, y, w, positive=1):
    tp = jnp.sum(w * ((pred == positive) & (y == positive)))
    fp = jnp.sum(w * ((pred == positive) & (y != positive)))
    fn = jnp.sum(w * ((pred != positive) & (y == positive)))
    return tp, fp, fn


@_scorer("pred")
def _f1(v, y, w, meta):
    tp, fp, fn = _binary_counts(v["pred"], y, w)
    return 2 * tp / jnp.maximum(2 * tp + fp + fn, EPS)


@_scorer("pred")
def _precision(v, y, w, meta):
    tp, fp, fn = _binary_counts(v["pred"], y, w)
    return tp / jnp.maximum(tp + fp, EPS)


@_scorer("pred")
def _recall(v, y, w, meta):
    tp, fp, fn = _binary_counts(v["pred"], y, w)
    return tp / jnp.maximum(tp + fn, EPS)


@_scorer("pred")
def _f1_macro(v, y, w, meta):
    pred = v["pred"]
    k = meta["n_classes"]

    def per_class(c):
        tp = jnp.sum(w * ((pred == c) & (y == c)))
        fp = jnp.sum(w * ((pred == c) & (y != c)))
        fn = jnp.sum(w * ((pred != c) & (y == c)))
        return 2 * tp / jnp.maximum(2 * tp + fp + fn, EPS)

    return jnp.mean(jax.vmap(per_class)(jnp.arange(k)))


@_scorer("pred")
def _balanced_accuracy(v, y, w, meta):
    """Macro-average recall over classes present in the fold (sklearn
    semantics: classes absent from y_true drop out of the mean)."""
    pred = v["pred"]
    k = meta["n_classes"]

    def per_class(c):
        support = jnp.sum(w * (y == c))
        tp = jnp.sum(w * ((pred == c) & (y == c)))
        rec = tp / jnp.maximum(support, EPS)
        return rec, (support > 0).astype(rec.dtype)

    recalls, present = jax.vmap(per_class)(jnp.arange(k))
    return jnp.sum(recalls * present) / jnp.maximum(jnp.sum(present), 1.0)


@_scorer("pred")
def _explained_variance(v, y, w, meta):
    err = y - v["pred"]
    ebar = jnp.sum(w * err) / _wsum(w)
    var_err = jnp.sum(w * (err - ebar) ** 2) / _wsum(w)
    ybar = jnp.sum(w * y) / _wsum(w)
    var_y = jnp.sum(w * (y - ybar) ** 2) / _wsum(w)
    return 1.0 - var_err / jnp.maximum(var_y, EPS)


@_scorer("pred")
def _neg_msle(v, y, w, meta):
    # sklearn RAISES on negative targets/predictions; inside a compiled
    # program we return NaN instead, which surfaces through the
    # non-finite-score warning rather than silently scoring a clamp
    pred = v["pred"]
    invalid = jnp.sum(w * ((y < 0) | (pred < 0)).astype(w.dtype)) > 0
    ly = jnp.log1p(jnp.maximum(y, 0.0))
    lp = jnp.log1p(jnp.maximum(pred, 0.0))
    val = -(jnp.sum(w * (ly - lp) ** 2) / _wsum(w))
    return jnp.where(invalid, jnp.nan, val)


@_scorer("decision")
def _roc_auc(v, y, w, meta):
    """Weighted binary AUC via the rank/Mann-Whitney statistic."""
    s = v["decision"]
    y = y.astype(s.dtype)
    order = jnp.argsort(s)
    s_s, y_s, w_s = s[order], y[order], w[order]
    # weighted rank = cumulative weight; ties handled approximately (exact
    # tie-averaging needs segment means — acceptable for continuous margins)
    cw = jnp.cumsum(w_s) - 0.5 * w_s
    pos = jnp.sum(w_s * y_s)
    neg = jnp.sum(w_s * (1.0 - y_s))
    rank_pos = jnp.sum(w_s * y_s * cw)
    return (rank_pos - 0.5 * pos * pos) / jnp.maximum(pos * neg, EPS)


@_scorer("pred")
def _r2(v, y, w, meta):
    pred = v["pred"]
    ybar = jnp.sum(w * y) / _wsum(w)
    ss_res = jnp.sum(w * (y - pred) ** 2)
    ss_tot = jnp.sum(w * (y - ybar) ** 2)
    return 1.0 - ss_res / jnp.maximum(ss_tot, EPS)


def _neg_mse_core(v, y, w, meta):
    return -(jnp.sum(w * (y - v["pred"]) ** 2) / _wsum(w))


_neg_mse = _scorer("pred")(_neg_mse_core)


@_scorer("pred")
def _neg_rmse(v, y, w, meta):
    return -jnp.sqrt(-_neg_mse_core(v, y, w, meta))


@_scorer("pred")
def _neg_mae(v, y, w, meta):
    return -(jnp.sum(w * jnp.abs(y - v["pred"])) / _wsum(w))


@_scorer("pred")
def _neg_median_ae(v, y, w, meta):
    # weighted median via sorting on |err| with mask-weights; when the
    # cumulative weight hits exactly half (even-sized unweighted folds),
    # average the two middle errors the way np.median does
    err = jnp.abs(y - v["pred"])
    order = jnp.argsort(err)
    e_s, w_s = err[order], w[order]
    cw = jnp.cumsum(w_s)
    half = 0.5 * jnp.sum(w_s)
    n = err.shape[0]
    idx_lo = jnp.clip(jnp.searchsorted(cw, half), 0, n - 1)
    idx_hi = jnp.clip(jnp.searchsorted(cw, half, side="right"), 0, n - 1)
    lo, hi = e_s[idx_lo], e_s[idx_hi]
    return -jnp.where(cw[idx_lo] == half, 0.5 * (lo + hi), lo)


@_scorer("pred")
def _max_error(v, y, w, meta):
    return -jnp.max(w * jnp.abs(y - v["pred"]))


SCORERS: Dict[str, Callable] = {
    "accuracy": _accuracy,
    "balanced_accuracy": _balanced_accuracy,
    "explained_variance": _explained_variance,
    "neg_mean_squared_log_error": _neg_msle,
    "neg_log_loss": _neg_log_loss,
    "f1": _f1,
    "f1_macro": _f1_macro,
    "precision": _precision,
    "recall": _recall,
    "roc_auc": _roc_auc,
    "r2": _r2,
    "neg_mean_squared_error": _neg_mse,
    "neg_root_mean_squared_error": _neg_rmse,
    "neg_mean_absolute_error": _neg_mae,
    "neg_median_absolute_error": _neg_median_ae,
    "max_error": _max_error,        # legacy sklearn name
    "neg_max_error": _max_error,    # sklearn >= 1.6 name
}


#: scorers that need label/class structure (meta["n_classes"]) — consulted
#: by the engine's pre-sweep validation so mismatches fail clearly
CLASSIFICATION_SCORERS = {
    "accuracy", "balanced_accuracy", "neg_log_loss", "f1", "f1_macro",
    "precision", "recall", "roc_auc",
}
#: binary-only compiled implementations (multiclass variants live on the
#: host path with sklearn's averaging semantics)
BINARY_ONLY_SCORERS = {"f1", "precision", "recall", "roc_auc"}

#: compiled impls whose sklearn twin does NOT accept sample_weight; the
#: engine scores these with unweighted masks even in a weighted search,
#: mirroring _MultimetricScorer's per-scorer forwarding
SAMPLE_WEIGHT_BLIND_FNS = frozenset({_max_error})


#: make_scorer(_score_func, sign) -> compiled scorer name; consulted so
#: user-built `make_scorer(accuracy_score)`-style objects (with default
#: kwargs) stay on the compiled path instead of de-optimizing to host
_SCORE_FUNC_TABLE = {
    ("accuracy_score", 1): "accuracy",
    ("balanced_accuracy_score", 1): "balanced_accuracy",
    ("recall_score", 1): "recall",
    ("precision_score", 1): "precision",
    ("f1_score", 1): "f1",
    ("roc_auc_score", 1): "roc_auc",
    ("log_loss", -1): "neg_log_loss",
    ("r2_score", 1): "r2",
    ("explained_variance_score", 1): "explained_variance",
    ("mean_squared_error", -1): "neg_mean_squared_error",
    ("root_mean_squared_error", -1): "neg_root_mean_squared_error",
    ("mean_absolute_error", -1): "neg_mean_absolute_error",
    ("median_absolute_error", -1): "neg_median_absolute_error",
    ("mean_squared_log_error", -1): "neg_mean_squared_log_error",
    ("max_error", -1): "max_error",
}


def compiled_name_for_scorer(obj):
    """Map a sklearn make_scorer object with default kwargs to the
    equivalent compiled scorer name, or None when it has no compiled
    twin (custom kwargs, custom callables, pos_label overrides...)."""
    try:
        from sklearn.metrics._scorer import _Scorer
    except ImportError:                                # pragma: no cover
        return None
    if not isinstance(obj, _Scorer):
        return None
    if getattr(obj, "_kwargs", None):
        return None
    fn_name = getattr(getattr(obj, "_score_func", None), "__name__", None)
    sign = getattr(obj, "_sign", 1)
    name = _SCORE_FUNC_TABLE.get((fn_name, sign))
    return name if name in SCORERS else None


def resolve_scoring(scoring, family):
    """scoring arg -> ordered {name: jax scorer}.  None uses the estimator
    default (accuracy / r2) like sklearn's check_scoring."""
    if scoring is None:
        default = getattr(family, "default_scorer", None)
        if default is not None:   # e.g. KMeans: -inertia
            return {"score": default}, "score"
        name = "accuracy" if family.is_classifier else "r2"
        return {"score": SCORERS[name]}, "score"
    if isinstance(scoring, str):
        if scoring not in SCORERS:
            raise KeyError(
                f"scoring={scoring!r} has no compiled implementation; "
                f"available: {sorted(SCORERS)} (or use backend='host')")
        return {"score": SCORERS[scoring]}, "score"
    obj_name = compiled_name_for_scorer(scoring)
    if obj_name is not None:
        return {"score": SCORERS[obj_name]}, "score"
    if isinstance(scoring, (list, tuple, set)):
        # sklearn's contract: list/tuple scoring must be unique metric-name
        # STRINGS (_check_multimetric_scoring rejects objects in lists) —
        # keep that behavior rather than canonicalizing objects here
        out = {}
        for s in scoring:
            if not isinstance(s, str) or s not in SCORERS:
                raise KeyError(
                    f"scoring entry {s!r} not compiled (list scoring takes "
                    "unique metric-name strings); use backend='host'")
            out[s] = SCORERS[s]
        return out, None
    if isinstance(scoring, dict):
        out = {}
        for name, s in scoring.items():
            if not isinstance(s, str):
                s = compiled_name_for_scorer(s)
            if s is None or s not in SCORERS:
                raise KeyError(
                    f"multimetric entry {name}={scoring[name]!r} not "
                    "compiled; use backend='host'")
            out[name] = SCORERS[s]
        return out, None
    raise TypeError(f"Unsupported scoring spec for the compiled path: "
                    f"{scoring!r}; use backend='host'")
